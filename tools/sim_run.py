"""simnet CLI — run deterministic multi-node simulations from seeds.

Usage:
  python tools/sim_run.py --seed 42 --scenario partition-heal
      One run. stdout is EXACTLY the event log plus one deterministic
      summary line — run it twice, diff nothing (the acceptance check
      pipes both runs to files and compares bytes). Wall-clock notes go
      to stderr so they can't perturb the log.

  python tools/sim_run.py --seeds 0..24 [--scenario all]
      Seed sweep. With --scenario all (default) the bundled scenarios
      are assigned round-robin by seed, so a range covers the whole
      catalog; every line names its (scenario, seed) for replay.

  python tools/sim_run.py --selftest
      Fast determinism + recovery proof (wired into tools/run_suite.sh):
      same seed => identical log digest, different seed => divergent,
      crash-restart => WAL replay converges. Exit 0 on success.

  python tools/sim_run.py --list
      Print the scenario catalog.

On any invariant violation the tool prints a REPLAYABLE failure line:
  SIMNET-FAIL scenario=<s> seed=<n> ... reproduce: python tools/sim_run.py ...
and exits 1.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cometbft_tpu.simnet.scenarios import (SCENARIOS, run_scenario,  # noqa: E402
                                           sweep)


def _summary(r) -> str:
    """Deterministic one-liner (no wall time — byte-stable across runs)."""
    return (f"SUMMARY scenario={r.scenario} seed={r.seed} "
            f"max_height={r.max_height} commits_per_sim_s="
            f"{r.commits_per_sim_s:.3f} virtual_s={r.virtual_s:.3f} "
            f"delivered={r.stats['delivered']} dropped={r.stats['dropped']} "
            f"blocked={r.stats['blocked']} crashes={r.crashes} "
            f"restarts={r.restarts} evidence={r.evidence_seen} "
            f"violations={len(r.violations)} log={r.digest}")


def _run_single(args) -> int:
    if args.out:
        os.makedirs(args.out, exist_ok=True)
    r = run_scenario(args.scenario, args.seed, quick=args.quick,
                     workdir=args.out)
    for line in r.log_lines:
        print(line)
    print(_summary(r))
    print(f"# wall {r.wall_s:.2f}s, {r.stats['events']} events",
          file=sys.stderr)
    for err in r.errors:
        print(f"# node error: {err}", file=sys.stderr)
    if not r.ok:
        for v in r.violations:
            print(f"VIOLATION {v}", file=sys.stderr)
        print(r.failure_line())
        return 1
    return 0


def _run_sweep(args) -> int:
    a, _, b = args.seeds.partition("..")
    seeds = range(int(a), int(b) + 1) if b else [int(a)]
    t0 = time.monotonic()
    failed = 0
    for r in sweep(seeds, scenario=args.scenario, quick=args.quick):
        status = "OK" if r.ok else "FAIL"
        print(f"{status} scenario={r.scenario} seed={r.seed} "
              f"h={r.max_height} commits_per_sim_s="
              f"{r.commits_per_sim_s:.2f} wall={r.wall_s:.2f}s "
              f"log={r.digest[:16]}")
        if not r.ok:
            failed += 1
            print(r.failure_line())
    n = len(list(seeds))
    print(f"sweep: {n - failed}/{n} clean in "
          f"{time.monotonic() - t0:.1f}s wall")
    return 1 if failed else 0


def _selftest() -> int:
    t0 = time.monotonic()
    a = run_scenario("baseline", 7, quick=True)
    b = run_scenario("baseline", 7, quick=True)
    if a.digest != b.digest:
        print("SELFTEST FAIL: same seed produced different event logs")
        print(f"  {a.digest} vs {b.digest}")
        return 1
    c = run_scenario("baseline", 8, quick=True)
    if c.digest == a.digest:
        print("SELFTEST FAIL: different seeds produced identical logs")
        return 1
    d = run_scenario("crash-restart", 3, quick=True)
    if not d.ok or d.restarts < 1:
        print("SELFTEST FAIL: crash-restart did not recover "
              f"(violations={d.violations}, restarts={d.restarts})")
        print(d.failure_line())
        return 1
    e = run_scenario("device-flap", 1, quick=True)
    flap = [ln for ln in e.log_lines if "blocksync_device" in ln]
    if not e.ok or not flap or "state=healthy" not in flap[0] \
            or "probes=0" in flap[0]:
        print("SELFTEST FAIL: device-flap did not probe back to "
              f"HEALTHY ({flap or 'no device line'})")
        print(e.failure_line())
        return 1
    f = run_scenario("device-corrupt", 1, quick=True)
    corr = [ln for ln in f.log_lines if "blocksync_device" in ln]
    if not f.ok or not corr or "state=quarantined" not in corr[0] \
            or "quarantines=1" not in corr[0]:
        print("SELFTEST FAIL: device-corrupt did not quarantine "
              f"({corr or 'no device line'})")
        print(f.failure_line())
        return 1
    for r in (a, c, d, e, f):
        if not r.ok:
            print(r.failure_line())
            return 1
    print(f"SELFTEST OK: determinism + crash recovery + device "
          f"flap/corrupt ({time.monotonic() - t0:.1f}s wall, "
          f"h={a.max_height}/{c.max_height}/{d.max_height}/"
          f"{e.max_height}/{f.max_height})")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--seeds", help="A..B inclusive sweep")
    ap.add_argument("--scenario", default=None,
                    help="scenario name, or 'all' (sweep round-robin)")
    ap.add_argument("--quick", action="store_true",
                    help="reduced target heights (CI smoke)")
    ap.add_argument("--out", default=None,
                    help="workdir for run artifacts (single-run mode): "
                         "node dirs, and for traced scenarios the "
                         "trace_seed<N>.jsonl flight-recorder stream")
    ap.add_argument("--selftest", action="store_true")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)

    if args.list:
        for name in sorted(SCENARIOS):
            s = SCENARIOS[name]
            print(f"{name:20} target_h={s.target_height:2} "
                  f"deadline={s.deadline_ms}ms  {s.description}")
        return 0
    if args.selftest:
        return _selftest()
    if args.seeds:
        args.scenario = args.scenario or "all"
        return _run_sweep(args)
    args.scenario = args.scenario or "baseline"
    return _run_single(args)


if __name__ == "__main__":
    sys.exit(main())
