"""WAL debug tools: dump a consensus WAL to JSON lines and rebuild a
WAL from them (reference scripts/wal2json, scripts/json2wal — the
operator tooling for inspecting and hand-repairing a node's
write-ahead log).

Usage:
    python tools/wal.py wal2json <wal-file> [> out.jsonl]
    python tools/wal.py json2wal <out.jsonl> <new-wal-file>

Round-trip is byte-exact at the message level: json2wal(wal2json(w))
replays identically.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.consensus.wal import (  # noqa: E402
    EndHeightMessage, WAL, WALBlockPart, WALProposal, WALTimeout,
    WALVote, _decode_proposal, _encode_proposal)
from cometbft_tpu.types.vote import Vote  # noqa: E402


def msg_to_json(m) -> dict:
    if isinstance(m, EndHeightMessage):
        return {"type": "end_height", "height": m.height}
    if isinstance(m, WALVote):
        return {"type": "vote", "vote": m.vote.encode().hex(),
                "peer_id": m.peer_id,
                "summary": {"h": m.vote.height, "r": m.vote.round,
                            "t": m.vote.type_,
                            "val": m.vote.validator_index,
                            "nil": m.vote.is_nil()}}
    if isinstance(m, WALProposal):
        return {"type": "proposal",
                "proposal": _encode_proposal(m.proposal).hex(),
                "peer_id": m.peer_id,
                "summary": {"h": m.proposal.height,
                            "r": m.proposal.round}}
    if isinstance(m, WALBlockPart):
        return {"type": "block_part", "height": m.height,
                "round": m.round, "index": m.index,
                "part": m.part.hex(), "peer_id": m.peer_id}
    if isinstance(m, WALTimeout):
        return {"type": "timeout", "height": m.height, "round": m.round,
                "step": m.step, "duration_ms": m.duration_ms}
    raise TypeError(f"unknown WAL message {type(m)}")


def msg_from_json(d: dict):
    t = d["type"]
    if t == "end_height":
        return EndHeightMessage(d["height"])
    if t == "vote":
        return WALVote(Vote.decode(bytes.fromhex(d["vote"])),
                       d.get("peer_id", ""))
    if t == "proposal":
        return WALProposal(
            _decode_proposal(bytes.fromhex(d["proposal"])),
            d.get("peer_id", ""))
    if t == "block_part":
        return WALBlockPart(d["height"], d["round"], d["index"],
                            bytes.fromhex(d["part"]),
                            d.get("peer_id", ""))
    if t == "timeout":
        return WALTimeout(d["height"], d["round"], d["step"],
                          d["duration_ms"])
    raise ValueError(f"unknown WAL json type {t!r}")


def wal2json(path: str, out=sys.stdout) -> int:
    wal = WAL(path)
    n = 0
    try:
        for m in wal.iter_messages():
            out.write(json.dumps(msg_to_json(m)) + "\n")
            n += 1
    finally:
        wal.close()
    return n


def json2wal(json_path: str, wal_path: str) -> int:
    if os.path.exists(wal_path) and os.path.getsize(wal_path):
        # WAL opens append-mode: writing into an existing log would
        # KEEP the records being repaired and replay them first
        raise SystemExit(
            f"refusing to append to existing non-empty WAL {wal_path}; "
            f"write to a fresh path and move it into place")
    wal = WAL(wal_path)
    n = 0
    try:
        with open(json_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                m = msg_from_json(json.loads(line))
                if isinstance(m, EndHeightMessage):
                    wal.write_sync(m)
                else:
                    wal.write(m)
                n += 1
    finally:
        wal.close()
    return n


def main() -> int:
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    w2j = sub.add_parser("wal2json")
    w2j.add_argument("wal")
    j2w = sub.add_parser("json2wal")
    j2w.add_argument("json")
    j2w.add_argument("wal")
    args = ap.parse_args()
    if args.cmd == "wal2json":
        wal2json(args.wal)
    else:
        n = json2wal(args.json, args.wal)
        print(f"wrote {n} messages to {args.wal}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
