"""Light-client verification benchmark (the BASELINE.json "light
client: sequential verify of SignedHeaders, 150 validators" config;
reference light/client_benchmark_test.go:24-75 — harness-only there
too, sequential vs bisection over a mock chain).

Generates an N-block chain with a V-validator set, then times a light
client catching up to the tip BOTH ways:
  sequential — verify every header 2..N (adjacent rule each step);
  bisection  — skipping verification with the 1/3-trust rule (static
               valset: one jump).
Reports headers/s for the sequential pass and total wall for each.

Usage:
    JAX_PLATFORMS=cpu python tools/bench_light.py [--blocks 64]
        [--validators 150] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=64)
    ap.add_argument("--validators", type=int, default=150)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    # device-vs-cpu by PROBING (the shared bench-tool discipline —
    # the ambient config pins the TPU platform even under
    # JAX_PLATFORMS=cpu, and any verify_batch jit then blocks forever
    # on a wedged tunnel)
    from bench import resolve_backend_or_pin_cpu
    from cometbft_tpu.libs.jax_cache import enable_compile_cache
    enable_compile_cache()
    backend = resolve_backend_or_pin_cpu()

    from cometbft_tpu.db.kv import MemDB
    from cometbft_tpu.engine.chain_gen import (ChainLightProvider,
                                               generate_chain)
    from cometbft_tpu.light.client import LightClient, TrustOptions
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.types.proto import Timestamp

    t0 = time.monotonic()
    print(f"[bench_light] generating {args.blocks} blocks x "
          f"{args.validators} validators...", file=sys.stderr, flush=True)
    chain = generate_chain(n_blocks=args.blocks,
                           n_validators=args.validators)
    print(f"[bench_light] chain in {time.monotonic() - t0:.1f}s",
          file=sys.stderr, flush=True)

    now = Timestamp(1_700_000_000 + chain.max_height() + 5, 0)
    opts = TrustOptions(period_seconds=30 * 24 * 3600, height=1,
                        hash=chain.blocks[0].hash())

    def catchup(sequential: bool) -> float:
        client = LightClient(chain.chain_id, opts,
                             ChainLightProvider(chain), [],
                             LightStore(MemDB()), sequential=sequential,
                             now_fn=lambda: now)
        t = time.monotonic()
        lb = client.verify_light_block_at_height(chain.max_height())
        dt = time.monotonic() - t
        assert lb.height == chain.max_height()
        return dt

    seq_s = catchup(sequential=True)
    # first bisection may pay a one-time jit of the 64-lane RLC bucket
    # (minutes on XLA:CPU, docs/PERF.md); the steady-state number is
    # the warm second pass
    cold_bis_s = catchup(sequential=False)
    bis_s = catchup(sequential=False)
    headers = args.blocks - 1  # sequential verifies 2..N

    rec = {
        "metric": "light_client_verify",
        "sequential_headers_per_sec": round(headers / seq_s, 1),
        "sequential_seconds": round(seq_s, 3),
        "bisection_seconds": round(bis_s, 3),
        "bisection_cold_seconds": round(cold_bis_s, 3),
        "unit": "headers/s",
        "blocks": args.blocks,
        "validators": args.validators,
        "sigs_per_commit": args.validators,
        "backend": backend,
    }
    if args.json:
        print(json.dumps(rec))
    else:
        print(f"light client: sequential {rec['sequential_headers_per_sec']}"
              f" headers/s ({seq_s:.2f}s for {headers} headers x "
              f"{args.validators} sigs), bisection to tip {bis_s:.3f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
