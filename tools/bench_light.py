"""Light-client verification benchmark (the BASELINE.json "light
client: sequential verify of SignedHeaders, 150 validators" config;
reference light/client_benchmark_test.go:24-75 — harness-only there
too, sequential vs bisection over a mock chain).

Generates an N-block chain with a V-validator set, then times a light
client catching up to the tip BOTH ways:
  sequential — verify every header 2..N (adjacent rule each step);
  bisection  — skipping verification with the 1/3-trust rule (static
               valset: one jump).
Reports headers/s for the sequential pass and total wall for each.

--farm A/B (docs/FARM.md): N already-subscribed clients at staggered
trusted heights all verify the tip —
  sequential — N independent LightClients, one after another, the
               shared SigCache RESET between them (each models its own
               process, paying its full bisection);
  farm       — one VerificationFarm, the N requests planned host-side
               and their signature lanes coalesced/deduped into shared
               batches.
Session setup is untimed on both sides: the A/B measures the
steady-state verify workload. In --farm mode --validators defaults to
60 (below types/validation.BATCH_VERIFY_THRESHOLD) so BOTH sides run
the native per-signature CPU path — larger sets would jit the XLA:CPU
RLC bucket mid-measurement (docs/PERF.md "known compile hazard").

Usage:
    JAX_PLATFORMS=cpu python tools/bench_light.py [--blocks 64]
        [--validators 150] [--json]
    JAX_PLATFORMS=cpu python tools/bench_light.py --farm
        [--clients 32] [--blocks 64] [--validators 60] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_farm(args, chain, now, backend):
    """The --farm A/B: N coalesced sessions vs N sequential clients."""
    from cometbft_tpu.db.kv import MemDB
    from cometbft_tpu.engine.chain_gen import ChainLightProvider
    from cometbft_tpu.farm import VerificationFarm
    from cometbft_tpu.farm.batcher import FarmBatcher
    from cometbft_tpu.light.client import LightClient, TrustOptions
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.pipeline.cache import SigCache, reset_shared_cache

    tip = chain.max_height()
    n = args.clients
    # staggered trusted heights across the lower half of the chain
    roots = [1 + (i * 3) % max(1, tip // 2) for i in range(n)]

    # --- sequential: N independent clients, each its own "process" ---
    clients = []
    for h0 in roots:
        opts = TrustOptions(period_seconds=30 * 24 * 3600, height=h0,
                            hash=chain.blocks[h0 - 1].hash())
        reset_shared_cache()  # init must not warm the next client
        clients.append(LightClient(
            chain.chain_id, opts, ChainLightProvider(chain), [],
            LightStore(MemDB()), now_fn=lambda: now))
    t = time.monotonic()
    for client in clients:
        reset_shared_cache()  # each client pays its own verification
        lb = client.verify_light_block_at_height(tip)
        assert lb.height == tip
    seq_s = time.monotonic() - t
    reset_shared_cache()

    # --- farm: the same N requests, coalesced ------------------------
    cache = SigCache(1 << 20)
    farm = VerificationFarm(
        chain.chain_id, ChainLightProvider(chain), cache=cache,
        batcher=FarmBatcher(cache=cache, coalesce_window_s=0.0),
        now_fn=lambda: now)
    sessions = [farm.subscribe(h0, chain.blocks[h0 - 1].hash(),
                               30 * 24 * 3600) for h0 in roots]
    farm.batcher.flush()
    t = time.monotonic()
    pendings = [farm.begin_verify(s.session_id, tip) for s in sessions]
    farm.batcher.flush()
    for p in pendings:
        out = farm.finish_verify(p)
        assert out["height"] == tip
    farm_s = time.monotonic() - t

    st = farm.status()
    rec = {
        "metric": "light_farm_ab",
        "clients": n,
        "blocks": args.blocks,
        "validators": args.validators,
        "sequential_seconds": round(seq_s, 4),
        "farm_seconds": round(farm_s, 4),
        "speedup": round(seq_s / farm_s, 2) if farm_s else 0.0,
        "sequential_clients_per_sec": round(n / seq_s, 1) if seq_s
        else 0.0,
        "farm_clients_per_sec": round(n / farm_s, 1) if farm_s else 0.0,
        "farm_batches": st["batches"],
        "farm_max_batch_width": st["max_batch_width"],
        "farm_dedup_batch_hits": st["dedup_batch_hits"],
        "farm_cache_hit_rate": st["cache_hit_rate"],
        "lanes_by_backend": st["lanes_by_backend"],
        "backend": backend,
    }
    if args.json:
        print(json.dumps(rec))
    else:
        print(f"light farm A/B: {n} clients to tip {args.blocks} — "
              f"sequential {seq_s:.3f}s, farm {farm_s:.3f}s "
              f"({rec['speedup']}x; widest batch "
              f"{st['max_batch_width']} lanes, cache hit rate "
              f"{st['cache_hit_rate']})")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=64)
    ap.add_argument("--validators", type=int, default=None)
    ap.add_argument("--farm", action="store_true",
                    help="A/B: N coalesced farm clients vs N "
                         "sequential independent clients")
    ap.add_argument("--clients", type=int, default=32)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.validators is None:
        # --farm keeps BOTH sides on the native per-sig path (module
        # docstring); the classic bench keeps its BASELINE config
        args.validators = 60 if args.farm else 150

    # device-vs-cpu by PROBING (the shared bench-tool discipline —
    # the ambient config pins the TPU platform even under
    # JAX_PLATFORMS=cpu, and any verify_batch jit then blocks forever
    # on a wedged tunnel)
    from bench import resolve_backend_or_pin_cpu
    from cometbft_tpu.libs.jax_cache import enable_compile_cache
    enable_compile_cache()
    backend = resolve_backend_or_pin_cpu()

    from cometbft_tpu.db.kv import MemDB
    from cometbft_tpu.engine.chain_gen import (ChainLightProvider,
                                               generate_chain)
    from cometbft_tpu.light.client import LightClient, TrustOptions
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.types.proto import Timestamp

    t0 = time.monotonic()
    print(f"[bench_light] generating {args.blocks} blocks x "
          f"{args.validators} validators...", file=sys.stderr, flush=True)
    chain = generate_chain(n_blocks=args.blocks,
                           n_validators=args.validators)
    print(f"[bench_light] chain in {time.monotonic() - t0:.1f}s",
          file=sys.stderr, flush=True)

    now = Timestamp(1_700_000_000 + chain.max_height() + 5, 0)
    if args.farm:
        return bench_farm(args, chain, now, backend)
    opts = TrustOptions(period_seconds=30 * 24 * 3600, height=1,
                        hash=chain.blocks[0].hash())

    def catchup(sequential: bool) -> float:
        client = LightClient(chain.chain_id, opts,
                             ChainLightProvider(chain), [],
                             LightStore(MemDB()), sequential=sequential,
                             now_fn=lambda: now)
        t = time.monotonic()
        lb = client.verify_light_block_at_height(chain.max_height())
        dt = time.monotonic() - t
        assert lb.height == chain.max_height()
        return dt

    seq_s = catchup(sequential=True)
    # first bisection may pay a one-time jit of the 64-lane RLC bucket
    # (minutes on XLA:CPU, docs/PERF.md); the steady-state number is
    # the warm second pass
    cold_bis_s = catchup(sequential=False)
    bis_s = catchup(sequential=False)
    headers = args.blocks - 1  # sequential verifies 2..N

    rec = {
        "metric": "light_client_verify",
        "sequential_headers_per_sec": round(headers / seq_s, 1),
        "sequential_seconds": round(seq_s, 3),
        "bisection_seconds": round(bis_s, 3),
        "bisection_cold_seconds": round(cold_bis_s, 3),
        "unit": "headers/s",
        "blocks": args.blocks,
        "validators": args.validators,
        "sigs_per_commit": args.validators,
        "backend": backend,
    }
    if args.json:
        print(json.dumps(rec))
    else:
        print(f"light client: sequential {rec['sequential_headers_per_sec']}"
              f" headers/s ({seq_s:.2f}s for {headers} headers x "
              f"{args.validators} sigs), bisection to tip {bis_s:.3f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
