"""Microbenchmark the ed25519 kernel stages on the current default device.

Chains K repetitions of each op inside one jit (scan with carry) so
per-dispatch overhead and fusion behave as in the real kernel, then
reports per-call time. Run on TPU: `python tools/profile_ops.py`.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax

from cometbft_tpu.libs.jax_cache import enable_compile_cache

enable_compile_cache()
import jax.numpy as jnp
from jax import lax

from cometbft_tpu.ops import edwards as ed
from cometbft_tpu.ops import field as fe
from cometbft_tpu.ops.scalar import sc_nibbles, sc_mul
from cometbft_tpu.ops.sha512 import sha512_blocks

N = int(os.environ.get("PROF_N", "4096"))
K = int(os.environ.get("PROF_K", "32"))


def timeit(name, fn, *args, reps=3):
    out = fn(*args)
    jax.block_until_ready(out)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{name:28s} {best*1e3:9.2f} ms total  {best*1e6/K:9.1f} us/call")
    return out


def chain(opfn):
    """jit a scan of K sequential applications of opfn on a Point carry."""
    @jax.jit
    def run(p):
        def step(c, _):
            return opfn(c), None
        c, _ = lax.scan(step, p, None, length=K)
        return c
    return run


def main():
    rng = np.random.default_rng(0)
    # limb axis LEADING (16, *batch) — the field.py layout
    limbs = lambda *s: jnp.asarray(
        rng.integers(0, 1 << 16, size=(16, *s), dtype=np.int32))
    print(f"device={jax.devices()[0].platform} N={N} K={K}")

    pt = (limbs(N), limbs(N), limbs(N), limbs(N))

    # fe_mul chained
    @jax.jit
    def mulchain(a, b):
        def step(c, _):
            return fe.fe_mul(c, b), None
        c, _ = lax.scan(step, a, None, length=K)
        return c
    timeit("fe_mul (N)", mulchain, limbs(N), limbs(N))

    # fe_carry chained
    @jax.jit
    def carrychain(a):
        def step(c, _):
            return fe.fe_carry(c + 7), None
        c, _ = lax.scan(step, a, None, length=K)
        return c
    timeit("fe_carry (N)", carrychain, limbs(N))

    timeit("pt_add (N)", chain(lambda p: ed.pt_add(p, pt)), pt)
    timeit("pt_double (N)", chain(ed.pt_double), pt)

    # decompress x10
    enc = jnp.asarray(rng.integers(0, 256, size=(32, N), dtype=np.uint8))
    @jax.jit
    def dec(e):
        def step(c, _):
            p, ok = ed.pt_decompress(e)
            return c + p[0][0] * ok, None
        c, _ = lax.scan(step, jnp.zeros((N,), jnp.int32), None, length=4)
        return c
    K_save = K
    globals()["K"] = 4
    timeit("pt_decompress (N)", dec, enc)
    globals()["K"] = 1

    # window table build (1 call)
    wt = jax.jit(lambda p: ed.window_table(p))
    timeit("window_table (N)", wt, pt)

    # straus (1 call)
    s = limbs(N) & 0x0FFF
    k = limbs(N) & 0x0FFF
    @jax.jit
    def straus(s, k, p):
        tab = ed.window_table(p)
        return ed.straus_double_mul(s, k, tab)
    timeit("straus_full (N)", straus, s, k, pt)

    # tree path: lookup + tree sum over N for 64 windows (1 call)
    @jax.jit
    def treepath(t_scalar, p):
        tab = ed.window_table(p)
        sel = ed.lookup_windows(tab, sc_nibbles(t_scalar))
        return ed.pt_tree_sum(sel)
    timeit("tab+lookup+tree64 (N)", treepath, k, pt)

    # horner (1 call)
    w64 = tuple(limbs(64) for _ in range(4))
    timeit("horner64 (1)", jax.jit(ed.horner_windows), w64)

    # sha512, 2 blocks (1 call)
    hb = jnp.asarray(rng.integers(0, 256, size=(N, 2, 128), dtype=np.uint8))
    hn = jnp.full((N,), 2, dtype=np.int32)
    timeit("sha512 2blk (N)", jax.jit(sha512_blocks), hb, hn)

    # sc_mul (1 call)
    timeit("sc_mul (N)", jax.jit(sc_mul), s, k)
    globals()["K"] = K_save

    # pallas kernels (mosaic-compiled — device platforms only; the
    # XLA-vs-pallas A/B that motivates ops/pallas_verify.py)
    if jax.devices()[0].platform != "cpu" and \
            os.environ.get("PROF_PALLAS", "1") == "1":
        from cometbft_tpu.ops import pallas_verify as pv
        g = N // pv.TILE
        if N % pv.TILE != 0 or (g * pv.TAIL) & (g * pv.TAIL - 1):
            print(f"pallas section skipped: N={N} needs N % TILE"
                  f"({pv.TILE}) == 0 and a power-of-two tile count")
        else:
            packed = jnp.stack(pt)
            globals()["K"] = 1
            timeit("PALLAS pt_add tiled (N)",
                   lambda p: pv.pt_add_tiled(p, p), packed)
            enc = jnp.asarray(
                rng.integers(0, 256, size=(32, N), dtype=np.uint8))
            timeit("PALLAS decompress (N)", pv.pt_decompress_tiled, enc)
            td = jnp.asarray(rng.integers(0, 16, (64, N), np.int32))
            zd = jnp.asarray(rng.integers(0, 16, (32, N), np.int32))
            timeit("PALLAS window_sums (N)",
                   lambda a, t_, z_: pv.rlc_window_sums(a, a, t_, z_),
                   packed, td, zd)
            m = (N // pv.TILE) * pv.TAIL
            folded = jnp.asarray(rng.integers(
                0, 1 << 16, size=(4, 16, 96, m), dtype=np.int32))
            from cometbft_tpu.ops.edwards import small_base_table
            timeit("PALLAS epilogue (1)",
                   lambda f: pv.rlc_epilogue(
                       f, jnp.asarray(small_base_table()),
                       jnp.zeros((64,), jnp.int32)), folded)
            globals()["K"] = K_save


if __name__ == "__main__":
    main()
