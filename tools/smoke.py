"""Fast pre-commit smoke check (VERDICT r2 #9: a snapshot must never land
with bench.py or dryrun broken again).

Runs on a small virtual CPU mesh in one process, in under ~2 minutes warm:
  1. compile+run the single-chip verify kernel on a 16-sig batch
     (the `entry()` path),
  2. one RLC tile through `verify_rlc_kernel` incl. a corrupted lane
     falling back to attribution,
  3. one sharded `TiledCommitVerifier`-style multi-device step
     (the `dryrun_multichip` path) on a 4-device mesh.

Usage: python tools/smoke.py   (exit 0 = safe to commit)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# jax is pre-imported by the environment: config must go through
# jax.config (env vars are already latched)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

from cometbft_tpu.libs.jax_cache import enable_compile_cache  # noqa: E402

enable_compile_cache()

import numpy as np


def _batch(n, msg_len=40, seed=123):
    import random
    from cometbft_tpu.crypto import ref_ed25519 as ref
    rng = random.Random(seed)
    pubs, msgs, sigs = [], [], []
    for _ in range(n):
        sd = bytes([rng.randrange(256) for _ in range(32)])
        m = bytes([rng.randrange(256) for _ in range(msg_len)])
        pubs.append(ref.pubkey_from_seed(sd))
        msgs.append(m)
        sigs.append(ref.sign(sd, m))
    return pubs, msgs, sigs


def main():
    from cometbft_tpu.ops.ed25519 import (
        make_rlc_coefficients, prepare_batch, verify_batch,
        verify_rlc_kernel)

    # 1. per-lane kernel via the host API (entry() path)
    pubs, msgs, sigs = _batch(16)
    ok = verify_batch(pubs, msgs, sigs, batch_size=16, rlc=False)
    assert ok.all(), f"per-lane kernel rejected valid sigs: {ok}"

    # 2. RLC tile: clean pass, then corrupted lane -> attribution fallback
    pub, sig, hb, hn, mask = prepare_batch(pubs, msgs, sigs, 16, 64)
    assert mask.all()
    z = make_rlc_coefficients(16)
    bok, sok = verify_rlc_kernel(pub, sig, hb, hn, z)
    assert bool(bok) and np.asarray(sok).all(), "RLC clean tile failed"
    bad_sigs = list(sigs)
    bad_sigs[5] = bytes(64)
    ok = verify_batch(pubs, msgs, bad_sigs, batch_size=16)
    want = [True] * 16
    want[5] = False
    assert list(ok) == want, f"attribution failed: {list(ok)}"

    # 3. sharded multi-device tile (dryrun path)
    from cometbft_tpu.parallel.mesh import make_mesh
    from cometbft_tpu.parallel.verify import make_sharded_verifier
    mesh = make_mesh(4)
    C, V = mesh.shape["commit"], 2 * mesh.shape["sig"]
    pubs, msgs, sigs = _batch(C * V)
    pub, sig, hb, hn, mask = prepare_batch(pubs, msgs, sigs, C * V, 64)
    assert mask.all()
    grid = lambda x: x.reshape(C, V, *x.shape[1:])
    power = np.full((C, V), 3.0, dtype=np.float32)
    ok, tally = make_sharded_verifier(mesh)(
        grid(pub), grid(sig), grid(hb), grid(hn), power)
    assert np.asarray(ok).all() and (np.asarray(tally) == 3.0 * V).all()

    print("smoke: ok")


if __name__ == "__main__":
    main()
