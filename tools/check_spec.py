"""Explicit-state model checker for spec/Consensus.tla.

The environment has no Java/TLC, so this is the machine-checking half
of the spec: a breadth-first enumeration of the EXACT transition system
Consensus.tla describes (same actions, same guards — the docstrings
below quote the TLA+ action names), with the two model strengthenings
round 4's review demanded (VERDICT weak #7):

  * REAL round-robin proposer rotation — Proposer(r) = r mod n, the
    reduction of types/validator.py proposer-priority under equal
    powers — instead of the old `CHOOSE v : TRUE` fixed proposer, so
    rotation-dependent interleavings are explored;
  * a STRONGER Byzantine model: faulty validators are "wildcards" that
    count toward EVERY quorum for EVERY value simultaneously (the
    standard over-approximation of equivocation — strictly more
    adversarial than the old one-vote-per-round Byzantine actions, and
    it shrinks the state space because faulty votes carry no state).

Checked invariants (the spec's properties):
  Agreement     — no two correct validators decide differently.
  ValidityLock  — every correct ≠nil precommit in round r is backed by
                  a polka for that value in r.
  DecisionPower — every decision is backed by a 2/3 precommit quorum.

Usage:
  python tools/check_spec.py [--n 4] [--f 1] [--values 2] \
      [--max-round 1] [--self-test]

--self-test weakens the quorum size by one and asserts the checker
DOES find an Agreement violation — evidence the search can detect
bugs, not just terminate.

Exhaustiveness note: the full asynchronous interleaving space grows
hyper-exponentially in MaxRound; n=4/f=1/|V|=2/MaxRound=1 closes in
minutes in pure Python (hundreds of thousands of canonical states,
value-symmetry reduced). Higher MaxRound needs --state-cap, which turns
the run into a bounded (still useful, no-longer-exhaustive) search —
the same tradeoff TLC users make with depth bounds.
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time
from collections import deque

NIL = 0          # the spec's Nil
NONE = -1        # "no vote cast yet"

# step encoding (consensus/state.py STEP_* constants; PrevoteWait /
# PrecommitWait collapse into their base steps exactly as in the spec,
# where the Wait states gate nothing)
NEW_HEIGHT, PROPOSE, PREVOTE, PRECOMMIT, COMMIT = range(5)


class Model:
    def __init__(self, n=4, f=1, n_values=2, max_round=1,
                 quorum_delta=0):
        assert 3 * f < n, "need n > 3f"
        self.n = n
        self.f = f
        self.correct = n - f          # validators 0..correct-1 are correct
        self.values = tuple(range(1, n_values + 1))
        self.rounds = tuple(range(max_round + 1))
        self.max_round = max_round
        # QuorumSize == (2n) \div 3 + 1  (+delta only for --self-test)
        self.quorum = (2 * n) // 3 + 1 + quorum_delta

    # state = (steps, rounds, locked_v, locked_r, valid_v, valid_r,
    #          decisions, proposals, prevotes, precommits)
    # all tuples over CORRECT validators only; prevotes/precommits are
    # (round, validator)-indexed; Byzantine validators are wildcards.

    def initial(self):
        c, R = self.correct, len(self.rounds)
        return ((NEW_HEIGHT,) * c, (0,) * c, (NIL,) * c, (-1,) * c,
                (NIL,) * c, (-1,) * c, (NIL,) * c, (NIL,) * R,
                ((NONE,) * c,) * R, ((NONE,) * c,) * R)

    def proposer(self, r):
        """Round-robin rotation: types/validator.py proposer-priority
        under equal powers (the spec's Proposer(r))."""
        return r % self.n

    # --- quorum accounting (wildcard Byzantine) ---------------------------

    def has_polka(self, st, r, x):
        """HasPolka(r, x): correct prevotes for x plus all f wildcards."""
        prevotes = st[8]
        return (sum(1 for v in prevotes[r] if v == x) + self.f
                >= self.quorum)

    def any_polka(self, st, r):
        """AnyPolka(r): 2/3 of some mix of prevotes arrived."""
        prevotes = st[8]
        return (sum(1 for v in prevotes[r] if v != NONE) + self.f
                >= self.quorum)

    def has_commit(self, st, r, x):
        precommits = st[9]
        return (sum(1 for v in precommits[r] if v == x) + self.f
                >= self.quorum)

    # --- successor generation (the spec's Next) ---------------------------

    def successors(self, st):
        (steps, rounds, lv, lr, vv, vr, dec, props, prevotes,
         precommits) = st
        out = []

        def emit(**kw):
            out.append((
                kw.get("steps", steps), kw.get("rounds", rounds),
                kw.get("lv", lv), kw.get("lr", lr),
                kw.get("vv", vv), kw.get("vr", vr),
                kw.get("dec", dec), kw.get("props", props),
                kw.get("prevotes", prevotes),
                kw.get("precommits", precommits)))

        def rep(t, i, x):
            return t[:i] + (x,) + t[i + 1:]

        # ByzantinePropose(r, x): a Byzantine proposer may broadcast any
        # value for its round at any time (round 3 under the default
        # n=4/f=1 rotation). Without this action, props[r] stays Nil in
        # Byzantine-proposer rounds and correct validators can only
        # prevote nil/locked — a strictly smaller transition system than
        # spec/Consensus.tla (ADVICE round 5 medium).
        for r in self.rounds:
            if self.proposer(r) >= self.correct and props[r] == NIL:
                for x in self.values:
                    emit(props=rep(props, r, x))

        for v in range(self.correct):
            r = rounds[v]

            # StartRound(v, r): enter Propose; the proposer broadcasts
            # validValue (re-proposal with POL) or a fresh value
            if steps[v] == NEW_HEIGHT:
                if self.proposer(r) == v and props[r] == NIL:
                    cands = ([vv[v]] if vv[v] != NIL else self.values)
                    for x in cands:
                        emit(steps=rep(steps, v, PROPOSE),
                             props=rep(props, r, x))
                else:
                    emit(steps=rep(steps, v, PROPOSE))

            # DoPrevote(v, r, x)
            if steps[v] == PROPOSE and prevotes[r][v] == NONE:
                opts = set()
                if lv[v] != NIL:
                    opts.add(lv[v])         # locked: vote the lock
                else:
                    if props[r] != NIL:
                        opts.add(props[r])  # acceptable proposal
                    opts.add(NIL)           # invalid/missing/untimely
                for x in opts:
                    emit(steps=rep(steps, v, PREVOTE),
                         prevotes=rep(prevotes, r,
                                      rep(prevotes[r], v, x)))

            # PrecommitValue(v, r, x): polka incl. own prevote -> lock
            if steps[v] == PREVOTE and precommits[r][v] == NONE:
                x = prevotes[r][v]
                if x != NIL and x != NONE and self.has_polka(st, r, x):
                    emit(steps=rep(steps, v, PRECOMMIT),
                         lv=rep(lv, v, x), lr=rep(lr, v, r),
                         vv=rep(vv, v, x), vr=rep(vr, v, r),
                         precommits=rep(precommits, r,
                                        rep(precommits[r], v, x)))

            # PrecommitNil(v, r): nil-polka unlocks; mixed 2/3 without
            # a value polka precommits nil keeping the lock
            if steps[v] == PREVOTE and precommits[r][v] == NONE:
                nil_polka = self.has_polka(st, r, NIL)
                mixed = (self.any_polka(st, r)
                         and not any(self.has_polka(st, r, x)
                                     for x in self.values))
                if nil_polka:
                    emit(steps=rep(steps, v, PRECOMMIT),
                         lv=rep(lv, v, NIL), lr=rep(lr, v, -1),
                         precommits=rep(precommits, r,
                                        rep(precommits[r], v, NIL)))
                elif mixed:
                    emit(steps=rep(steps, v, PRECOMMIT),
                         precommits=rep(precommits, r,
                                        rep(precommits[r], v, NIL)))

            # Decide(v, r', x): any visible commit quorum decides
            if dec[v] == NIL:
                for rr in self.rounds:
                    for x in self.values:
                        if self.has_commit(st, rr, x):
                            emit(steps=rep(steps, v, COMMIT),
                                 dec=rep(dec, v, x))

            # NextRound(v, r)
            if steps[v] == PRECOMMIT and r < self.max_round \
                    and dec[v] == NIL:
                emit(steps=rep(steps, v, NEW_HEIGHT),
                     rounds=rep(rounds, v, r + 1))

        return out

    # --- invariants -------------------------------------------------------

    def check(self, st):
        (steps, rounds, lv, lr, vv, vr, dec, props, prevotes,
         precommits) = st
        # Agreement
        decided = [d for d in dec if d != NIL]
        if len(set(decided)) > 1:
            return f"Agreement violated: decisions {dec}"
        # ValidityLock: every correct non-nil precommit has its polka
        for r in self.rounds:
            for v in range(self.correct):
                x = precommits[r][v]
                if x != NIL and x != NONE and not self.has_polka(st, r, x):
                    return (f"ValidityLock violated: precommit {x} in "
                            f"round {r} by {v} without polka")
        # DecisionPower: every decision has a commit quorum somewhere
        for v in range(self.correct):
            if dec[v] != NIL and not any(
                    self.has_commit(st, r, dec[v]) for r in self.rounds):
                return f"DecisionPower violated: {v} decided {dec[v]}"
        return None

    # --- value-symmetry reduction ----------------------------------------

    def canon(self, st):
        """Smallest state under permutations of Values (the spec's
        values are interchangeable — TLC's SYMMETRY set)."""
        if len(self.values) < 2:
            return st
        best = None
        for perm in itertools.permutations(self.values):
            m = {NIL: NIL, NONE: NONE}
            m.update({old: new for old, new
                      in zip(self.values, perm)})
            (steps, rounds, lv, lr, vv, vr, dec, props, pv, pc) = st
            cand = (steps, rounds,
                    tuple(m[x] for x in lv), lr,
                    tuple(m[x] for x in vv), vr,
                    tuple(m[x] for x in dec),
                    tuple(m[x] for x in props),
                    tuple(tuple(m[x] for x in row) for row in pv),
                    tuple(tuple(m[x] for x in row) for row in pc))
            if best is None or cand < best:
                best = cand
        return best


def run(model: Model, state_cap=0, progress=True):
    """BFS over the reachable canonical states; returns (n_states,
    violation-or-None, exhaustive: bool)."""
    init = model.canon(model.initial())
    seen = {init}
    q = deque([init])
    t0 = time.monotonic()
    while q:
        st = q.popleft()
        err = model.check(st)
        if err:
            return len(seen), err, True
        for nxt in model.successors(st):
            c = model.canon(nxt)
            if c not in seen:
                seen.add(c)
                q.append(c)
        if state_cap and len(seen) >= state_cap:
            return len(seen), None, False
        if progress and len(seen) % 200_000 < 10 and len(seen) > 10:
            print(f"  ... {len(seen):,} states, queue {len(q):,}, "
                  f"{time.monotonic() - t0:.0f}s", file=sys.stderr)
    return len(seen), None, True


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--f", type=int, default=1)
    ap.add_argument("--values", type=int, default=2)
    ap.add_argument("--max-round", type=int, default=1)
    ap.add_argument("--state-cap", type=int, default=0,
                    help="stop after N states (bounded, non-exhaustive)")
    ap.add_argument("--self-test", action="store_true",
                    help="weaken the quorum by 1; a violation MUST be "
                         "found or the checker itself is broken")
    args = ap.parse_args(argv)

    delta = -1 if args.self_test else 0
    model = Model(args.n, args.f, args.values, args.max_round,
                  quorum_delta=delta)
    t0 = time.monotonic()
    n_states, err, exhaustive = run(model, args.state_cap)
    dt = time.monotonic() - t0
    scope = (f"n={args.n} f={args.f} |V|={args.values} "
             f"MaxRound={args.max_round} quorum={model.quorum}")

    if args.self_test:
        if err and "Agreement" in err:
            print(f"SELF-TEST OK: weakened quorum finds: {err} "
                  f"({n_states:,} states, {dt:.1f}s)")
            return 0
        print(f"SELF-TEST FAILED: no Agreement violation found with a "
              f"weakened quorum ({scope}) — checker is not detecting "
              f"violations")
        return 1

    if err:
        print(f"VIOLATION ({scope}): {err}  [{n_states:,} states]")
        return 1
    kind = "exhaustive" if exhaustive else f"bounded at {n_states:,}"
    print(f"OK ({scope}): Agreement + ValidityLock + DecisionPower hold "
          f"over {n_states:,} states ({kind}, {dt:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
