"""Differential fuzzer for the kernel-interval no-overflow proof.

The staticcheck `kernel-interval` rule (tools/staticcheck/
interval_rules.py) proves, by interval abstract interpretation, that no
int32 value inside an ops/ kernel ever leaves [-2**31, 2**31).  This
harness attacks that proof from the concrete side: it executes the SAME
kernel source under a shim `jax` whose arrays hold exact Python ints
(numpy object arrays), samples every input uniformly inside the
interval its `# staticcheck: assume(...)` pragma claims (with a bias
toward the lo/hi endpoints, where overflows live), and asserts the
int32 contract on EVERY intermediate operation:

- int32 results must lie in [-2**31, 2**31) — an escape is a concrete
  counterexample that disproves the analyzer's verdict and fails the
  suite (exit 1, with the kernel, seed, and op location to replay);
- uint32/uint8 results wrap (hardware semantics — sha512's carry
  detection deliberately overflows uint32, that is not a finding);
- `.astype(int32)` asserts the value already fits (the analyzer models
  the conversion as exact, so a wrapping conversion would silently
  invalidate every downstream bound).

Because every element of every input is an independent draw from its
claimed interval, one batched execution yields thousands of samples;
the per-kernel sample counts reported (and enforced: >= --samples,
default 1000) count those sampled scalars.

Scope notes (kept honest in the report):
- Mid-function assume() obligations are subsumed: the shadow checks
  every op, not just the annotated sites.
- The two bls12 chain entries close over fixed ~quadruple-length
  static bit strings (HARD_BITS, the Fermat exponent); the shadow runs
  the identical loop bodies over truncated static chains — the per-op
  interval claims are chain-length-invariant (the analyzer itself
  proves them as a loop fixpoint), but the full-length chains are only
  executed on real jax (tests/test_aggsig).

Usage:
    python -m tools.interval_fuzz              # full: 3 seeds/kernel
    python -m tools.interval_fuzz --quick      # 1 seed/kernel (CI)
    python -m tools.interval_fuzz --kernel rlc_epilogue --seed 7
    python -m tools.interval_fuzz --list
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
import time
import traceback
import types
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

I32_MIN, I32_MAX = -(1 << 31), (1 << 31) - 1
_WRAP = {"uint32": (1 << 32) - 1, "uint8": 0xFF}


class Counterexample(Exception):
    """A concrete int32 escape — disproves the interval proof."""

    def __init__(self, msg: str, where: str):
        super().__init__(msg)
        self.where = where


def _blame() -> str:
    """Innermost cometbft_tpu/ops frame of the current stack — the
    kernel source line the overflowing op lives on."""
    for fr in reversed(traceback.extract_stack()):
        if f"cometbft_tpu{os.sep}ops" in fr.filename:
            return f"{os.path.relpath(fr.filename, ROOT)}:{fr.lineno} " \
                   f"({fr.name}) {fr.line}"
    return "<outside ops/>"


# --- shadow arrays ----------------------------------------------------------
#
# SA wraps a numpy object array of exact Python ints plus a dtype tag.
# Arithmetic is exact; the tag decides what happens to the exact result:
# int32 escapes raise, unsigned dtypes wrap, bool stays 0/1.

def _rank(dt: str) -> int:
    return {"bool": 0, "uint8": 1, "int32": 2, "uint32": 3}[dt]


def _promote(a: str, b: str) -> str:
    return a if _rank(a) >= _rank(b) else b


class SA:
    __slots__ = ("a", "dtype")

    def __init__(self, a: np.ndarray, dtype: str):
        self.a = a
        self.dtype = dtype

    # -- construction with the contract check --------------------------
    @staticmethod
    def make(a, dtype: str) -> "SA":
        if not isinstance(a, np.ndarray):
            # 0-d object arrays decay to python scalars under numpy ops
            a = np.array(a, dtype=object)
        if dtype == "int32" and a.size:
            mn, mx = a.min(), a.max()
            if mn < I32_MIN or mx > I32_MAX:
                bad = mx if mx > I32_MAX else mn
                raise Counterexample(
                    f"int32 escape: value {bad} outside "
                    f"[-2**31, 2**31) at {_blame()}", _blame())
        elif dtype in _WRAP and a.size:
            m = _WRAP[dtype]
            if a.min() < 0 or a.max() > m:
                a = a & m
        elif dtype == "bool":
            a = a != 0
        return SA(a, dtype)

    # -- numpy-ish surface ---------------------------------------------
    @property
    def shape(self):
        return self.a.shape

    @property
    def ndim(self):
        return self.a.ndim

    def reshape(self, *s):
        if len(s) == 1 and isinstance(s[0], (tuple, list)):
            s = tuple(s[0])
        return SA(self.a.reshape(s), self.dtype)

    def astype(self, dt) -> "SA":
        dt = _dt_name(dt)
        if dt == self.dtype:
            return self
        if dt == "bool":
            return SA(self.a != 0, "bool")
        a = self.a
        if self.dtype == "bool":
            a = np.asarray(a.astype(object) * 1, dtype=object)
        if dt == "int32" and a.size:
            mn, mx = a.min(), a.max()
            if mn < I32_MIN or mx > I32_MAX:
                # the analyzer models astype(int32) as exact — a
                # wrapping conversion invalidates every downstream bound
                raise Counterexample(
                    f"astype(int32) of out-of-range value "
                    f"{mx if mx > I32_MAX else mn} at {_blame()}",
                    _blame())
        return SA.make(a, dt)

    def item(self):
        return self.a.item()

    def __int__(self):
        return int(self.a.item())

    def __bool__(self):
        if self.a.size != 1:
            raise ValueError("truth value of non-scalar shadow array")
        return bool(self.a.item())

    def __index__(self):
        return int(self.a.item())

    def __len__(self):
        return self.a.shape[0]

    def __getitem__(self, idx):
        idx = _coerce_index(idx)
        r = self.a[idx]
        if not isinstance(r, np.ndarray):
            r = np.array(r, dtype=object)
        return SA(r, self.dtype)

    @property
    def at(self):
        return _At(self)

    # -- arithmetic ----------------------------------------------------
    def _bin(self, other, fn, out_dt: Optional[str] = None) -> "SA":
        oa, odt = _operand(other, self.dtype)
        dt = out_dt or _promote(self.dtype, odt)
        if dt == "bool" and out_dt is None:
            dt = "int32" if fn not in (_and, _or, _xor) else "bool"
        return SA.make(fn(self.a, oa), dt)

    def _rbin(self, other, fn, out_dt: Optional[str] = None) -> "SA":
        oa, odt = _operand(other, self.dtype)
        dt = out_dt or _promote(self.dtype, odt)
        if dt == "bool" and out_dt is None:
            dt = "int32" if fn not in (_and, _or, _xor) else "bool"
        return SA.make(fn(oa, self.a), dt)

    def __add__(self, o):
        return self._bin(o, lambda a, b: a + b)

    def __radd__(self, o):
        return self._rbin(o, lambda a, b: a + b)

    def __sub__(self, o):
        return self._bin(o, lambda a, b: a - b)

    def __rsub__(self, o):
        return self._rbin(o, lambda a, b: a - b)

    def __mul__(self, o):
        return self._bin(o, lambda a, b: a * b)

    def __rmul__(self, o):
        return self._rbin(o, lambda a, b: a * b)

    def __floordiv__(self, o):
        return self._bin(o, lambda a, b: a // b)

    def __mod__(self, o):
        return self._bin(o, lambda a, b: a % b)

    def __rshift__(self, o):
        return self._bin(o, lambda a, b: a >> b)

    def __lshift__(self, o):
        return self._bin(o, lambda a, b: a << b)

    def __and__(self, o):
        return self._bin(o, _and)

    def __rand__(self, o):
        return self._rbin(o, _and)

    def __or__(self, o):
        return self._bin(o, _or)

    def __ror__(self, o):
        return self._rbin(o, _or)

    def __xor__(self, o):
        return self._bin(o, _xor)

    def __neg__(self):
        return SA.make(-(self.a.astype(object) * 1
                         if self.dtype == "bool" else self.a),
                       "int32" if self.dtype == "bool" else self.dtype)

    def __invert__(self):
        if self.dtype == "bool":
            return SA(~(self.a.astype(bool)), "bool")
        return self._bin(-1, _xor)

    def _cmp(self, o, fn) -> "SA":
        oa, _ = _operand(o, self.dtype)
        r = np.asarray(fn(self.a, oa))
        return SA(r.astype(bool), "bool")

    def __eq__(self, o):  # type: ignore[override]
        return self._cmp(o, lambda a, b: a == b)

    def __ne__(self, o):  # type: ignore[override]
        return self._cmp(o, lambda a, b: a != b)

    def __lt__(self, o):
        return self._cmp(o, lambda a, b: a < b)

    def __le__(self, o):
        return self._cmp(o, lambda a, b: a <= b)

    def __gt__(self, o):
        return self._cmp(o, lambda a, b: a > b)

    def __ge__(self, o):
        return self._cmp(o, lambda a, b: a >= b)

    __hash__ = None  # type: ignore[assignment]

    def __repr__(self):
        return f"SA{self.shape}:{self.dtype}"


def _and(a, b):
    return a & b


def _or(a, b):
    return a | b


def _xor(a, b):
    return a ^ b


class _At:
    """`x.at[idx].set(v)` — functional update, copy-on-write."""

    def __init__(self, sa: SA):
        self.sa = sa

    def __getitem__(self, idx):
        sa = self.sa

        class _Upd:
            @staticmethod
            def set(v):
                a = sa.a.copy()
                a[_coerce_index(idx)] = _operand(v, sa.dtype)[0]
                return SA.make(a, sa.dtype)

            @staticmethod
            def add(v):
                a = sa.a.copy()
                ci = _coerce_index(idx)
                a[ci] = a[ci] + _operand(v, sa.dtype)[0]
                return SA.make(a, sa.dtype)

        return _Upd


def _coerce_index(idx):
    if isinstance(idx, tuple):
        return tuple(_coerce_index(i) for i in idx)
    if isinstance(idx, SA):
        return int(idx) if idx.a.ndim == 0 else idx.a.astype(
            bool if idx.dtype == "bool" else int)
    return idx


def _dt_name(dt) -> str:
    if isinstance(dt, str):
        return dt
    if dt is bool:
        return "bool"
    if dt is int:
        return "int32"
    name = getattr(dt, "__name__", None) or str(np.dtype(dt))
    return {"bool_": "bool", "int64": "int32"}.get(name, name)


def _operand(v, ctx_dt: str) -> Tuple[Any, str]:
    """(object-array-or-scalar, dtype) view of any operand."""
    if isinstance(v, SA):
        a = v.a
        if v.dtype == "bool":
            return a.astype(object) * 1, "bool"
        return a, v.dtype
    if isinstance(v, np.ndarray):
        return v.astype(object), _dt_name(v.dtype)
    if isinstance(v, np.generic):
        return int(v), _dt_name(v.dtype)
    if isinstance(v, bool):
        return int(v), "bool"
    if isinstance(v, int):
        return v, ctx_dt          # python scalar adopts context dtype
    if isinstance(v, (list, tuple)):
        return np.array(v, dtype=object), ctx_dt
    raise TypeError(f"shadow op with {type(v).__name__}")


def as_sa(v, dtype: Optional[str] = None) -> SA:
    if isinstance(v, SA):
        return v.astype(dtype) if dtype else v
    if isinstance(v, np.ndarray):
        dt = dtype or _dt_name(v.dtype)
        return SA.make(v.astype(object), dt)
    if isinstance(v, np.generic):
        dt = dtype or _dt_name(v.dtype)
        return SA.make(np.array(int(v), dtype=object), dt)
    if isinstance(v, (bool, int)):
        dt = dtype or ("bool" if isinstance(v, bool) else "int32")
        return SA.make(np.array(int(v), dtype=object), dt)
    if isinstance(v, (list, tuple)):
        return SA.make(np.array(v, dtype=object), dtype or "int32")
    raise TypeError(f"cannot shadow {type(v).__name__}")


# --- pytree helpers (tuples/lists/dicts of SA) ------------------------------

def _tree_map(fn, *trees):
    t0 = trees[0]
    if isinstance(t0, (tuple, list)):
        return type(t0)(_tree_map(fn, *elems) for elems in zip(*trees))
    if isinstance(t0, dict):
        return {k: _tree_map(fn, *(t[k] for t in trees)) for k in t0}
    return fn(*trees)


def _tree_leaves(t, out):
    if isinstance(t, (tuple, list)):
        for e in t:
            _tree_leaves(e, out)
    elif isinstance(t, dict):
        for k in sorted(t):
            _tree_leaves(t[k], out)
    elif t is not None:
        out.append(t)
    return out


# --- the jax shim -----------------------------------------------------------

def _np_of(v):
    return v.a if isinstance(v, SA) else (
        v.astype(object) if isinstance(v, np.ndarray) else v)


def _dt_of(v, default="int32"):
    if isinstance(v, SA):
        return v.dtype
    if isinstance(v, np.ndarray):
        return _dt_name(v.dtype)
    return default


def _uniform_dt(xs):
    dt = "bool"
    for x in xs:
        dt = _promote(dt, _dt_of(x))
    return dt


def _mk_jnp() -> types.ModuleType:
    jnp = types.ModuleType("jax.numpy")
    jnp.ndarray = SA
    jnp.int32 = "int32"
    jnp.uint32 = "uint32"
    jnp.uint8 = "uint8"
    jnp.bool_ = "bool"

    def asarray(x, dtype=None):
        return as_sa(x, _dt_name(dtype) if dtype is not None else None)

    def zeros(shape, dtype="int32"):
        return SA(np.zeros(shape, dtype=object), _dt_name(dtype))

    def ones(shape, dtype="int32"):
        return SA(np.ones(shape, dtype=object) * 1, _dt_name(dtype))

    def zeros_like(x):
        x = as_sa(x)
        return SA(np.zeros(x.shape, dtype=object), x.dtype)

    def arange(n, dtype="int32"):
        return SA(np.arange(int(n)).astype(object), _dt_name(dtype))

    def stack(xs, axis=0):
        xs = list(xs)
        dt = _uniform_dt(xs)
        return SA.make(np.stack([_np_of(as_sa(x)) for x in xs],
                                axis=axis), dt)

    def concatenate(xs, axis=0):
        xs = list(xs)
        dt = _uniform_dt(xs)
        return SA.make(np.concatenate([_np_of(as_sa(x)) for x in xs],
                                      axis=axis), dt)

    def where(cond, a, b):
        c = as_sa(cond).a
        sa, sb = as_sa(a), as_sa(b)
        return SA.make(np.where(c.astype(bool), _np_of(sa), _np_of(sb)),
                       _promote(sa.dtype, sb.dtype))

    def moveaxis(x, src, dst):
        x = as_sa(x)
        return SA(np.moveaxis(x.a, src, dst), x.dtype)

    def transpose(x, axes=None):
        x = as_sa(x)
        return SA(np.transpose(x.a, axes), x.dtype)

    def broadcast_to(x, shape):
        x = as_sa(x)
        return SA(np.broadcast_to(x.a, shape), x.dtype)

    def broadcast_arrays(*xs):
        sas = [as_sa(x) for x in xs]
        bs = np.broadcast_arrays(*[s.a for s in sas])
        return [SA(b, s.dtype) for b, s in zip(bs, sas)]

    def all_(x, axis=None):
        x = as_sa(x)
        r = np.all(x.a.astype(bool), axis=axis)
        if not isinstance(r, np.ndarray):
            r = np.array(bool(r), dtype=object)
        return SA(r, "bool")

    def sum_(x, axis=None, dtype=None):
        x = as_sa(x)
        r = np.sum(x.a if x.dtype != "bool" else x.a.astype(object) * 1,
                   axis=axis)
        if not isinstance(r, np.ndarray):
            r = np.array(r, dtype=object)
        dt = _dt_name(dtype) if dtype else (
            "int32" if x.dtype == "bool" else x.dtype)
        return SA.make(r, dt)

    def take(x, idx, axis=None):
        x = as_sa(x)
        if isinstance(idx, SA):
            idx = (int(idx) if idx.a.ndim == 0
                   else idx.a.astype(int))
        return SA(np.take(x.a, idx, axis=axis), x.dtype)

    jnp.asarray = asarray
    jnp.array = asarray
    jnp.zeros = zeros
    jnp.ones = ones
    jnp.zeros_like = zeros_like
    jnp.ones_like = lambda x: ones(as_sa(x).shape, as_sa(x).dtype)
    jnp.arange = arange
    jnp.stack = stack
    jnp.concatenate = concatenate
    jnp.where = where
    jnp.moveaxis = moveaxis
    jnp.transpose = transpose
    jnp.broadcast_to = broadcast_to
    jnp.broadcast_arrays = broadcast_arrays
    jnp.broadcast_shapes = np.broadcast_shapes
    jnp.all = all_
    jnp.sum = sum_
    jnp.take = take
    return jnp


def _mk_lax() -> types.ModuleType:
    lax = types.ModuleType("jax.lax")

    def scan(f, init, xs, length=None):
        if xs is None:
            n = int(length)
            steps = [None] * n
        else:
            leaves = _tree_leaves(xs, [])
            n = leaves[0].shape[0]
            steps = [_tree_map(lambda l: l[i], xs) for i in range(n)]
        carry, ys = init, []
        for st in steps:
            carry, y = f(carry, st)
            ys.append(y)
        if not ys or all(y is None for y in ys):
            return carry, None
        stacked = _tree_map(
            lambda *row: SA.make(
                np.stack([_np_of(r) for r in row], axis=0),
                _uniform_dt(row)), *ys)
        return carry, stacked

    def fori_loop(lo, hi, body, init):
        v = init
        for i in range(int(lo), int(hi)):
            v = body(i, v)
        return v

    def dynamic_slice(x, starts, sizes):
        x = as_sa(x)
        idx = tuple(slice(int(s), int(s) + int(z))
                    for s, z in zip(starts, sizes))
        return SA(x.a[idx], x.dtype)

    def dynamic_index_in_dim(x, i, axis=0, keepdims=True):
        x = as_sa(x)
        i = int(i)
        idx = [slice(None)] * x.ndim
        idx[axis] = slice(i, i + 1) if keepdims else i
        r = x.a[tuple(idx)]
        if not isinstance(r, np.ndarray):
            r = np.array(r, dtype=object)
        return SA(r, x.dtype)

    lax.scan = scan
    lax.fori_loop = fori_loop
    lax.dynamic_slice = dynamic_slice
    lax.dynamic_index_in_dim = dynamic_index_in_dim
    return lax


# --- pallas shim ------------------------------------------------------------

class ShapeDtypeStruct:
    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = _dt_name(dtype)


class BlockSpec:
    def __init__(self, block_shape=None, index_map=None,
                 memory_space=None):
        self.block_shape = (tuple(block_shape)
                            if block_shape is not None else None)
        self.index_map = index_map


class VMEM:
    """Doubles as the memory_space token (the class object) and the
    scratch-shape spec (instances)."""

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.dtype = _dt_name(dtype)


class Ref:
    """A mutable block view: reads return SA, writes land in the
    (view of the) underlying object array."""

    def __init__(self, a: np.ndarray, dtype: str):
        self.a = a
        self.dtype = dtype

    @property
    def shape(self):
        return self.a.shape

    def __getitem__(self, idx):
        r = self.a[_coerce_index(idx)]
        if not isinstance(r, np.ndarray):
            r = np.array(r, dtype=object)
        return SA(r, self.dtype)

    def __setitem__(self, idx, val):
        sa = as_sa(val, self.dtype)   # astype runs the contract check
        self.a[_coerce_index(idx)] = sa.a


def _block_view(arr: np.ndarray, spec: Optional[BlockSpec],
                gidx: Tuple[int, ...]) -> np.ndarray:
    if spec is None or spec.block_shape is None:
        return arr
    bs = spec.block_shape
    if spec.index_map is None:
        off = (0,) * len(bs)
    else:
        off = tuple(int(i) for i in spec.index_map(*gidx))
    sl = tuple(slice(o * b, o * b + b) for o, b in zip(off, bs))
    return arr[sl]


def _pallas_call(kernel, out_shape, grid=None, in_specs=None,
                 out_specs=None, scratch_shapes=(), interpret=False,
                 **_kw):
    multi = isinstance(out_shape, (tuple, list))
    outs = list(out_shape) if multi else [out_shape]
    out_sp = (list(out_specs) if isinstance(out_specs, (tuple, list))
              else [out_specs])

    def call(*inputs):
        sas = [as_sa(x) for x in inputs]
        bufs = [np.zeros(o.shape, dtype=object) for o in outs]
        steps = ([()] if not grid else
                 [(i,) for i in range(int(grid[0]))] if len(grid) == 1
                 else list(np.ndindex(*[int(g) for g in grid])))
        specs = list(in_specs) if in_specs else [None] * len(sas)
        for gidx in steps:
            refs = [Ref(_block_view(s.a, sp, gidx), s.dtype)
                    for s, sp in zip(sas, specs)]
            orefs = [Ref(_block_view(b, sp, gidx), o.dtype)
                     for b, sp, o in zip(bufs, out_sp, outs)]
            scratch = [Ref(np.zeros(sc.shape, dtype=object), sc.dtype)
                       for sc in scratch_shapes]
            kernel(*refs, *orefs, *scratch)
        res = [SA.make(b, o.dtype) for b, o in zip(bufs, outs)]
        return tuple(res) if multi else res[0]

    return call


def _install_shim() -> None:
    if "jax" in sys.modules:
        raise SystemExit(
            "interval_fuzz must own the `jax` module: run it in a "
            "fresh interpreter (python -m tools.interval_fuzz), not "
            "inside a process that already imported jax")
    jax = types.ModuleType("jax")
    jnp = _mk_jnp()
    lax = _mk_lax()
    tree_util = types.ModuleType("jax.tree_util")
    tree_util.tree_map = _tree_map

    def jit(fn=None, **_kw):
        if fn is None:
            return lambda f: f
        return fn

    jax.jit = jit
    jax.numpy = jnp
    jax.lax = lax
    jax.tree_util = tree_util
    jax.ShapeDtypeStruct = ShapeDtypeStruct

    pallas = types.ModuleType("jax.experimental.pallas")
    pallas.BlockSpec = BlockSpec
    pallas.pallas_call = _pallas_call
    pltpu = types.ModuleType("jax.experimental.pallas.tpu")
    pltpu.VMEM = VMEM
    pallas.tpu = pltpu
    experimental = types.ModuleType("jax.experimental")
    experimental.pallas = pallas
    jax.experimental = experimental

    sys.modules["jax"] = jax
    sys.modules["jax.numpy"] = jnp
    sys.modules["jax.lax"] = lax
    sys.modules["jax.tree_util"] = tree_util
    sys.modules["jax.experimental"] = experimental
    sys.modules["jax.experimental.pallas"] = pallas
    sys.modules["jax.experimental.pallas.tpu"] = pltpu


# --- assume() spec extraction (same pragmas the analyzer seeds from) --------

def _fn_specs(relpath: str, qual: str) -> Dict[str, Any]:
    """assume() pragmas of the (possibly nested) function `qual` in
    `relpath`: pragma lines sit between the `def` line and the first
    body statement."""
    from tools.staticcheck import parse_assume
    path = os.path.join(ROOT, relpath)
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    lines = src.splitlines()
    tree = ast.parse(src)
    node: Any = tree
    for part in qual.split("."):
        node = next(n for n in ast.walk(node)
                    if isinstance(n, ast.FunctionDef) and n.name == part)
    specs: Dict[str, Any] = {}
    for ln in range(node.lineno, node.body[0].lineno - 1):
        sp = parse_assume(lines[ln], ln + 1)
        if sp is not None:
            specs[sp.var] = sp
    if not specs:
        raise SystemExit(f"{relpath}::{qual}: no assume() pragmas — "
                         f"the fuzzer has nothing to sample inside")
    return specs


def _sample(spec, dims: Dict[str, int], rng: np.random.Generator
            ) -> Tuple[SA, int]:
    """One input drawn inside the claimed interval: uniform, with 1/8
    of the elements pinned to the lo/hi endpoints."""
    shape = tuple(dims[d] if isinstance(d, str) else d
                  for d in (spec.shape or ()))
    vals = rng.integers(spec.lo, spec.hi + 1, size=shape or (),
                        dtype=np.int64)
    edge = rng.random(size=shape or ()) < 0.125
    ends = np.where(rng.random(size=shape or ()) < 0.5,
                    spec.lo, spec.hi)
    vals = np.where(edge, ends, vals)
    arr = np.asarray(vals).astype(object)
    if not shape:
        return SA(np.array(int(arr), dtype=object), spec.dtype), 1
    return SA(arr, spec.dtype), int(np.asarray(vals).size)


# --- fuzz targets -----------------------------------------------------------
#
# Each target names the ops function whose assume() pragmas define the
# input intervals, the dims to instantiate the symbolic axes with, and
# how to call it. TILE is pinned to 8 (env override below) so pallas
# grids stay small; TAIL=8 forces TILE >= 8.

def _t_pallas(fn_name):
    def run(specs, dims, rng, count):
        import cometbft_tpu.ops.pallas_verify as pv
        fn = getattr(pv, fn_name)
        params = [p for p in specs
                  if specs[p].shape is not None or p in ("bucket",)]
        args = []
        for p in params:
            sa, n = _sample(specs[p], dims, rng)
            args.append(sa)
            count[0] += n
        fn(*args)
    return run


def _t_ed25519(fn_name, with_z):
    def run(specs, dims, rng, count):
        import cometbft_tpu.ops.ed25519 as e
        fn = getattr(e, fn_name)
        order = ["pub", "sig", "hblocks", "hnblocks"] + (
            ["z"] if with_z else [])
        args = []
        for p in order:
            sp = specs[p]
            if p == "hnblocks":
                # live block count can't exceed the padded B axis —
                # sample the [1, B] sub-interval of the claim
                vals = rng.integers(1, dims["B"] + 1,
                                    size=(dims["N"],)).astype(object)
                args.append(SA(vals, "int32"))
                count[0] += dims["N"]
                continue
            sa, n = _sample(sp, dims, rng)
            args.append(sa)
            count[0] += n
        fn(*args)
    return run


def _t_bls_pow(specs, dims, rng, count):
    import cometbft_tpu.ops.bls12 as b
    arr, n = _sample(specs["arr"], dims, rng)
    count[0] += n
    # short static chain: same loop body as HARD_BITS, truncated
    b._compiled(dims["B"], (1, 0, 1, 1, 0, 1))(arr)


def _t_bls_miller(specs, dims, rng, count):
    import cometbft_tpu.ops.bls12 as b
    lines, n = _sample(specs["lines"], dims, rng)
    count[0] += n
    m = b._unpack_tree(b.miller_scan(lines))
    b.final_exp_easy_j(m)   # incl. the Fermat-inversion scan


TARGETS: List[Tuple[str, str, str, Dict[str, int], Dict[str, int],
                    Any]] = [
    # (name, relpath, qualname-with-the-pragmas, dims,
    #  quick-mode dim overrides, runner)
    ("pt_add_tiled", "cometbft_tpu/ops/pallas_verify.py",
     "pt_add_tiled", {"N": 16}, {}, _t_pallas("pt_add_tiled")),
    ("rlc_window_sums", "cometbft_tpu/ops/pallas_verify.py",
     "rlc_window_sums_impl", {"N": 8}, {},
     _t_pallas("rlc_window_sums_impl")),
    ("pt_decompress_tiled", "cometbft_tpu/ops/pallas_verify.py",
     "pt_decompress_tiled_impl", {"N": 16}, {},
     _t_pallas("pt_decompress_tiled_impl")),
    ("rlc_epilogue", "cometbft_tpu/ops/pallas_verify.py",
     "rlc_epilogue_impl", {"M": 2}, {}, _t_pallas("rlc_epilogue_impl")),
    ("verify_core", "cometbft_tpu/ops/ed25519.py",
     "verify_core", {"N": 8, "B": 2}, {"B": 1},
     _t_ed25519("verify_core", False)),
    ("verify_rlc_core", "cometbft_tpu/ops/ed25519.py",
     "verify_rlc_core", {"N": 8, "B": 2}, {"B": 1},
     _t_ed25519("verify_rlc_core", True)),
    ("verify_rlc_core_pallas", "cometbft_tpu/ops/ed25519.py",
     "verify_rlc_core_pallas", {"N": 8, "B": 2}, {"B": 1},
     _t_ed25519("verify_rlc_core_pallas", True)),
    ("bls12_pow_is_one", "cometbft_tpu/ops/bls12.py",
     "_compiled.run", {"B": 4}, {}, _t_bls_pow),
    ("bls12_miller_finalexp", "cometbft_tpu/ops/bls12.py",
     "_compiled_miller.run", {"S": 2, "B": 4}, {}, _t_bls_miller),
]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.interval_fuzz",
        description="concrete-execution differential check of the "
                    "kernel-interval no-overflow proof")
    ap.add_argument("--quick", action="store_true",
                    help="one seed per kernel (CI smoke; full mode "
                         "runs 3)")
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed (per-kernel seeds derive from it)")
    ap.add_argument("--samples", type=int, default=1000,
                    help="minimum sampled scalars per kernel "
                         "(reruns with fresh seeds until reached)")
    ap.add_argument("--kernel", action="append", default=None,
                    help="run only this target (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="list fuzz targets")
    args = ap.parse_args(argv)

    if args.list:
        for name, rel, qual, dims, _qdims, _run in TARGETS:
            print(f"{name:24s} {rel}::{qual}  dims={dims}")
        return 0

    targets = TARGETS
    if args.kernel:
        by = {t[0]: t for t in TARGETS}
        unknown = [k for k in args.kernel if k not in by]
        if unknown:
            print(f"unknown kernel(s): {', '.join(unknown)} "
                  f"(see --list)", file=sys.stderr)
            return 2
        targets = [by[k] for k in args.kernel]

    # TILE=8 keeps pallas grids/trees tiny (TAIL=8 is the floor);
    # must be set before cometbft_tpu.ops.pallas_verify is imported
    os.environ["COMETBFT_TPU_PALLAS_TILE"] = "8"
    _install_shim()
    sys.path.insert(0, ROOT)

    rounds = 1 if args.quick else 3
    failed = False
    for name, rel, qual, dims, qdims, run in targets:
        if args.quick:
            dims = {**dims, **qdims}
        specs = _fn_specs(rel, qual)
        t0 = time.monotonic()
        count = [0]
        seed_used = None
        try:
            r = 0
            while r < rounds or count[0] < args.samples:
                seed_used = (args.seed * 10007
                             + zlib.crc32(name.encode()) % 65536 + r)
                rng = np.random.default_rng(seed_used)
                run(specs, dims, rng, count)
                r += 1
        except Counterexample as e:
            failed = True
            print(f"FAIL {name}: {e}  [seed {seed_used}] — the "
                  f"kernel-interval proof is unsound here; replay: "
                  f"python -m tools.interval_fuzz --kernel {name} "
                  f"--seed {args.seed}", file=sys.stderr)
            continue
        dt = time.monotonic() - t0
        print(f"ok {name}: {count[0]} samples, {r} run(s), {dt:.1f}s")
    if failed:
        return 1
    print("interval_fuzz: all kernels clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
