"""Flight-recorder trace viewer — JSONL dumps -> Chrome trace-event JSON.

Usage:
  python tools/trace_view.py dump.jsonl [-o trace.json]
      Convert a flight-recorder dump (or a ring snapshot) to the Chrome
      trace-event format; load the output at chrome://tracing or
      ui.perfetto.dev. `-o -` (the default) writes to stdout.

  python tools/trace_view.py dump.jsonl --chain <sid>
      Reconstruct and print the causal chain ending at span id <sid>
      (cause first): parent links walked span by span, coalescing seams
      (a flush span serving many tickets) crossed via span links.

  python tools/trace_view.py --selftest
      Build a synthetic rpc -> ingest -> flush -> mesh trace through
      the REAL Tracer/FlightRecorder under a virtual clock, trigger a
      dump, convert it, and assert the invariants the test suite and
      acceptance checks rely on (id determinism, parent/link fidelity,
      exactly-once dumps, stable double conversion). Exit 0 on success;
      wired into tools/run_suite.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cometbft_tpu.libs import timesource  # noqa: E402
from cometbft_tpu.trace.export import (causal_chain, convert,  # noqa: E402
                                       load_jsonl)
from cometbft_tpu.trace.recorder import FlightRecorder  # noqa: E402
from cometbft_tpu.trace.span import NOOP_SPAN, Tracer  # noqa: E402


def _convert_file(path: str, out: str) -> int:
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    doc = convert(text)
    if out in ("-", ""):
        sys.stdout.write(doc + "\n")
    else:
        with open(out, "w", encoding="utf-8") as fh:
            fh.write(doc + "\n")
        print(f"wrote {out}", file=sys.stderr)
    return 0


def _print_chain(path: str, sid: int) -> int:
    with open(path, "r", encoding="utf-8") as fh:
        _meta, spans = load_jsonl(fh.read())
    chain = causal_chain(spans, sid)
    if not chain:
        print(f"no span with sid={sid} in {path}", file=sys.stderr)
        return 1
    for i, span in enumerate(chain):
        hop = "  " * i
        attrs = span.get("attrs", {})
        extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        print(f"{hop}{span['name']} sid={span['sid']} "
              f"tid={span['tid']} t0={span['t0']} t1={span['t1']}"
              + (f" {extra}" if extra else ""))
    return 0


def _selftest() -> int:
    # virtual clock so the selftest's bytes are reproducible anywhere
    vclock = [1_000_000]

    def now_ns() -> int:
        vclock[0] += 1_000
        return vclock[0]

    timesource.install(now_ns)
    try:
        rec = FlightRecorder(capacity=64)
        tracer = Tracer(recorder=rec, enabled=True, seed=7)

        # disabled mode returns the singleton — no allocations
        tracer.enabled = False
        assert tracer.start("off") is NOOP_SPAN
        tracer.enabled = True

        # rpc root -> ingest admit; a flush span links the admit span
        root = tracer.start("rpc.broadcast_tx", route="sync")
        admit = tracer.start("ingest.admit", parent=root, lane=0)
        admit.event("enqueued", depth=1)
        admit.end()
        flush = tracer.start("ingest.flush", lanes=1)
        flush.link(admit.ctx)
        mesh = tracer.start("mesh.dispatch", parent=flush, shards=2)
        cpu = tracer.start("mesh.cpu_reverify", parent=mesh, shard=1)
        cpu.end()
        mesh.end()
        flush.end()
        root.end()

        # seeded ids are deterministic
        assert root.span_id == 7 * (1 << 20) + 1, root.span_id
        assert admit.parent_id == root.span_id

        # exactly-once dump per (kind, key)
        assert rec.trigger("selftest", "0", "forced") is True
        assert rec.trigger("selftest", "0", "forced") is False
        assert len(rec.dumps) == 1

        kind, key, _detail, text, _path = rec.dumps[0]
        assert (kind, key) == ("selftest", "0")
        meta, spans = load_jsonl(text)
        assert meta is not None and meta["kind"] == "selftest"
        assert meta["spans"] == len(spans) == 5
        assert meta["evicted"] == 0

        # causal chain crosses the flush coalescing seam back to rpc
        chain = causal_chain(spans, cpu.span_id)
        names = [s["name"] for s in chain]
        assert names == ["rpc.broadcast_tx", "ingest.admit",
                         "ingest.flush", "mesh.dispatch",
                         "mesh.cpu_reverify"], names

        # conversion round-trips and is stable
        doc1 = convert(text)
        doc2 = convert(text)
        assert doc1 == doc2
        events = json.loads(doc1)["traceEvents"]
        complete = [e for e in events if e["ph"] == "X"]
        instants = [e for e in events if e["ph"] == "i"]
        assert len(complete) == 5 and len(instants) == 1
        by_name = {e["name"]: e for e in complete}
        assert (by_name["ingest.admit"]["args"]["parent_sid"]
                == root.span_id)
        assert (by_name["ingest.flush"]["args"]["links"]
                == [admit.span_id])
        assert all(e["dur"] >= 0 for e in complete)

        # ring eviction accounting survives overflow
        small = FlightRecorder(capacity=2)
        t2 = Tracer(recorder=small, enabled=True, seed=1)
        for i in range(5):
            t2.start(f"s{i}").end()
        st = small.stats()
        assert st["recorded"] == 5 and st["evicted"] == 3
        assert st["occupancy"] == 2
    finally:
        timesource.reset()
    print("trace_view selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="flight-recorder JSONL -> Chrome trace JSON")
    ap.add_argument("input", nargs="?", help="dump/snapshot JSONL file")
    ap.add_argument("-o", "--output", default="-",
                    help="output path ('-' = stdout)")
    ap.add_argument("--chain", type=int, metavar="SID",
                    help="print the causal chain ending at span SID")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in invariant checks")
    args = ap.parse_args(argv)

    if args.selftest:
        return _selftest()
    if not args.input:
        ap.error("input JSONL required (or --selftest)")
    if args.chain is not None:
        return _print_chain(args.input, args.chain)
    return _convert_file(args.input, args.output)


if __name__ == "__main__":
    sys.exit(main())
