"""Explicit-state checker for light-client verification safety
(spec/LightClient.tla; reference spec/light-client/verification/ —
VERDICT r4 missing #6's "formal artifacts beyond one TLA+ file").

Models EXACTLY the implementation's acceptance rules
(light/verifier.py + types/validation.py):

  adjacent (h0 -> h0+1):  untrusted valset must BE the trusted
      header's next-valset (hash-bound), and its commit carries
      > floor(2/3·power) of that set;
  non-adjacent (skipping): commit signers within the TRUSTED set carry
      > floor(1/3·power(trusted)) [verify_commit_light_trusting,
      strict, floor-divided exactly as validation.py:192], and the
      commit carries > floor(2/3·power(claimed set)) of the header's
      OWN claimed valset.

Adversary model: a fixed faulty subset F signs ANYTHING (forged
headers with arbitrary claimed valsets); honest validators sign only
the canonical header of each height. The checker enumerates every
canonical chain over a valset family, every faulty subset satisfying
the fault assumption (|F ∩ C[h]| power < 1/3 of C[h] for every height
in the trust period), every reachable trusted state, and EVERY forged
header (claimed valset × signer subset) against it.

Safety (the spec's Invariant): a header accepted from a trusted state
is the canonical header of its height — forged headers are always
rejected while the fault assumption holds.

--self-test drops the fault assumption (allows F up to 2/3 of a
valset) and must FIND an accepted forgery — proving the checker can
detect unsafety, and demonstrating exactly why the 1/3 bound is the
trust assumption.

Usage: python tools/check_light_spec.py [--n 4] [--heights 4]
           [--self-test]
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time


def subsets(universe, min_size=1):
    for k in range(min_size, len(universe) + 1):
        yield from itertools.combinations(universe, k)


# --- the two acceptance predicates, power-weighted ---------------------------
#
# Shared by the exhaustive model below (equal-power valsets: power =
# cardinality) and by `check_decisions`, which re-judges CONCRETE
# acceptance records (the farm's decision log, where validators carry
# real voting power). Both restate validation.py's floor-divided strict
# thresholds: needed = total * num // den, accepted iff tallied > needed.

def trusting_ok_power(signed: int, total: int,
                      num: int = 1, den: int = 3) -> bool:
    """verify_commit_light_trusting: trusted-set power that signed must
    EXCEED floor(total * num/den) (validation.py:210-216, strict)."""
    return signed > (total * num) // den


def own_commit_ok_power(signed: int, total: int) -> bool:
    """verify_commit_light: claimed-set power on the commit must EXCEED
    floor(2/3 * total) (validation.py:189-194, strict)."""
    return signed > (total * 2) // 3


def check_decisions(records):
    """Re-judge accepted-header decision records against the spec's
    acceptance rules; returns violation strings (empty = all conform).

    Each record states one farm/light acceptance as its power tallies
    (farm/planner._record): `adjacent`, `valhash_bound`, `own_signed` /
    `own_total` (the header's own claimed set on its commit), and for
    skipping steps `trusted_signed` / `trusted_total` (trusted-set
    power that signed) plus the trust fraction. This is the bridge the
    light-farm simnet scenario crosses: every header the farm accepted
    must satisfy exactly the rules the exhaustive model proves safe."""
    errs = []
    for i, r in enumerate(records):
        label = (f"record {i} h={r.get('height')} "
                 f"session={r.get('session', '?')}")
        if not own_commit_ok_power(r["own_signed"], r["own_total"]):
            errs.append(
                f"{label}: own-commit power {r['own_signed']}/"
                f"{r['own_total']} fails the >2/3 rule")
        if r.get("adjacent"):
            if not r.get("valhash_bound"):
                errs.append(f"{label}: adjacent step accepted without "
                            f"valset-hash binding")
        elif not trusting_ok_power(r["trusted_signed"],
                                   r["trusted_total"],
                                   r.get("trust_num", 1),
                                   r.get("trust_den", 3)):
            errs.append(
                f"{label}: trusting power {r['trusted_signed']}/"
                f"{r['trusted_total']} fails the "
                f">{r.get('trust_num', 1)}/{r.get('trust_den', 3)} "
                f"rule")
    return errs


class LightModel:
    def __init__(self, n=4, heights=4, min_valset=3,
                 break_assumption=False):
        self.n = n
        self.vals = tuple(range(n))
        self.heights = heights
        # candidate valsets for canonical chains (equal power 1 each)
        self.valsets = [frozenset(s) for s in
                        subsets(self.vals, min_valset)]
        self.break_assumption = break_assumption

    # --- the implementation's two threshold rules ------------------------

    @staticmethod
    def trusting_ok(signers, trusted) -> bool:
        """validation.py:192-194 + tallied > needed (strict); equal
        power, so power = cardinality."""
        return trusting_ok_power(len(signers & trusted), len(trusted))

    @staticmethod
    def own_commit_ok(signers, claimed) -> bool:
        """verify_commit_light: signers must be members; > 2/3."""
        return (signers <= claimed
                and own_commit_ok_power(len(signers), len(claimed)))

    # --- enumeration ------------------------------------------------------

    def fault_sets(self, chain):
        """Faulty subsets F consistent with the fault assumption over
        the whole chain (or ALL subsets when --self-test breaks it)."""
        for f in subsets(self.vals, 1):
            F = frozenset(f)
            if self.break_assumption:
                yield F
            elif all(len(F & c) <= (len(c) - 1) // 3 for c in chain):
                # strictly below 1/3 of every canonical valset
                yield F

    def check_chain(self, chain, F):
        """BFS over trusted states (height index into the chain);
        returns a violation string or None. Trusted state h means the
        client trusts canonical header h with valset chain[h]."""
        # every forged header: claimed valset W + signers S ⊆ F ∪ ∅
        # (honest validators never sign a forged header)
        reachable = {0}
        frontier = [0]
        while frontier:
            h0 = frontier.pop()
            trusted = chain[h0]
            has_skip_target = h0 + 2 < len(chain)
            # skipping-forgery acceptance depends only on the trusted
            # state, not the target height — check ONCE per h0
            if has_skip_target:
                for s in subsets(F):
                    S = frozenset(s)
                    if not self.trusting_ok(S, trusted):
                        continue
                    for w in subsets(self.vals):
                        W = frozenset(w)
                        if self.own_commit_ok(S, W):
                            return (f"SKIPPING FORGERY accepted: "
                                    f"trusted h{h0} {set(trusted)}, "
                                    f"faulty {set(F)} claimed "
                                    f"{set(W)} signers {set(S)}")
            for h in range(h0 + 1, len(chain)):
                adjacent = h == h0 + 1
                # 1) canonical header of height h: honest+faulty of
                # chain[h] may all sign — the client should accept
                canon_signers = chain[h]
                if adjacent:
                    ok = self.own_commit_ok(canon_signers, chain[h])
                else:
                    ok = (self.trusting_ok(canon_signers, trusted)
                          and self.own_commit_ok(canon_signers,
                                                 chain[h]))
                if ok and h not in reachable:
                    reachable.add(h)
                    frontier.append(h)
                # 2) forged ADJACENT header: hash-bound — claimed
                # valset must be the real next valset chain[h]; only
                # the content forks
                if adjacent:
                    for s in subsets(F):
                        S = frozenset(s)
                        if self.own_commit_ok(S, chain[h]):
                            return (f"ADJACENT FORGERY accepted: "
                                    f"trusted h{h0} {set(trusted)}, "
                                    f"faulty {set(F)} forged h{h} "
                                    f"signers {set(S)}")
        return None

    def run(self):
        """All chains × all fault sets; returns (n_configs,
        violation-or-None)."""
        n_cfg = 0
        for chain in itertools.product(self.valsets,
                                       repeat=self.heights):
            for F in self.fault_sets(chain):
                n_cfg += 1
                err = self.check_chain(chain, F)
                if err:
                    return n_cfg, err
        return n_cfg, None


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--heights", type=int, default=4)
    ap.add_argument("--min-valset", type=int, default=3)
    ap.add_argument("--self-test", action="store_true",
                    help="drop the <1/3 fault assumption; an accepted "
                         "forgery MUST be found")
    args = ap.parse_args(argv)

    model = LightModel(args.n, args.heights, args.min_valset,
                       break_assumption=args.self_test)
    t0 = time.monotonic()
    n_cfg, err = model.run()
    dt = time.monotonic() - t0
    scope = (f"n={args.n} heights={args.heights} "
             f"valsets>={args.min_valset}")

    if args.self_test:
        if err:
            print(f"SELF-TEST OK: without the fault assumption the "
                  f"checker finds: {err}  [{n_cfg:,} configs, "
                  f"{dt:.1f}s]")
            return 0
        print("SELF-TEST FAILED: no forgery found even without the "
              "fault assumption — checker cannot detect unsafety")
        return 1
    if err:
        print(f"VIOLATION ({scope}): {err}  [{n_cfg:,} configs]")
        return 1
    print(f"OK ({scope}): no forged header accepted across {n_cfg:,} "
          f"(chain × faulty-set) configs, all trusted states, all "
          f"forged headers ({dt:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
