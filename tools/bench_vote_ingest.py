"""Device-side vote-ingest benchmark: the ≤100µs/vote amortized budget
(tests/test_vote_perf.py defers its wall-clock assertion here, since the
budget is a DEVICE number — this host's single core verifies at ~400µs
per signature even through OpenSSL).

Measures `VoteSet.add_votes` — the consensus addVote hot path (reference
state.go:2341 addVote → types/vote_set.go:158, per-vote Verify at
types/vote.go:235) — batched through the device kernel for a
200-validator precommit wave.

Prints ONE JSON line:
  {"metric": "vote_ingest_amortized", "value": <µs/vote>, "unit": "us",
   "budget_us": 100, "within_budget": bool, "backend": "..."}

Env knobs: VOTES (default 200), ROUNDS (default 4),
BENCH_ALLOW_CPU=1 to run on the CPU backend (numbers then miss the
budget by design — dev only).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cometbft_tpu.libs.jax_cache import enable_compile_cache  # noqa: E402

BUDGET_US = 100.0


def _valset(n, seed=5):
    import random
    from cometbft_tpu.crypto.keys import Ed25519PrivKey
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    rng = random.Random(seed)
    keys = [Ed25519PrivKey(bytes(rng.randrange(256) for _ in range(32)))
            for _ in range(n)]
    vals = ValidatorSet([Validator(k.pub_key(), 10) for k in keys])
    by_addr = {k.pub_key().address(): k for k in keys}
    return vals, [by_addr[v.address] for v in vals.validators]


def main():
    from bench import probe_backend  # reuse the wedge-safe probe

    n_votes = int(os.environ.get("VOTES", "200"))
    rounds = int(os.environ.get("ROUNDS", "4"))
    allow_cpu = os.environ.get("BENCH_ALLOW_CPU") == "1"

    platform = probe_backend()
    if platform is None:
        print("bench_vote_ingest: FATAL: backend unavailable "
              "(see probe log)", file=sys.stderr)
        return 1
    if platform == "cpu" and not allow_cpu:
        print("bench_vote_ingest: FATAL: only CPU available and "
              "BENCH_ALLOW_CPU!=1 — the budget is a device number",
              file=sys.stderr)
        return 1
    enable_compile_cache()
    import jax

    from cometbft_tpu.types.block import BlockID, PartSetHeader
    from cometbft_tpu.types.proto import Timestamp
    from cometbft_tpu.types.vote import Vote, PRECOMMIT_TYPE
    from cometbft_tpu.types.vote_set import VoteSet

    chain = "perf-chain"
    bid = BlockID(b"\x77" * 32, PartSetHeader(1, b"\x88" * 32))
    vals, keys = _valset(n_votes)

    def wave(height):
        votes = []
        for i, k in enumerate(keys):
            v = Vote(type_=PRECOMMIT_TYPE, height=height, round=0,
                     block_id=bid, timestamp=Timestamp(100, i),
                     validator_address=k.pub_key().address(),
                     validator_index=i)
            v.signature = k.sign(v.sign_bytes(chain))
            votes.append(v)
        return votes

    # warm the kernel bucket out-of-band
    warm = VoteSet(chain, 1, 0, PRECOMMIT_TYPE, vals)
    warm.add_votes(wave(1)[:4])

    total, counted = 0.0, 0
    for r in range(rounds):
        votes = wave(2 + r)
        vs = VoteSet(chain, 2 + r, 0, PRECOMMIT_TYPE, vals)
        t0 = time.perf_counter()
        res = vs.add_votes(votes)
        total += time.perf_counter() - t0
        assert all(x is True for x in res), "ingest failed"
        counted += len(votes)

    us_per_vote = total / counted * 1e6
    print(json.dumps({
        "metric": "vote_ingest_amortized",
        "value": round(us_per_vote, 2),
        "unit": "us",
        "budget_us": BUDGET_US,
        "within_budget": us_per_vote <= BUDGET_US,
        "backend": jax.devices()[0].platform,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
