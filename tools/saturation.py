"""Saturation sweep: find this build's tx/s knee the way the reference
QA process does (docs/references/qa/method.md: escalate load until the
net stops keeping up; the v1 baseline saturates at c=1,r=400 ≈ 400 tx/s
on a 200-node DigitalOcean testnet).

Starts a local e2e testnet (OS processes over TCP), then runs
tools/loadtime.py rate steps against it, recording delivered tx/s and
latency per step. A step "saturates" when commits or delivered rate
drop below 80% of offered, or p90 latency exceeds the latency budget.
Writes a JSON report and a markdown row for docs/PERF.md.

Usage:
    JAX_PLATFORMS=cpu python tools/saturation.py \
        [--validators 4] [--rates 25,50,100,200,400] [--duration 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.e2e.runner import Manifest, Testnet  # noqa: E402
from tools import loadtime  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--validators", type=int, default=4)
    ap.add_argument("--rates", default="25,50,100,200,400")
    ap.add_argument("--duration", type=float, default=20.0)
    ap.add_argument("--latency-budget", type=float, default=8.0,
                    help="p90 commit-latency ceiling, seconds (the QA "
                         "baseline saw peaks of 8s at its knee)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    rates = [float(r) for r in args.rates.split(",")]
    root = tempfile.mkdtemp(prefix="saturation-")
    net = Testnet(Manifest(chain_id="sat-net",
                           validators=args.validators,
                           timeout_commit_ms=200), root)
    print(f"[saturation] starting {args.validators}-validator net "
          f"under {root}...", file=sys.stderr, flush=True)
    net.setup()
    net.start()
    steps = []
    try:
        net.wait_for_height(2, timeout=300)
        host, port = "127.0.0.1", net.nodes[0].rpc_port
        for rate in rates:
            print(f"[saturation] step: {rate} tx/s for "
                  f"{args.duration}s...", file=sys.stderr, flush=True)
            rep = loadtime.run(host, port, rate, args.duration,
                               connections=2)
            delivered = rep["throughput_tx_s"]
            p90 = rep["latency_p90_s"]
            lost = rep["txs_sent"] - rep["txs_committed"]
            sat = (rep["txs_committed"] < 0.8 * rep["txs_sent"]
                   or delivered < 0.8 * rate
                   or p90 > args.latency_budget)
            steps.append({"offered_tx_per_sec": rate,
                          "delivered_tx_per_sec": delivered,
                          "latency_p50_s": rep["latency_p50_s"],
                          "latency_p90_s": p90,
                          "committed": rep["txs_committed"],
                          "sent": rep["txs_sent"],
                          "lost": lost,
                          "saturated": sat})
            print(f"[saturation]   delivered {delivered:.1f} tx/s, "
                  f"p90 {p90}s, lost {lost}, saturated={sat}",
                  file=sys.stderr, flush=True)
            if sat:
                break
    finally:
        net.stop()

    knee = next((s for s in steps if s["saturated"]), None)
    best = max((s["delivered_tx_per_sec"] for s in steps), default=0.0)
    report = {
        "metric": "tx_saturation",
        "validators": args.validators,
        "best_delivered_tx_per_sec": round(best, 1),
        "knee_offered_tx_per_sec":
            knee["offered_tx_per_sec"] if knee else None,
        "steps": steps,
        "reference_baseline":
            "~400 tx/s on 200 DigitalOcean nodes (QA v1)",
        "hardware": "all validators + load generator on one local box",
    }
    print(json.dumps(report if args.json else
                     {k: v for k, v in report.items() if k != "steps"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
