"""bench_ingest: A/B the batched admission pipeline against sequential
check_tx on a fixed-latency stub device (the tunnel-RTT model bench.py
--pipeline and the blocksync A/B already use).

Both sides run the REAL IngestPipeline over a real CListMempool; the
only difference is coalescing: the batched side submits a whole wave
and flushes ONE coalesced signature batch, the sequential side flushes
after every tx — the width-1 degenerate case, so both pay identical
per-dispatch device latency and the delta is purely amortization. Tx
signatures are the flash-crowd MAC stub (deterministic, microseconds)
so the measurement isolates the admission path, not pure-Python curve
math.

A third (untimed) burst phase offers 2x the queue cap in one wave so
the shed path actually fires and the reported shed rate is a measured
number, not a zero.

Emits ONE JSON line (bench_light schema): metric/value/unit plus the
sequential baseline, the speedup, p50/p90 admission latency and the
shed rate — the latter read back from IngestMetrics, the same counters
a production node exports.

Usage:
    python tools/bench_ingest.py [--clients 256] [--rounds 6]
        [--latency 0.002] [--trace] [--json]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.ingest import IngestPipeline, IngestShed  # noqa: E402
from cometbft_tpu.libs.metrics import Registry  # noqa: E402
from cometbft_tpu.libs.metrics_gen import IngestMetrics  # noqa: E402
from cometbft_tpu.mempool.mempool import CListMempool  # noqa: E402
from cometbft_tpu.pipeline.cache import SigCache  # noqa: E402
from cometbft_tpu.simnet.flash_crowd import (_signed,  # noqa: E402
                                             mac_backend)


class FixedLatencyBackend:
    """Verify backend stub: each DISPATCH costs `latency` seconds (the
    device round trip), verdicts come from the deterministic MAC rule.
    Batched admission pays it once per flush, sequential once per tx."""

    def __init__(self, latency_s: float):
        self.latency_s = latency_s
        self.dispatches = 0

    def __call__(self, lanes):
        self.dispatches += 1
        time.sleep(self.latency_s)
        oks, _ = mac_backend(lanes)
        return oks, "stub-device"


def _gen_txs(n: int, tag: str):
    return [_signed(hashlib.sha256(f"{tag}:{i % 64}".encode()).digest(),
                    f"{tag}{i}=v{i}".encode())
            for i in range(n)]


def _mk_pipeline(backend, cap=1 << 16):
    metrics = IngestMetrics(Registry())
    mp = CListMempool(lambda tx: (0, 1), size=1 << 20,
                      max_txs_bytes=1 << 30, cache_size=1 << 20)
    pipe = IngestPipeline(mp, cache=SigCache(1 << 17), batch=True,
                          max_pending=cap, coalesce_window_s=0.0,
                          verify_backend=backend, metrics=metrics)
    return pipe, metrics


def run(clients: int, rounds: int, latency_s: float,
        trace: bool = False) -> dict:
    from cometbft_tpu import trace as _trace
    if trace:
        _trace.enable(seed=0)
    else:
        _trace.disable()
    n = clients * rounds
    print(f"[bench_ingest] generating {n} MAC-signed txs...",
          file=sys.stderr, flush=True)

    # --- batched side ------------------------------------------------------
    backend = FixedLatencyBackend(latency_s)
    pipe, metrics = _mk_pipeline(backend)
    txs = _gen_txs(n, "b")
    t0 = time.perf_counter()
    for r in range(rounds):
        wave = [pipe.submit(tx) for tx in txs[r * clients:(r + 1) * clients]]
        pipe.flush()
        assert all(t.code == 0 for t in wave)
    batched_dt = time.perf_counter() - t0
    batched_rate = n / batched_dt
    q = pipe.latency_quantiles()

    # --- sequential side (flush per tx: width-1 batches, same stub) --------
    seq_backend = FixedLatencyBackend(latency_s)
    seq_pipe, _seq_metrics = _mk_pipeline(seq_backend)
    # bound the sequential side's wall time (~2s of stub latency is
    # plenty to measure a per-tx-dispatch rate)
    seq_n = n if latency_s <= 0 else max(1, min(n, int(2.0 / latency_s)))
    seq_txs = _gen_txs(seq_n, "s")
    t0 = time.perf_counter()
    for tx in seq_txs:
        ticket = seq_pipe.submit(tx)
        seq_pipe.flush()
        assert ticket.code == 0
    seq_dt = time.perf_counter() - t0
    seq_rate = seq_n / seq_dt

    # --- untimed burst: pin a nonzero shed rate ----------------------------
    cap = max(8, clients // 2)
    burst_backend = FixedLatencyBackend(0.0)
    burst_pipe, burst_metrics = _mk_pipeline(burst_backend, cap=cap)
    offered = 2 * cap
    for tx in _gen_txs(offered, "o"):
        try:
            burst_pipe.submit(tx)
        except IngestShed:
            pass
    burst_pipe.flush()
    shed = burst_metrics.shed.value()

    return {
        "metric": "ingest_admission_throughput",
        "value": round(batched_rate, 1),
        "unit": "tx/s",
        "backend": "cpu-stub",
        "clients": clients,
        "rounds": rounds,
        "stub_latency_s": latency_s,
        "sequential_tx_s": round(seq_rate, 1),
        "speedup_vs_sequential": round(batched_rate / seq_rate, 2),
        "p50_admission_s": round(q["p50"], 6),
        "p90_admission_s": round(q["p90"], 6),
        "batched_dispatches": backend.dispatches,
        "admitted": int(metrics.admitted.value()),
        "burst_offered": offered,
        "burst_shed": int(shed),
        "shed_rate": round(shed / offered, 3),
        "trace": trace,
        "trace_spans": int(_trace.shared_recorder().stats()["recorded"]),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=256,
                    help="txs per coalescing wave")
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--latency", type=float, default=0.002,
                    help="stub device round-trip seconds per dispatch")
    ap.add_argument("--trace", action="store_true",
                    help="enable the flight recorder for the timed run "
                         "(measures tracing-on overhead; default measures "
                         "the disabled no-op path)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    rep = run(args.clients, args.rounds, args.latency, trace=args.trace)
    print(f"[bench_ingest] batched {rep['value']} tx/s vs sequential "
          f"{rep['sequential_tx_s']} tx/s -> "
          f"{rep['speedup_vs_sequential']}x; p90 admission "
          f"{rep['p90_admission_s']}s; shed rate {rep['shed_rate']}",
          file=sys.stderr, flush=True)
    print(json.dumps(rep), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
