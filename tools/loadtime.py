"""Load-generation + latency measurement against a running node's RPC
(reference test/loadtime/: tx generator with rate control + report
aggregator; test/e2e/runner/benchmark.go:24: block-interval stats).

Usage:
    python tools/loadtime.py --rpc 127.0.0.1:26657 --rate 50 \
        --duration 10 [--connections 2] [--json]

Each tx embeds a send-timestamp nonce (the reference's loadtime payload
carries the same); latency = commit-observation time - send time,
measured by polling /tx until the hash is indexed. Prints a report with
throughput, latency quantiles, and block-interval stats.
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.rpc.client import RPCClient, RPCClientError  # noqa: E402
from cometbft_tpu.types.block import tx_hash  # noqa: E402


def generate_load(host: str, port: int, rate: float, duration: float,
                  connections: int = 1) -> dict:
    """Fire `rate` tx/s for `duration`s; return the raw send ledger."""
    sent = []  # (hash, send_monotonic)
    lock = threading.Lock()
    stop_at = time.monotonic() + duration
    interval = connections / rate

    def worker(wid: int):
        rpc = RPCClient(host, port, timeout=30)
        next_send = time.monotonic()
        while True:
            now = time.monotonic()
            if now >= stop_at:
                return
            if now < next_send:
                time.sleep(min(next_send - now, 0.05))
                continue
            next_send += interval
            tx = (f"load-{wid}-".encode() + secrets.token_hex(8).encode()
                  + b"=" + str(time.time_ns()).encode())
            try:
                r = rpc.broadcast_tx_sync(tx)
            except (RPCClientError, OSError):
                continue
            if r.get("code", 1) == 0:
                with lock:
                    sent.append((tx_hash(tx), time.time()))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(connections)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return {"sent": sent}


def await_commits(host: str, port: int, ledger: dict,
                  timeout: float = 60.0) -> list:
    """Poll the tx index until every sent tx is committed (or timeout);
    returns [(latency_seconds, height)]. Latency = committed block's
    header time - send wall time (the reference's loadtime report also
    derives latency from block timestamps, not poll observation)."""
    rpc = RPCClient(host, port, timeout=30)
    latencies = []
    pending = dict(ledger["sent"])
    block_time: dict = {}
    deadline = time.monotonic() + timeout
    while pending and time.monotonic() < deadline:
        for h, t0 in list(pending.items()):
            try:
                r = rpc.call("tx", hash=h.hex())
                height = r["height"]
                if height not in block_time:
                    t = rpc.header(height)["header"]["time"]
                    block_time[height] = t[0] + t[1] / 1e9
            except (RPCClientError, OSError):
                continue
            latencies.append((max(block_time[height] - t0, 0.0), height))
            del pending[h]
        if pending:
            time.sleep(0.1)
    return latencies


def block_interval_stats(host: str, port: int, heights) -> dict:
    """reference test/e2e/runner/benchmark.go: block time deltas over
    the load window."""
    if not heights:
        return {}
    rpc = RPCClient(host, port, timeout=30)
    lo, hi = min(heights), max(heights)
    times = {}
    for h in range(lo, hi + 1):
        hd = rpc.header(h)["header"]
        times[h] = hd["time"][0] + hd["time"][1] / 1e9
    deltas = [times[h + 1] - times[h] for h in range(lo, hi)]
    if not deltas:
        return {"blocks": 1}
    return {"blocks": hi - lo + 1,
            "interval_avg_s": sum(deltas) / len(deltas),
            "interval_max_s": max(deltas)}


def quantile(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def run(host: str, port: int, rate: float, duration: float,
        connections: int) -> dict:
    t0 = time.monotonic()
    ledger = generate_load(host, port, rate, duration, connections)
    results = await_commits(host, port, ledger)
    wall = time.monotonic() - t0
    lats = [lat for lat, _h in results]
    heights = [h for _lat, h in results]
    return {
        "txs_sent": len(ledger["sent"]),
        "txs_committed": len(results),
        "throughput_tx_s": round(len(results) / wall, 2) if wall else 0,
        "latency_p50_s": round(quantile(lats, 0.50), 4),
        "latency_p90_s": round(quantile(lats, 0.90), 4),
        "latency_max_s": round(quantile(lats, 1.0), 4),
        **block_interval_stats(host, port, heights),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rpc", default="127.0.0.1:26657")
    ap.add_argument("--rate", type=float, default=20.0)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--connections", type=int, default=1)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    host, _, port = args.rpc.rpartition(":")
    report = run(host or "127.0.0.1", int(port), args.rate,
                 args.duration, args.connections)
    if args.json:
        print(json.dumps(report))
    else:
        for k, v in report.items():
            print(f"{k:20s} {v}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
