#!/bin/bash
# TPU tunnel watcher: probe the single-client axon tunnel every ~4 min
# in a throwaway subprocess; the moment it answers, run the highest-value
# measurements IMMEDIATELY (the alive window can be short):
#   1. bench.py           -> BENCH_r05_live.json   (headline number)
#   2. tools/ab_pallas.py -> docs/ab_r05.log       (XLA vs pallas A/B)
#   3. TILE sweep         -> docs/ab_r05_sweep.log (256/1024/2048)
# Worst-case hold time once alive: ~1h bench + ~45min A/B + ~2h sweep.
# All measurement runs are strictly sequential — the tunnel is
# single-client; a second concurrent process blocks forever and killing
# it can wedge the server side for hours (docs/PERF.md).
set -u
cd /root/repo
LOG=/root/repo/tunnel_watch.log
# after this wall-clock deadline, capture ONLY the bench (the A/B and
# sweep would hold the single-client tunnel for hours and could block
# the round driver's own bench run at round end)
EXTRAS_DEADLINE=${WATCH_EXTRAS_DEADLINE:-$(( $(date +%s) + 4 * 3600 ))}
echo "$(date -u +%F' '%H:%M:%S) watcher start (extras until "\
"$(date -u -d @$EXTRAS_DEADLINE +%H:%M))" >> "$LOG"
for i in $(seq 1 200); do
  out=$(timeout 75 python -c "
import sys; sys.path.insert(0, '/root/repo')
from cometbft_tpu.libs.jax_cache import enable_compile_cache
enable_compile_cache()
import jax
print('ALIVE', jax.devices()[0].platform, flush=True)
" 2>/dev/null)
  if echo "$out" | grep -q ALIVE; then
    echo "$(date -u +%F' '%H:%M:%S) tunnel ALIVE ($out) — measuring" >> "$LOG"
    BENCH_TOTAL_TIMEOUT=3600 timeout 3900 python bench.py \
      > /root/repo/BENCH_r05_live.json 2>> "$LOG"
    rc=$?
    echo "$(date -u +%F' '%H:%M:%S) bench rc=$rc: $(cat /root/repo/BENCH_r05_live.json)" >> "$LOG"
    # gate on START + WORST-CASE duration: a stage must FINISH before
    # the deadline, not merely start before it
    if [ "$(( $(date +%s) + 2700 ))" -gt "$EXTRAS_DEADLINE" ]; then
      echo "$(date -u +%F' '%H:%M:%S) A/B cannot finish before the "\
"extras deadline — leaving the tunnel free for the driver" >> "$LOG"
      exit 0
    fi
    AB_N=8192 timeout 2700 python tools/ab_pallas.py \
      > /root/repo/docs/ab_r05.log 2>&1
    echo "$(date -u +%F' '%H:%M:%S) ab_pallas rc=$?" >> "$LOG"
    if [ "$(( $(date +%s) + 7500 ))" -gt "$EXTRAS_DEADLINE" ]; then
      echo "$(date -u +%F' '%H:%M:%S) sweep cannot finish before the "\
"extras deadline — skipping" >> "$LOG"
      exit 0
    fi
    AB_N=8192 AB_SWEEP=256,1024,2048 timeout 7500 python tools/ab_pallas.py \
      > /root/repo/docs/ab_r05_sweep.log 2>&1
    echo "$(date -u +%F' '%H:%M:%S) tile sweep rc=$? — watcher done" >> "$LOG"
    exit 0
  fi
  echo "$(date -u +%F' '%H:%M:%S) probe $i: wedged" >> "$LOG"
  sleep 240
done
echo "$(date -u +%F' '%H:%M:%S) watcher gave up (no revival)" >> "$LOG"
exit 1
