"""Project symbol table + call graph — the whole-program layer under
staticcheck's v2 rule families (lock-order, verdict-taint,
kernel-discipline).

The PR-4 linter was strictly per-file: every rule saw one `ast` tree
and that file's import aliases. The bugs that now matter (a lock taken
in one method while a helper in another file takes the reverse pair;
an un-canaried device verdict crossing three modules before it reaches
`mempool.check_tx`) are invisible at that granularity. This module
builds, once per full-tree run:

  * a MODULE map        (repo path <-> dotted module name),
  * a SYMBOL TABLE      (module-level functions, classes, methods),
  * LIGHT TYPE FACTS    (parameter/return annotations that name project
                         classes; `self.x = <ClassCall>()` attribute
                         types; `self._backend = param or module_fn`
                         callable attributes),
  * a CALL RESOLVER     (name calls, module-attribute calls,
                         `self.method()`, typed-receiver method calls,
                         `len(obj)` -> `__len__`), with a conservative
                         DYNAMIC fallback (`by_method_name`) for
                         receivers nothing resolves — callers opt into
                         it per rule, because for some analyses
                         conservative means MORE edges (lock cycles)
                         and for others it means FEWER assumptions
                         (taint treats unresolved returns as clean and
                         leans on the pinned seam tests instead).

Everything is stdlib `ast`; resolution is best-effort and documented
as such in docs/STATICCHECK.md — the rules built on top are tuned so
that unresolved things fail SAFE for their particular question.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from . import FileCtx


def module_name(path: str) -> str:
    """Repo-relative posix path -> dotted module name."""
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class FuncInfo:
    """One function or method definition."""

    __slots__ = ("qualname", "module", "path", "cls", "name", "node",
                 "lineno", "ret_types")

    def __init__(self, qualname: str, module: str, path: str,
                 cls: Optional[str], name: str, node: ast.AST):
        self.qualname = qualname      # mod.fn or mod.Class.fn
        self.module = module
        self.path = path
        self.cls = cls                # class qualname (mod.Class) or None
        self.name = name
        self.node = node
        self.lineno = getattr(node, "lineno", 1)
        self.ret_types: Set[str] = set()   # project-class qualnames

    @property
    def is_method(self) -> bool:
        return self.cls is not None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<FuncInfo {self.qualname}>"


class ClassInfo:
    __slots__ = ("qualname", "module", "path", "name", "node", "bases",
                 "methods", "attr_types", "attr_callables")

    def __init__(self, qualname: str, module: str, path: str,
                 name: str, node: ast.ClassDef):
        self.qualname = qualname
        self.module = module
        self.path = path
        self.name = name
        self.node = node
        self.bases: List[str] = []             # resolved class qualnames
        self.methods: Dict[str, FuncInfo] = {}
        # self.<attr> -> set of project-class qualnames it may hold
        self.attr_types: Dict[str, Set[str]] = {}
        # self.<attr> -> set of project FUNCTION qualnames it may hold
        # (the `self._backend = verify_backend or device_or_cpu_backend`
        # plugin-seam shape)
        self.attr_callables: Dict[str, Set[str]] = {}


class Project:
    """Symbol table + call graph over one full-tree scan's FileCtx map."""

    def __init__(self, root: str, ctxs: Dict[str, FileCtx]):
        self.root = root
        self.ctxs = ctxs
        self.modules: Dict[str, str] = {}          # dotted module -> path
        self.packages: Set[str] = set()
        self.functions: Dict[str, FuncInfo] = {}   # qualname -> info
        self.classes: Dict[str, ClassInfo] = {}    # qualname -> info
        # per-path import resolution: local name -> dotted target (module
        # OR symbol); built with RELATIVE import support, which FileCtx's
        # own alias maps deliberately skip
        self.imports: Dict[str, Dict[str, str]] = {}
        self.by_method_name: Dict[str, List[str]] = {}
        self._build()

    # --- construction -----------------------------------------------------

    def _build(self) -> None:
        for path in self.ctxs:
            mod = module_name(path)
            self.modules[mod] = path
            parts = mod.split(".")
            for i in range(1, len(parts)):
                self.packages.add(".".join(parts[:i]))
        for path, ctx in self.ctxs.items():
            self._index_file(path, ctx)
        # second pass: facts that need the full symbol table (base-class
        # resolution, annotation types, attribute types/callables)
        for cls in self.classes.values():
            self._resolve_bases(cls)
        for fn in self.functions.values():
            fn.ret_types = self.annotation_types(
                getattr(fn.node, "returns", None), fn.path)
        for cls in self.classes.values():
            self._infer_attr_facts(cls)

    def _index_file(self, path: str, ctx: FileCtx) -> None:
        mod = module_name(path)
        imports: Dict[str, str] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom):
                base = self._import_base(mod, path, node)
                if base is None:
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    target = f"{base}.{a.name}" if base else a.name
                    imports[a.asname or a.name] = target
        self.imports[path] = imports

        for stmt in ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{mod}.{stmt.name}"
                self._add_function(FuncInfo(qn, mod, path, None,
                                            stmt.name, stmt))
            elif isinstance(stmt, ast.ClassDef):
                cqn = f"{mod}.{stmt.name}"
                cls = ClassInfo(cqn, mod, path, stmt.name, stmt)
                self.classes[cqn] = cls
                for item in stmt.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = FuncInfo(f"{cqn}.{item.name}", mod, path,
                                      cqn, item.name, item)
                        cls.methods[item.name] = fi
                        self._add_function(fi)

    def _add_function(self, fi: FuncInfo) -> None:
        self.functions[fi.qualname] = fi
        self.by_method_name.setdefault(fi.name, []).append(fi.qualname)

    def _import_base(self, mod: str, path: str,
                     node: ast.ImportFrom) -> Optional[str]:
        """Dotted base the imported names hang off ('' for a bare
        `from . import x` at a repo-root package)."""
        if node.level == 0:
            return node.module or ""
        # relative: drop `level` trailing components of the importing
        # module (packages import relative to themselves, modules
        # relative to their parent — __init__ paths already collapsed
        # by module_name, so a module drops level components and a
        # package drops level - 1)
        parts = mod.split(".")
        is_pkg = path.endswith("__init__.py")
        drop = node.level - (1 if is_pkg else 0)
        if drop >= len(parts) and not (drop == len(parts) and is_pkg):
            base_parts: List[str] = []
        else:
            base_parts = parts[: len(parts) - drop] if drop else parts
        base = ".".join(base_parts)
        if node.module:
            base = f"{base}.{node.module}" if base else node.module
        return base

    def _resolve_bases(self, cls: ClassInfo) -> None:
        for b in cls.node.bases:
            qn = self._symbol_for_expr(b, cls.path)
            if qn in self.classes:
                cls.bases.append(qn)

    # --- symbol lookup ----------------------------------------------------

    def _symbol_for_expr(self, node: ast.AST, path: str) -> Optional[str]:
        """Resolve a Name / dotted-Attribute EXPRESSION to a project
        symbol's qualname (function, class, or module) via this file's
        imports — no local-scope awareness (callers overlay that)."""
        if isinstance(node, ast.Name):
            target = self.imports.get(path, {}).get(node.id)
            if target is None:
                # module-local symbol?
                mod = module_name(path)
                local = f"{mod}.{node.id}"
                if local in self.functions or local in self.classes:
                    return local
                return None
            return target
        if isinstance(node, ast.Attribute):
            base = self._symbol_for_expr(node.value, path)
            if base is None:
                return None
            return f"{base}.{node.attr}"
        return None

    # --- annotations and expression types ---------------------------------

    def annotation_types(self, node: Optional[ast.AST],
                         path: str) -> Set[str]:
        """Project-class qualnames named by an annotation (through
        Optional[...] / Union[...] / \"quoted\" forms)."""
        out: Set[str] = set()
        if node is None:
            return out
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            try:
                node = ast.parse(node.value, mode="eval").body
            except SyntaxError:
                return out
        if isinstance(node, ast.Subscript):
            sl = node.slice
            elts = sl.elts if isinstance(sl, ast.Tuple) else [sl]
            for e in elts:
                out |= self.annotation_types(e, path)
            return out
        if isinstance(node, (ast.Name, ast.Attribute)):
            qn = self._symbol_for_expr(node, path)
            if qn in self.classes:
                out.add(qn)
            elif isinstance(node, ast.Name):
                # unqualified name matching a unique project class (the
                # common `client: DeviceClient` in the defining module)
                mod = module_name(path)
                local = f"{mod}.{node.id}"
                if local in self.classes:
                    out.add(local)
        return out

    def expr_types(self, node: ast.AST, func: FuncInfo,
                   env: Optional[Dict[str, Set[str]]] = None) -> Set[str]:
        """May-types (project-class qualnames) of an expression inside
        `func`. `env` carries local-variable types the caller tracked."""
        env = env or {}
        if isinstance(node, ast.Name):
            if node.id == "self" and func.cls:
                return {func.cls}
            if node.id in env:
                return set(env[node.id])
            ann = self._param_annotation(func, node.id)
            if ann is not None:
                return self.annotation_types(ann, func.path)
            return set()
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self" \
                    and func.cls:
                out: Set[str] = set()
                for c in self._mro(func.cls):
                    out |= self.classes[c].attr_types.get(node.attr, set())
                return out
            return set()
        if isinstance(node, ast.Call):
            return self.call_return_types(node, func, env)
        if isinstance(node, ast.BoolOp):
            out = set()
            for v in node.values:
                out |= self.expr_types(v, func, env)
            return out
        if isinstance(node, ast.IfExp):
            return (self.expr_types(node.body, func, env)
                    | self.expr_types(node.orelse, func, env))
        if isinstance(node, ast.NamedExpr):
            return self.expr_types(node.value, func, env)
        if isinstance(node, ast.Await):
            return self.expr_types(node.value, func, env)
        return set()

    def call_return_types(self, node: ast.Call, func: FuncInfo,
                          env: Optional[Dict[str, Set[str]]] = None
                          ) -> Set[str]:
        out: Set[str] = set()
        for qn in self.resolve_call(func, node, env):
            if qn in self.classes:
                out.add(qn)                      # constructor call
            elif qn in self.functions:
                out |= self.functions[qn].ret_types
        return out

    def _param_annotation(self, func: FuncInfo,
                          name: str) -> Optional[ast.AST]:
        args = getattr(func.node, "args", None)
        if args is None:
            return None
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a.arg == name:
                return a.annotation
        return None

    def _mro(self, cqn: str) -> List[str]:
        """Linearized project-local ancestry (self first; best-effort)."""
        out: List[str] = []
        stack = [cqn]
        while stack:
            c = stack.pop(0)
            if c in out or c not in self.classes:
                continue
            out.append(c)
            stack.extend(self.classes[c].bases)
        return out

    def lookup_method(self, cqn: str, name: str) -> Optional[FuncInfo]:
        for c in self._mro(cqn):
            m = self.classes[c].methods.get(name)
            if m is not None:
                return m
        return None

    # --- attribute facts --------------------------------------------------

    def _infer_attr_facts(self, cls: ClassInfo) -> None:
        for m in cls.methods.values():
            for node in ast.walk(m.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign):
                    targets, value = [node.target], node.value
                    if isinstance(node.target, ast.Attribute) and \
                            isinstance(node.target.value, ast.Name) and \
                            node.target.value.id == "self":
                        for t in self.annotation_types(node.annotation,
                                                       cls.path):
                            cls.attr_types.setdefault(
                                node.target.attr, set()).add(t)
                if value is None:
                    continue
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    for ty in self.expr_types(value, m):
                        cls.attr_types.setdefault(t.attr, set()).add(ty)
                    for fn in self._callable_targets(value, m):
                        cls.attr_callables.setdefault(
                            t.attr, set()).add(fn)

    def _callable_targets(self, node: ast.AST,
                          func: FuncInfo) -> Set[str]:
        """Function qualnames an expression may evaluate to (plugin
        seams: `verify_backend or device_or_cpu_backend`)."""
        if isinstance(node, (ast.Name, ast.Attribute)):
            qn = self._symbol_for_expr(node, func.path)
            return {qn} if qn in self.functions else set()
        if isinstance(node, ast.BoolOp):
            out: Set[str] = set()
            for v in node.values:
                out |= self._callable_targets(v, func)
            return out
        if isinstance(node, ast.IfExp):
            return (self._callable_targets(node.body, func)
                    | self._callable_targets(node.orelse, func))
        return set()

    # --- call resolution --------------------------------------------------

    def resolve_call(self, func: FuncInfo, node: ast.Call,
                     env: Optional[Dict[str, Set[str]]] = None,
                     dynamic: bool = False) -> List[str]:
        """Qualnames a call may land on: functions, methods, or CLASS
        qualnames (constructor calls). `env` supplies local-variable
        types. `dynamic=True` adds the same-method-name fallback for
        attribute calls nothing else resolved — conservative
        over-approximation, per-rule opt-in."""
        fn = node.func
        out: List[str] = []
        if isinstance(fn, ast.Name):
            if fn.id == "len" and node.args:
                for t in self.expr_types(node.args[0], func, env):
                    m = self.lookup_method(t, "__len__")
                    if m is not None:
                        out.append(m.qualname)
                return out
            qn = self._local_or_import(fn.id, func)
            if qn is not None:
                out.append(qn)
            return out
        if isinstance(fn, ast.Attribute):
            # self.method(...)
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                    and func.cls:
                m = self.lookup_method(func.cls, fn.attr)
                if m is not None:
                    return [m.qualname]
                for c in self._mro(func.cls):
                    for target in self.classes[c].attr_callables.get(
                            fn.attr, ()):
                        out.append(target)
                if out:
                    return sorted(set(out))
            # typed receiver (local var, annotated param, self-attr)
            for t in sorted(self.expr_types(fn.value, func, env)):
                m = self.lookup_method(t, fn.attr)
                if m is not None:
                    out.append(m.qualname)
            if out:
                return sorted(set(out))
            # module attribute:  alias.fn(...) / pkg.mod.fn(...)
            qn = self._symbol_for_expr(fn, func.path)
            if qn in self.functions or qn in self.classes:
                return [qn]
            if dynamic:
                return sorted(set(self.by_method_name.get(fn.attr, ())))
        return out

    def _local_or_import(self, name: str,
                         func: FuncInfo) -> Optional[str]:
        # a def nested in the same module scope, a classmate at module
        # level, or a from-import of a project symbol
        mod = func.module
        for cand in (f"{mod}.{name}",):
            if cand in self.functions or cand in self.classes:
                return cand
        target = self.imports.get(func.path, {}).get(name)
        if target and (target in self.functions
                       or target in self.classes):
            return target
        return None

    # --- convenience ------------------------------------------------------

    def functions_in(self, path_prefix: str) -> List[FuncInfo]:
        return [f for f in self.functions.values()
                if f.path == path_prefix
                or f.path.startswith(path_prefix.rstrip("/") + "/")]

    def iter_calls(self, func: FuncInfo) -> Iterable[ast.Call]:
        for node in ast.walk(func.node):
            if isinstance(node, ast.Call):
                yield node


def build_project(root: str, ctxs: Dict[str, FileCtx]) -> Project:
    return Project(root, ctxs)
