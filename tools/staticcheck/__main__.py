"""CLI for the project-invariant linter.

    python -m tools.staticcheck                # full tree, exit 1 on findings
    python -m tools.staticcheck --list-rules
    python -m tools.staticcheck --fix-baseline # rewrite baseline to now
    python -m tools.staticcheck cometbft_tpu/p2p/switch.py  # subset
                                               # (tree rules skipped)

Exit codes: 0 clean, 1 findings or stale baseline entries, 2 usage.
"""

from __future__ import annotations

import argparse
import os
import posixpath
import sys

from . import (default_baseline_path, load_baseline, run_checks,
               write_baseline)
from .rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.staticcheck",
        description="AST-driven invariant linter "
                    "(docs/STATICCHECK.md)")
    ap.add_argument("paths", nargs="*",
                    help="restrict to these files (tree rules skipped)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: parent of tools/)")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="rewrite baseline.txt to the current finding "
                         "set (growth is visible in review — justify "
                         "every added entry)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:14s} {cls.doc}")
        return 0

    if args.paths:
        # subset lint: per-file rules only, no baseline interaction
        # (fingerprints of unscanned files would all read as stale).
        # Relative args resolve against --root, NOT the cwd — running
        # from elsewhere must not silently filter everything away.
        wanted = []
        for p in args.paths:
            rel = (os.path.relpath(os.path.abspath(p), root)
                   if os.path.isabs(p) else p)
            # normalize ./x, a/../a/x, trailing / — the scan matches
            # by string prefix against normalized repo-relative paths
            rel = posixpath.normpath(rel.replace(os.sep, "/"))
            if rel.startswith("../"):
                print(f"path outside --root: {p}", file=sys.stderr)
                return 2
            if not os.path.exists(os.path.join(root, rel)):
                print(f"no such file or directory under root: {rel}",
                      file=sys.stderr)
                return 2
            wanted.append(rel)
        res = run_checks(root, baseline_path=os.devnull,
                         tree_rules=False, only_paths=wanted)
        res.stale_baseline = []
    else:
        res = run_checks(root)

    if args.fix_baseline:
        if args.paths:
            print("--fix-baseline requires a full-tree run",
                  file=sys.stderr)
            return 2
        bl_path = default_baseline_path(root)
        old = load_baseline(bl_path)
        n = write_baseline(bl_path, res.findings + res.baselined, old)
        print(f"baseline rewritten: {n} entries "
              f"({len(res.findings)} new, {len(res.stale_baseline)} "
              f"stale removed)")
        return 0

    for f in res.findings:
        print(f.render())
    for fp in res.stale_baseline:
        print(f"stale baseline entry (finding gone — delete the "
              f"line): {fp}")
    n_checked = f"{len(ALL_RULES)} rules"
    if res.ok:
        print(f"staticcheck: clean ({n_checked}, "
              f"{res.suppressed} pragma-allowed, "
              f"{len(res.baselined)} baselined)")
        return 0
    print(f"staticcheck: {len(res.findings)} finding(s), "
          f"{len(res.stale_baseline)} stale baseline entr(y/ies) — "
          f"see docs/STATICCHECK.md", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
