"""CLI for the project-invariant linter.

    python -m tools.staticcheck                # full tree, exit 1 on findings
    python -m tools.staticcheck --list-rules
    python -m tools.staticcheck --list-pragmas # allow() inventory
    python -m tools.staticcheck --format json  # machine-readable + timings
    python -m tools.staticcheck --format sarif # SARIF 2.1.0 (code scanning)
    python -m tools.staticcheck --rule lock-order --rule guarded-by
    python -m tools.staticcheck --fix-baseline # rewrite baseline to now
    python -m tools.staticcheck cometbft_tpu/p2p/switch.py  # subset
                                               # (tree rules skipped)

Exit codes: 0 clean, 1 findings or stale baseline entries, 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import posixpath
import sys

from . import (default_baseline_path, load_baseline, run_checks,
               write_baseline)
from .rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.staticcheck",
        description="AST-driven invariant linter "
                    "(docs/STATICCHECK.md)")
    ap.add_argument("paths", nargs="*",
                    help="restrict to these files (tree rules skipped)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: parent of tools/)")
    ap.add_argument("--fix-baseline", action="store_true",
                    help="rewrite baseline.txt to the current finding "
                         "set (growth is visible in review — justify "
                         "every added entry)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-pragmas", action="store_true",
                    help="inventory every `# staticcheck: allow(...)` "
                         "in the tree (path:line rule | source line)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="NAME",
                    help="run only this rule (repeatable) — bisect a "
                         "slow or regressing rule; baseline entries "
                         "for other rules are ignored, not stale")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="json: findings + per-rule wall-time for "
                         "run_suite/CI attribution; sarif: SARIF "
                         "2.1.0 for code-scanning upload")
    args = ap.parse_args(argv)

    root = os.path.abspath(args.root) if args.root else os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.name:17s} {cls.doc}")
        return 0

    rules = None
    if args.rule:
        by_name = {cls.name: cls for cls in ALL_RULES}
        unknown = [r for r in args.rule if r not in by_name]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(see --list-rules)", file=sys.stderr)
            return 2
        rules = [by_name[r] for r in args.rule]

    if args.paths:
        # subset lint: per-file rules only, no baseline interaction
        # (fingerprints of unscanned files would all read as stale).
        # Relative args resolve against --root, NOT the cwd — running
        # from elsewhere must not silently filter everything away.
        wanted = []
        for p in args.paths:
            rel = (os.path.relpath(os.path.abspath(p), root)
                   if os.path.isabs(p) else p)
            # normalize ./x, a/../a/x, trailing / — the scan matches
            # by string prefix against normalized repo-relative paths
            rel = posixpath.normpath(rel.replace(os.sep, "/"))
            if rel.startswith("../"):
                print(f"path outside --root: {p}", file=sys.stderr)
                return 2
            if not os.path.exists(os.path.join(root, rel)):
                print(f"no such file or directory under root: {rel}",
                      file=sys.stderr)
                return 2
            wanted.append(rel)
        res = run_checks(root, baseline_path=os.devnull, rules=rules,
                         tree_rules=False, only_paths=wanted)
        res.stale_baseline = []
    else:
        res = run_checks(root, rules=rules,
                         baseline_path=None if rules is None
                         else default_baseline_path(root))
        if rules is not None:
            # a --rule run never judged the other rules' baseline
            # entries; only entries belonging to the active rules can
            # be stale
            active = {cls.name for cls in rules}
            res.stale_baseline = [
                fp for fp in res.stale_baseline
                if fp.split("|", 1)[0] in active]

    if args.list_pragmas:
        def _src(path: str, line: int) -> str:
            try:
                with open(os.path.join(root, path),
                          encoding="utf-8") as fh:
                    return fh.read().splitlines()[line - 1].strip()
            except (OSError, IndexError):
                return ""
        for path, line, rule_name in res.pragma_inventory:
            print(f"{path}:{line}: allow({rule_name}) | "
                  f"{_src(path, line)}")
        for path, line, var in res.assume_inventory:
            print(f"{path}:{line}: assume({var}, ...) | "
                  f"{_src(path, line)}")
        print(f"{len(res.pragma_inventory)} pragma(s), "
              f"{len(res.assume_inventory)} assume(s)")
        return 0

    if args.fix_baseline:
        if args.paths or rules is not None:
            print("--fix-baseline requires a full-tree, all-rules run",
                  file=sys.stderr)
            return 2
        bl_path = default_baseline_path(root)
        old = load_baseline(bl_path)
        n = write_baseline(bl_path, res.findings + res.baselined, old)
        print(f"baseline rewritten: {n} entries "
              f"({len(res.findings)} new, {len(res.stale_baseline)} "
              f"stale removed)")
        return 0

    if args.format == "json":
        print(json.dumps(res.to_json(), indent=1))
        return 0 if res.ok else 1

    if args.format == "sarif":
        print(json.dumps(_to_sarif(res, rules or ALL_RULES), indent=1))
        return 0 if res.ok else 1

    for f in res.findings:
        print(f.render())
    for fp in res.stale_baseline:
        print(f"stale baseline entry (finding gone — delete the "
              f"line): {fp}")
    n_checked = (f"{len(rules)} of {len(ALL_RULES)} rules" if rules
                 else f"{len(ALL_RULES)} rules")
    if res.ok:
        slowest = max(res.rule_seconds.items(),
                      key=lambda kv: kv[1], default=("-", 0.0))
        print(f"staticcheck: clean ({n_checked}, "
              f"{res.suppressed} pragma-allowed, "
              f"{len(res.baselined)} baselined, "
              f"{sum(res.rule_seconds.values()):.1f}s total, "
              f"slowest rule {slowest[0]} {slowest[1]:.1f}s)")
        return 0
    print(f"staticcheck: {len(res.findings)} finding(s), "
          f"{len(res.stale_baseline)} stale baseline entr(y/ies) — "
          f"see docs/STATICCHECK.md", file=sys.stderr)
    return 1


def _to_sarif(res, rule_classes) -> dict:
    """SARIF 2.1.0 document: one run, one driver, the active rules as
    reportingDescriptors, each finding a `result` with a stable
    partialFingerprint (the baseline fingerprint, so code-scanning
    dedup agrees with the baseline's identity notion)."""
    rules_meta = [{
        "id": cls.name,
        "shortDescription": {"text": cls.doc},
        "helpUri": "docs/STATICCHECK.md",
    } for cls in rule_classes]
    results = []
    for f in res.findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "partialFingerprints": {
                "staticcheck/v1": f.fingerprint(),
            },
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "staticcheck",
                "informationUri": "docs/STATICCHECK.md",
                "rules": rules_meta,
            }},
            "results": results,
            "invocations": [{
                "executionSuccessful": res.ok,
                "properties": {
                    "ruleSeconds": {k: round(v, 4) for k, v in
                                    sorted(res.rule_seconds.items())},
                    "suppressed": res.suppressed,
                    "baselined": len(res.baselined),
                },
            }],
        }],
    }


if __name__ == "__main__":
    sys.exit(main())
