"""resource-lifecycle — futures, locks, and file handles must reach
their terminal operation on every path.

Three obligations, one rule (docs/STATICCHECK.md §v3):

FUTURE DRAIN (path-sensitive, intraprocedural). A `*Future` produced
by a `.submit(...)` call or a `SomethingFuture(...)` constructor is an
obligation: on every exit path out of the producing function the bound
name must have been USED — returned, enqueued, stored, completed
(`set_result`/`set_exception`/`cancel`/`result`), or handed to another
call (the watchdog / cpu_drain seams are ordinary argument sinks here).
The analysis walks the function body with an abstract "live
undischarged futures" set; `except` arms restart from the state at
`try` entry because any statement of the body — including the one that
would have discharged the future — may not have run. A `raise` or
`return` while an obligation is live is the finding. This is exactly
the `MeshExecutor.submit()` queue-full shape: the future exists, the
enqueue failed, and the error path walks away from it.

SHUTDOWN DRAIN (class-structural). A class whose `submit()` enqueues
its futures into a `self.<q>` queue owns every future in that queue:
its `close()` must fail or drain the queued-but-undispatched items
(`get_nowait` loop + `set_exception`/`cancel`) — otherwise a caller
blocked in `result()` with no timeout hangs on work that will never
run. Flagged when `close()` never touches the queue attribute with a
draining operation.

LOCK DISCIPLINE (lexical). `.acquire()` on a lock-named receiver
(`*lock*`, `*mutex*`, `_lk`) must sit inside a `try` whose `finally`
releases the same receiver, or be replaced by `with`. Deliberate
exported lock()/unlock() pair seams carry an allow() pragma with the
justification inline.

RAW open() (lexical). Builtin `open()` / `os.fdopen()` outside a
`with` item leaks the descriptor on any exception between open and
close. `libs/faultio.py` is the sanctioned seam (it IS the managed
wrapper); the crash-consistent trees are already forced through it by
raw-file-io.

Everything here is best-effort over `ast` and tuned to fail safe for
its question: an unresolved call target counts as a USE of its
argument futures (fewer false leaks), and only name-bound futures are
tracked (an expression-statement `.submit(...)` whose result is
dropped on the floor is flagged directly).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import FileCtx, Finding

_FUTURE_METHODS = {"result", "cancel", "exception", "set_result",
                   "set_exception", "add_done_callback"}
_DRAIN_OPS = {"get_nowait", "set_exception", "cancel", "join_and_fail"}
_LOCK_HINTS = ("lock", "mutex", "_lk")

# the managed-file seam itself opens raw by design
_OPEN_EXEMPT_PATHS = ("cometbft_tpu/libs/faultio.py",)


def _recv_text(node: ast.AST) -> Optional[str]:
    """Dotted receiver text for `a.b.c` shapes, None for anything
    dynamic."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _recv_text(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _own_statements(root: ast.AST):
    """Statement walk that never descends into nested defs/lambdas —
    a closure's obligations belong to whoever calls it."""
    for node in ast.iter_child_nodes(root):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        yield from _own_statements(node)


class _FutureLeakScan:
    """One function body: track names bound to fresh futures and flag
    exit paths that abandon them."""

    def __init__(self, rule, ctx: FileCtx, func, project, emit):
        self.rule = rule
        self.ctx = ctx
        self.func = func
        self.project = project
        self.emit = emit
        self.binds: Dict[str, int] = {}  # name -> binding line

    # -- producer / use classification ----------------------------------

    def _is_future_call(self, call: ast.Call) -> bool:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr == "submit":
            return True
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        return bool(name) and name.endswith("Future")

    def _uses(self, node: ast.AST) -> Set[str]:
        """Names loaded anywhere under `node` (nested defs included —
        capturing a future in a closure is a handoff)."""
        out: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.add(n.id)
        return out

    # -- abstract execution ---------------------------------------------

    def run(self) -> None:
        self.exec_block(self.func.node.body, set())

    def _leak(self, node: ast.AST, live: Set[str], why: str) -> None:
        for name in sorted(live):
            self.emit(self.ctx.finding(
                self.rule.name, node,
                f"future '{name}' (bound line {self.binds[name]}) is "
                f"abandoned on this {why} path — complete it "
                f"(set_exception/cancel) or hand it off before "
                f"leaving; a caller blocked in result() would hang"))

    def exec_block(self, body: List[ast.stmt],
                   live: Set[str]) -> Tuple[Set[str], bool]:
        """Returns (live set at fall-through, reachable) — reachable
        False when every path already exited."""
        for stmt in body:
            live, reachable = self.exec_stmt(stmt, live)
            if not reachable:
                return live, False
        return live, True

    def exec_stmt(self, stmt: ast.stmt,
                  live: Set[str]) -> Tuple[Set[str], bool]:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Call) \
                and self._is_future_call(stmt.value):
            live = live - self._uses(stmt.value)
            name = stmt.targets[0].id
            self.binds[name] = stmt.lineno
            return live | {name}, True
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call) \
                and self._is_future_call(stmt.value) \
                and isinstance(stmt.value.func, ast.Attribute) \
                and stmt.value.func.attr == "submit":
            # discarded submit: the future is born un-owned
            self.emit(self.ctx.finding(
                self.rule.name, stmt,
                "submit() result discarded — the returned future is "
                "the only handle to this dispatch; bind and drain it "
                "(or use the blocking verify seam)"))
            return live - self._uses(stmt), True
        if isinstance(stmt, (ast.Return, ast.Raise)):
            remaining = live - self._uses(stmt)
            if remaining:
                self._leak(stmt, remaining,
                           "raise" if isinstance(stmt, ast.Raise)
                           else "return")
            return set(), False
        if isinstance(stmt, ast.If):
            after_test = live - self._uses(stmt.test)
            l1, r1 = self.exec_block(stmt.body, set(after_test))
            l2, r2 = self.exec_block(stmt.orelse, set(after_test))
            if not (r1 or r2):
                return set(), False
            return ((l1 if r1 else set()) | (l2 if r2 else set()),
                    True)
        if isinstance(stmt, (ast.While, ast.For)):
            head = (stmt.test if isinstance(stmt, ast.While)
                    else stmt.iter)
            live = live - self._uses(head)
            l1, _ = self.exec_block(stmt.body, set(live))
            # may-leak join: zero iterations keeps `live`, one-or-more
            # ends at l1 (which may have minted new obligations)
            after = live | l1
            l2, r2 = self.exec_block(stmt.orelse, set(after))
            return (l2 if r2 else after), True
        if isinstance(stmt, ast.Try):
            entry = set(live)
            lb, rb = self.exec_block(stmt.body, set(live))
            outs: List[Set[str]] = []
            any_reach = False
            if rb:
                le, re_ = self.exec_block(stmt.orelse, set(lb))
                if re_:
                    outs.append(le)
                    any_reach = True
            for h in stmt.handlers:
                # the body may have failed BEFORE the discharging use
                # ran: the handler path owes everything owed at entry
                lh, rh = self.exec_block(h.body, set(entry))
                if rh:
                    outs.append(lh)
                    any_reach = True
            if stmt.finalbody:
                merged: Set[str] = set()
                for o in outs:
                    merged |= o
                if not outs:
                    merged = entry
                lf, rf = self.exec_block(stmt.finalbody, merged)
                if not rf:
                    return set(), False
                outs = [lf & o for o in outs] if outs else [lf]
            if not any_reach:
                # finally ran (or there was none) but every arm exited
                return set(), False
            out: Set[str] = set()
            for o in outs:
                out |= o
            return out, True
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                live = live - self._uses(item.context_expr)
            return self.exec_block(stmt.body, live)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # capturing a future inside a nested def is a handoff
            return live - self._uses(stmt), True
        # generic statement: any mention is a use/handoff
        return live - self._uses(stmt), True


class ResourceLifecycleRule:
    name = "resource-lifecycle"
    doc = ("a future from submit() abandoned on an exit path, a "
           "submit-queue close() that never fails queued futures, a "
           "lock.acquire() without with/try-finally release(), or a "
           "raw open() outside a context manager "
           "(docs/STATICCHECK.md §v3)")
    roots: Tuple[str, ...] = ("cometbft_tpu",)
    exempt: frozenset = frozenset()
    tree_rule = True
    needs_project = True

    def __init__(self):
        self.used_pragmas: Set[Tuple[str, int, str]] = set()

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return any(path == top or path.startswith(top + "/")
                   for top in self.roots)

    def check(self, ctx: FileCtx):
        return ()

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        if project is None:
            return
        findings: List[Finding] = []
        for f in project.functions.values():
            if not self.applies_to(f.path):
                continue
            ctx = project.ctxs.get(f.path)
            if ctx is None:
                continue
            _FutureLeakScan(self, ctx, f, project,
                            findings.append).run()
            self._scan_locks(ctx, f, findings.append)
            self._scan_opens(ctx, f, findings.append)
        for cls in project.classes.values():
            if self.applies_to(cls.path):
                self._scan_shutdown(project, cls, findings.append)
        seen = set()
        for fnd in sorted(findings,
                          key=lambda x: (x.path, x.line, x.message)):
            key = (fnd.path, fnd.line, fnd.message)
            if key not in seen:
                seen.add(key)
                yield fnd

    # -- shutdown drain --------------------------------------------------

    def _future_queue_attrs(self, cls) -> Set[str]:
        """self.<attr> queues that submit() feeds futures into."""
        out: Set[str] = set()
        submit = cls.methods.get("submit")
        if submit is None:
            return out
        fut_names: Set[str] = set()
        for node in ast.walk(submit.node):
            if isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call):
                fn = node.value.func
                nm = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if nm.endswith("Future"):
                    fut_names.add(node.targets[0].id)
        if not fut_names:
            return out
        for node in ast.walk(submit.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("put", "put_nowait")):
                continue
            recv = node.func.value
            if not (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"):
                continue
            payload_names = {n.id for a in node.args
                             for n in ast.walk(a)
                             if isinstance(n, ast.Name)}
            if payload_names & fut_names:
                out.add(recv.attr)
        return out

    def _scan_shutdown(self, project, cls, emit) -> None:
        qattrs = self._future_queue_attrs(cls)
        if not qattrs:
            return
        close = cls.methods.get("close") or cls.methods.get("stop")
        anchor = (close or cls.methods["submit"]).node
        ctx = project.ctxs[cls.path]
        drained: Set[str] = set()
        if close is not None:
            ops = {n.attr for n in ast.walk(close.node)
                   if isinstance(n, ast.Attribute)}
            if ops & _DRAIN_OPS:
                attrs = {n.attr for n in ast.walk(close.node)
                         if isinstance(n, ast.Attribute)
                         and isinstance(n.value, ast.Name)
                         and n.value.id == "self"}
                drained = attrs & qattrs
        for attr in sorted(qattrs - drained):
            emit(ctx.finding(
                self.name, anchor,
                f"{cls.name}.submit() enqueues futures into "
                f"self.{attr} but "
                f"{'close()' if close else 'no close()/stop()'} "
                f"never fails the queued-but-undispatched items — "
                f"drain with get_nowait() + set_exception so no "
                f"caller hangs in result() on work that will never "
                f"run"))

    # -- lock discipline -------------------------------------------------

    def _scan_locks(self, ctx: FileCtx, func, emit) -> None:
        protected: Set[str] = set()
        for node in ast.walk(func.node):
            if isinstance(node, ast.Try) and node.finalbody:
                for n in ast.walk(ast.Module(node.finalbody, [])):
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and n.func.attr == "release":
                        recv = _recv_text(n.func.value)
                        if recv:
                            protected.add(recv)
        for node in _own_statements(func.node):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            recv = _recv_text(node.func.value)
            if recv is None or recv in protected:
                continue
            low = recv.lower()
            if not any(h in low for h in _LOCK_HINTS):
                continue
            emit(ctx.finding(
                self.name, node,
                f"{recv}.acquire() without a try/finally "
                f"{recv}.release() — an exception between acquire "
                f"and release wedges every other waiter; use `with "
                f"{recv}:` or pair it in a finally"))

    # -- raw open --------------------------------------------------------

    def _scan_opens(self, ctx: FileCtx, func, emit) -> None:
        if ctx.path in _OPEN_EXEMPT_PATHS:
            return
        with_items: Set[int] = set()
        for node in ast.walk(func.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    for n in ast.walk(item.context_expr):
                        with_items.add(id(n))
        for node in ast.walk(func.node):
            if not isinstance(node, ast.Call) or id(node) in with_items:
                continue
            fn = node.func
            is_open = (isinstance(fn, ast.Name) and fn.id == "open") \
                or (isinstance(fn, ast.Attribute)
                    and fn.attr == "fdopen"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "os")
            if is_open:
                emit(ctx.finding(
                    self.name, node,
                    "open() outside a context manager leaks the "
                    "descriptor on any exception before close() — "
                    "use `with open(...)` (libs/faultio is the "
                    "managed seam for the crash-consistent trees)"))
