"""The rule set. Each rule is a class with:

  name / doc      — identity + one-line rationale (docs/STATICCHECK.md)
  roots           — path prefixes (repo-relative) the rule scans
  exempt          — whole-file carve-outs, each justified inline here
  check(ctx)      — yield Findings for one parsed file
  tree_rule       — True if finalize() draws cross-file conclusions
  finalize(root)  — yield Findings after every file was seen

To add a rule: subclass Rule, implement check()/finalize(), append the
class to ALL_RULES, document it in docs/STATICCHECK.md, and give it a
positive + negative fixture in tests/test_staticcheck.py.
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from . import FileCtx, Finding


class Rule:
    name = ""
    doc = ""
    roots: Tuple[str, ...] = ("cometbft_tpu",)
    exempt: frozenset = frozenset()
    tree_rule = False
    needs_project = False   # True: finalize() wants the Project graph

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return any(path == top or path.startswith(top + "/")
                   for top in self.roots)

    def check(self, ctx: FileCtx) -> Iterable[Finding]:
        return ()

    def finalize(self, root: str, project=None) -> Iterable[Finding]:
        return ()


def _module_of(ctx: FileCtx, node: ast.AST) -> Optional[str]:
    """Top-level module a Name refers to, via this file's imports."""
    if isinstance(node, ast.Name):
        return ctx.module_aliases.get(node.id)
    return None


class WallClockRule(Rule):
    """All time must flow through libs/timesource.py — a direct stdlib
    clock read in reactor code silently escapes simnet's virtual clock
    and breaks byte-identical-per-seed logs."""
    name = "wallclock"
    doc = ("wall-clock read outside libs/timesource.py — route through "
           "timesource.monotonic()/time_ns(), or pragma a deliberate "
           "wall-clock site (waits gated on external processes)")
    # mconn: thread loops that must keep running during a sim hold
    # long-lived wall-clock references BY DESIGN — the documented
    # carve-out in libs/timesource.py's module docstring.
    exempt = frozenset({"cometbft_tpu/libs/timesource.py",
                        "cometbft_tpu/p2p/mconn.py"})

    _TIME_FNS = {"time", "time_ns", "monotonic", "monotonic_ns",
                 "perf_counter", "perf_counter_ns"}
    _DT_FNS = {"now", "utcnow", "today"}

    def check(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute):
                mod = _module_of(ctx, fn.value)
                if mod == "time" and fn.attr in self._TIME_FNS:
                    yield ctx.finding(
                        self.name, node,
                        f"time.{fn.attr}() outside libs/timesource — "
                        f"use timesource.monotonic()/time_ns()")
                elif fn.attr in self._DT_FNS and (
                        mod == "datetime"
                        or (isinstance(fn.value, ast.Attribute)
                            and _module_of(ctx, fn.value.value)
                            == "datetime")
                        or (isinstance(fn.value, ast.Name)
                            and ctx.from_imports.get(fn.value.id)
                            == "datetime.datetime")):
                    yield ctx.finding(
                        self.name, node,
                        f"datetime .{fn.attr}() outside libs/timesource "
                        f"— use timesource.time_ns()")
            elif isinstance(fn, ast.Name):
                target = ctx.from_imports.get(fn.id, "")
                if target.startswith("time.") \
                        and target[5:] in self._TIME_FNS:
                    yield ctx.finding(
                        self.name, node,
                        f"{target}() outside libs/timesource — use "
                        f"timesource.monotonic()/time_ns()")


class GlobalRngRule(Rule):
    """Every random draw must come from a seeded random.Random
    instance; the module-global RNG is shared, unseeded process state
    that breaks simnet's (scenario, seed) -> identical-log purity."""
    name = "global-rng"
    doc = ("module-level random.<fn>() call — draw from an injected / "
           "seeded random.Random instance instead")
    # bits.py pick_random accepts rng=None and falls back to the module
    # for interactive use; every deterministic caller injects.
    exempt = frozenset({"cometbft_tpu/libs/bits.py"})

    _RNG_FNS = {"random", "randint", "randrange", "shuffle", "choice",
                "choices", "sample", "uniform", "gauss", "getrandbits",
                "randbytes", "seed", "triangular", "betavariate",
                "expovariate", "normalvariate", "lognormvariate",
                "vonmisesvariate", "paretovariate", "weibullvariate"}

    def _is_global_random(self, ctx: FileCtx, base: ast.AST) -> bool:
        if _module_of(ctx, base) == "random":
            return True
        # `(rng or random).choice(...)` — the fallback operand is still
        # the global RNG
        if isinstance(base, ast.BoolOp):
            return any(_module_of(ctx, v) == "random" for v in base.values)
        return False

    def check(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) \
                    and fn.attr == "Random" \
                    and _module_of(ctx, fn.value) == "random" \
                    and not node.args:
                # unseeded Random() draws OS entropy — deterministic
                # for nobody; the invariant is SEEDED instances
                yield ctx.finding(
                    self.name, node,
                    "unseeded random.Random() — seed it (node-key- or "
                    "scenario-seed-derived) so draws replay")
            elif isinstance(fn, ast.Attribute) \
                    and fn.attr in self._RNG_FNS \
                    and self._is_global_random(ctx, fn.value):
                yield ctx.finding(
                    self.name, node,
                    f"global random.{fn.attr}() — use a seeded "
                    f"random.Random instance (node-key- or "
                    f"scenario-seed-derived)")
            elif isinstance(fn, ast.Name):
                target = ctx.from_imports.get(fn.id, "")
                if target == "random.Random" and not node.args:
                    yield ctx.finding(
                        self.name, node,
                        "unseeded random.Random() — seed it (node-key- "
                        "or scenario-seed-derived) so draws replay")
                elif target.startswith("random.") \
                        and target[7:] in self._RNG_FNS:
                    yield ctx.finding(
                        self.name, node,
                        f"global {target}() — use a seeded "
                        f"random.Random instance")


class RawEnvRule(Rule):
    """Numeric/boolean env knobs must ride libs/env.py so a malformed
    override degrades to the default instead of raising at import."""
    name = "raw-env"
    doc = ("os.environ read wrapped in int()/float()/bool() — use "
           "libs/env.env_int/env_float/env_bool (malformed-tolerant)")
    exempt = frozenset({"cometbft_tpu/libs/env.py"})

    _CASTS = {"int": "env_int", "float": "env_float", "bool": "env_bool"}

    def _touches_environ(self, ctx: FileCtx, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) \
                    and sub.attr in ("environ", "getenv") \
                    and _module_of(ctx, sub.value) == "os":
                return True
            if isinstance(sub, ast.Name) \
                    and ctx.from_imports.get(sub.id) in ("os.environ",
                                                         "os.getenv"):
                return True
        return False

    def check(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in self._CASTS \
                    and any(self._touches_environ(ctx, a)
                            for a in node.args):
                yield ctx.finding(
                    self.name, node,
                    f"{fn.id}(os.environ...) raises on a malformed "
                    f"override — use libs/env.{self._CASTS[fn.id]}()")


class ReactorSleepRule(Rule):
    """Blocking sleeps in reactor/pipeline/engine code stall virtual
    time (simnet) and the event loop alike — use the ticker /
    timesource seams or an event wait."""
    name = "reactor-sleep"
    doc = ("time.sleep() in consensus//pipeline//engine//farm//ingest//"
           "aggsig//mesh — use the ticker seam, an Event wait, or the "
           "async form")
    # farm/ and ingest/: RPC worker threads block on batcher/ticket
    # Events; a raw sleep there would both stall coalescing and break
    # the light-farm / flash-crowd scenarios' determinism. aggsig/:
    # commit verification runs inline in consensus handlers and the
    # blocksync marshal stage — a sleep there stalls the round.
    # mesh/: the dispatch loop serializes every tile; a sleep there
    # stalls K-per-shard pipelining, and the shard supervisor's probe
    # windows flow through timesource for the mesh-degrade scenario's
    # determinism. trace/: the recorder runs inline under data-plane
    # locks (span end -> record), so a sleep there stalls every
    # instrumented hot path at once
    # sealsync: the provider serves on reactor threads and the adopter
    # runs the boot critical path — a sleep in either stalls catch-up
    roots = ("cometbft_tpu/consensus", "cometbft_tpu/pipeline",
             "cometbft_tpu/engine", "cometbft_tpu/farm",
             "cometbft_tpu/ingest", "cometbft_tpu/aggsig",
             "cometbft_tpu/mesh", "cometbft_tpu/trace",
             "cometbft_tpu/sealsync")

    def check(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "sleep"
                    and _module_of(ctx, fn.value) == "time") \
                    or (isinstance(fn, ast.Name)
                        and ctx.from_imports.get(fn.id) == "time.sleep"):
                yield ctx.finding(
                    self.name, node,
                    "time.sleep() in reactor code — schedule on the "
                    "ticker / wait on an Event instead")


# guarded-by moved to lock_rules.py in the v2 engine (flow-aware when
# the project graph is available, lexical on subset runs); re-exported
# here so ALL_RULES and existing imports keep one canonical home.
from .kernel_rules import KernelDisciplineRule  # noqa: E402
from .lock_rules import GuardedByRule, LockOrderRule  # noqa: E402
from .taint import VerdictTaintRule  # noqa: E402
from .interval_rules import KernelIntervalRule  # noqa: E402
from .lifecycle_rules import ResourceLifecycleRule  # noqa: E402
from .contract_rules import ExceptionContractRule  # noqa: E402


class FailPointRule(Rule):
    """fail_point labels are a registry: crash schedules address them
    by name (simnet crash_at_label, COMETBFT_TPU_FAIL_LABEL), so a
    duplicate silently splits a schedule and an undocumented label is
    undiscoverable. Labels must be unique string literals listed in
    docs/SIMNET.md."""
    name = "failpoint"
    doc = ("fail_point labels must be unique string literals "
           "registered in docs/SIMNET.md's fail-point registry")
    tree_rule = True

    def __init__(self):
        self._seen: Dict[str, Tuple[str, int]] = {}
        self._dups: List[Finding] = []
        self._sites: List[Tuple[str, Finding]] = []

    def check(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_fp = (isinstance(fn, ast.Name) and fn.id == "fail_point") \
                or (isinstance(fn, ast.Attribute)
                    and fn.attr == "fail_point")
            if not is_fp:
                continue
            if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value):
                yield ctx.finding(
                    self.name, node,
                    "fail_point label must be a non-empty string "
                    "literal (crash schedules address it by name)")
                continue
            label = node.args[0].value
            f = ctx.finding(self.name, node, "")
            if label in self._seen:
                first = self._seen[label]
                self._dups.append(Finding(
                    self.name, f.path, f.line,
                    f"duplicate fail_point label {label!r} (first at "
                    f"{first[0]}:{first[1]}) — crash schedules would "
                    f"split across the sites", f.source_line))
            else:
                self._seen[label] = (f.path, f.line)
                self._sites.append((label, f))

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        yield from self._dups
        doc_path = os.path.join(root, "docs", "SIMNET.md")
        try:
            with open(doc_path, encoding="utf-8") as fh:
                doc = fh.read()
        except OSError:
            doc = ""
        for label, f in self._sites:
            # exact backtick-delimited form only: a plain substring
            # match would accept any label that happens to be a prefix
            # of a documented one (e.g. "finalize:post" inside
            # "finalize:post-save") or of prose
            if f"`{label}`" not in doc:
                yield Finding(
                    self.name, f.path, f.line,
                    f"fail_point label {label!r} missing from "
                    f"docs/SIMNET.md's fail-point registry "
                    f"(backtick-delimited exact form required)",
                    f.source_line)


class BareExceptRule(Rule):
    """`except:` in the device/pipeline hot paths swallows
    KeyboardInterrupt/SystemExit and masks wedge signatures the
    watchdog and supervisor key off — name the exceptions."""
    name = "bare-except"
    doc = ("bare `except:` in device/, pipeline/, farm/, ingest/, "
           "aggsig/, mesh/, or trace/ — catch named exception types so "
           "wedge/corruption signals propagate")
    # farm/ and ingest/ dispatch through the same device seam: a
    # swallowed canary/transport signal would hide corruption from the
    # supervisor; aggsig/'s FinalExpChecker rides the same canary/
    # quarantine discipline; mesh/'s per-shard canary checks and
    # probe errors are exactly the signals shard quarantine keys off;
    # trace/ sits inline in all of the above — a bare except in the
    # recorder could eat the very exception a dump is documenting
    # sealsync/'s pairing verdicts gate finality install — a swallowed
    # checker error there would install unverified finality
    roots = ("cometbft_tpu/device", "cometbft_tpu/pipeline",
             "cometbft_tpu/farm", "cometbft_tpu/ingest",
             "cometbft_tpu/aggsig", "cometbft_tpu/mesh",
             "cometbft_tpu/trace", "cometbft_tpu/sealsync")

    def check(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield ctx.finding(
                    self.name, node,
                    "bare `except:` — name the exception types "
                    "(BaseException swallowing hides wedge signals)")


class RawFileIoRule(Rule):
    """The crash-consistent stores (db/, consensus WAL, store/,
    privval/) do all file I/O through libs/faultio.open_file /
    faultio.fsync so the crash matrix can shear any write at any byte
    offset deterministically. A raw builtin open() or os.fsync() in
    those trees is a hole in the fault-injection seam: the write it
    performs can never be torn under test, so its crash behavior ships
    unproven."""
    name = "raw-file-io"
    doc = ("direct open()/os.open()/os.fdopen()/os.fsync() in "
           "consensus/, db/, store/, or privval/ — route through "
           "libs/faultio.open_file()/fsync() so the crash matrix can "
           "tear the write")
    roots = ("cometbft_tpu/consensus", "cometbft_tpu/db",
             "cometbft_tpu/store", "cometbft_tpu/privval")

    _OS_FNS = {"open", "fdopen", "fsync", "fdatasync"}

    def check(self, ctx: FileCtx) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id == "open":
                yield ctx.finding(
                    self.name, node,
                    "builtin open() bypasses the faultio seam — use "
                    "faultio.open_file(path, mode, label=...)")
            elif isinstance(fn, ast.Attribute) \
                    and fn.attr in self._OS_FNS \
                    and _module_of(ctx, fn.value) == "os":
                repl = ("faultio.fsync(f)"
                        if fn.attr in ("fsync", "fdatasync")
                        else "faultio.open_file(...)")
                yield ctx.finding(
                    self.name, node,
                    f"os.{fn.attr}() bypasses the faultio seam — "
                    f"use {repl}")


class MetricsDriftRule(Rule):
    """libs/metrics_gen.py is generated from libs/metrics_defs.py;
    hand-edits or un-regenerated spec changes drift the Prometheus
    surface from its declared source of truth."""
    name = "metrics-drift"
    doc = ("libs/metrics_gen.py must be byte-equal to regenerating "
           "from libs/metrics_defs.py (python tools/metricsgen.py)")
    roots: Tuple[str, ...] = ()
    tree_rule = True

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        gen = os.path.join(root, "cometbft_tpu", "libs", "metrics_gen.py")
        script = os.path.join(root, "tools", "metricsgen.py")
        if not (os.path.exists(gen) and os.path.exists(script)):
            return
        try:
            proc = subprocess.run(
                [sys.executable, script, "--check"], cwd=root,
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            yield Finding(self.name, "cometbft_tpu/libs/metrics_gen.py",
                          1, f"metricsgen --check could not run: {e}")
            return
        if proc.returncode != 0:
            detail = (proc.stdout + proc.stderr).strip().splitlines()
            tail = detail[-1] if detail else "out of date"
            yield Finding(
                self.name, "cometbft_tpu/libs/metrics_gen.py", 1,
                f"metrics_gen.py drifted from metrics_defs.py "
                f"({tail}) — run: python tools/metricsgen.py")


ALL_RULES = [WallClockRule, GlobalRngRule, RawEnvRule, ReactorSleepRule,
             GuardedByRule, FailPointRule, BareExceptRule,
             MetricsDriftRule, LockOrderRule, VerdictTaintRule,
             KernelDisciplineRule, RawFileIoRule, KernelIntervalRule,
             ResourceLifecycleRule, ExceptionContractRule]
