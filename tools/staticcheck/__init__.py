"""staticcheck — AST-driven project-invariant linter (stdlib-only).

The tree's correctness story leans on seams nothing enforced until now:
simnet's byte-identical-per-seed logs assume all time flows through
`libs/timesource.py` and all randomness through seeded `random.Random`
instances; env knobs must ride `libs/env.py`'s malformed-tolerant
parsers; thread-shared state relies on "guarded by `_lock`"
conventions. This package is the Python analog of the Go side's
`go vet` + custom vet passes: ~8 plugin rules (tools/staticcheck/
rules.py) grounded in those seams, run as

    python -m tools.staticcheck            # full tree, exit 1 on findings
    python -m tools.staticcheck --fix-baseline

Escapes, in order of preference:
  1. fix the code (route through the seam);
  2. an inline pragma on the offending line, or on a comment-only
     line directly above it:
         # staticcheck: allow(<rule>[, <rule>...])
     with a justification comment — the explicit, reviewed decision;
  3. a per-rule file exemption in `rules.py` (whole files that are the
     seam's documented carve-out, e.g. p2p/mconn.py for wall-clock);
  4. a baseline entry (tools/staticcheck/baseline.txt) — grandfathered
     debt only. The baseline may only shrink: the checker fails on NEW
     findings and on STALE entries alike, so any drift in either
     direction must be committed deliberately.

See docs/STATICCHECK.md for rule descriptions and how to add a rule.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

_PRAGMA_RE = re.compile(r"#\s*staticcheck:\s*allow\(([\w\-, ]+)\)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str          # repo-root-relative, posix separators
    line: int          # 1-based
    message: str
    source_line: str = ""

    def fingerprint(self) -> str:
        """Line-number-free identity used for baseline matching: rule,
        path, and the whitespace-normalized source line survive code
        motion above the finding."""
        norm = " ".join(self.source_line.split())
        return f"{self.rule}|{self.path}|{norm}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class FileCtx:
    """Parsed view of one source file handed to every per-file rule."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path  # relative posix
        with open(os.path.join(root, path), encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        # import resolution: local alias -> top-level module it names
        # ("time", "random", "os", "datetime"), and from-imported
        # name -> "module.attr" ("sleep" -> "time.sleep")
        self.module_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        # pragma maps: 1-based line -> set of allowed rule names. A
        # pragma on a CODE line covers that line only; a pragma on a
        # comment-only line additionally covers the line below (the
        # justification-comment-above form). Without the comment-only
        # restriction, every same-line pragma would silently disable
        # its rule for the next statement too.
        self.pragmas: Dict[int, Set[str]] = {}
        self.comment_pragmas: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.pragmas[i] = rules
                if text.lstrip().startswith("#"):
                    self.comment_pragmas[i] = rules

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule, path=self.path, line=line,
                       message=message, source_line=self.line_text(line))

    def suppressed(self, f: Finding) -> bool:
        """A pragma on the finding's line, or on a COMMENT-ONLY line
        directly above it, silences the finding. Rules must be named
        explicitly — there is deliberately no allow-everything
        wildcard."""
        for allowed in (self.pragmas.get(f.line),
                        self.comment_pragmas.get(f.line - 1)):
            if allowed and f.rule in allowed:
                return True
        return False


@dataclass
class Result:
    findings: List[Finding] = field(default_factory=list)   # not baselined
    suppressed: int = 0            # pragma-silenced count
    baselined: List[Finding] = field(default_factory=list)  # matched baseline
    stale_baseline: List[str] = field(default_factory=list)  # unmatched entries

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline


# --- baseline -------------------------------------------------------------

def default_baseline_path(root: str) -> str:
    return os.path.join(root, "tools", "staticcheck", "baseline.txt")


def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint -> justification comment ('' if none)."""
    entries: Dict[str, str] = {}
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            fp, sep, comment = line.partition("  ## ")
            entries[fp.strip()] = comment.strip() if sep else ""
    return entries


_BASELINE_HEADER = """\
# tools/staticcheck baseline — findings grandfathered when their rule
# landed. POLICY: this file may only shrink. The checker fails on NEW
# findings (fix the code, or pragma with justification) and on STALE
# entries (delete the line) alike; growing it requires an explicit
# `python -m tools.staticcheck --fix-baseline` commit, which review
# should treat as a fix-me-now flag. Every entry needs a trailing
# `  ## why this is temporarily acceptable` justification.
#
# Format: <rule>|<path>|<normalized source line>  ## <justification>
"""


def write_baseline(path: str, findings: Iterable[Finding],
                   old_comments: Optional[Dict[str, str]] = None) -> int:
    """Rewrite the baseline to exactly `findings`, preserving existing
    justification comments. Returns the entry count."""
    old_comments = old_comments or {}
    fps = sorted({f.fingerprint() for f in findings})
    with open(path, "w", encoding="utf-8") as f:
        f.write(_BASELINE_HEADER)
        for fp in fps:
            comment = old_comments.get(fp, "TODO: justify or fix")
            f.write(f"{fp}  ## {comment}\n")
    return len(fps)


# --- runner ---------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".claude"}


def _iter_py_files(root: str, roots: Tuple[str, ...]) -> List[str]:
    out: List[str] = []
    for top in roots:
        base = os.path.join(root, top)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, name), root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


def run_checks(root: str,
               baseline_path: Optional[str] = None,
               rules: Optional[list] = None,
               tree_rules: bool = True,
               only_paths: Optional[List[str]] = None) -> Result:
    """Run every rule over the tree rooted at `root`.

    `baseline_path=None` uses tools/staticcheck/baseline.txt under
    `root` (absent file = empty baseline). `tree_rules=False` skips
    whole-tree rules (fail-point registry, metrics drift) — used when
    linting a path subset, where cross-file conclusions would be wrong.
    `only_paths` restricts scanning to the given repo-relative files or
    directory prefixes (posix separators) — files outside are never
    parsed.
    """
    from . import rules as rules_mod
    # fresh instances every run: tree rules accumulate per-run state
    active = [cls() for cls in
              (rules if rules is not None else rules_mod.ALL_RULES)]
    if not tree_rules:
        active = [r for r in active if not r.tree_rule]

    result = Result()
    raw: List[Tuple[Finding, Optional[FileCtx]]] = []
    ctxs: Dict[str, FileCtx] = {}

    scan_roots = tuple(sorted({top for r in active for top in r.roots}))
    for path in _iter_py_files(root, scan_roots):
        if only_paths is not None and not any(
                path == p or path.startswith(p.rstrip("/") + "/")
                for p in only_paths):
            continue
        applicable = [r for r in active if r.applies_to(path)]
        if not applicable:
            continue
        try:
            ctx = FileCtx(root, path)
        except (SyntaxError, UnicodeDecodeError) as e:
            raw.append((Finding("parse", path, getattr(e, "lineno", 1) or 1,
                                f"unparseable: {e}"), None))
            continue
        ctxs[path] = ctx
        for rule in applicable:
            for f in rule.check(ctx):
                raw.append((f, ctx))

    for rule in active:
        for f in rule.finalize(root):
            raw.append((f, ctxs.get(f.path)))

    baseline = load_baseline(baseline_path
                             if baseline_path is not None
                             else default_baseline_path(root))
    # each baseline entry absorbs AT MOST ONE finding: a new violation
    # whose normalized source line happens to duplicate a grandfathered
    # one must fail, not ride the old entry. Deterministic consumption
    # order (path, line) so reruns agree on which site is "the" old one.
    matched: Set[str] = set()
    ordered = sorted(raw, key=lambda t: (t[0].path, t[0].line, t[0].rule))
    for f, ctx in ordered:
        if ctx is not None and ctx.suppressed(f):
            result.suppressed += 1
            continue
        fp = f.fingerprint()
        if fp in baseline and fp not in matched:
            matched.add(fp)
            result.baselined.append(f)
            continue
        result.findings.append(f)
    result.stale_baseline = sorted(set(baseline) - matched)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
