"""staticcheck — AST-driven project-invariant linter (stdlib-only).

The tree's correctness story leans on seams nothing enforced until now:
simnet's byte-identical-per-seed logs assume all time flows through
`libs/timesource.py` and all randomness through seeded `random.Random`
instances; env knobs must ride `libs/env.py`'s malformed-tolerant
parsers; thread-shared state relies on "guarded by `_lock`"
conventions. This package is the Python analog of the Go side's
`go vet` + custom vet passes: ~8 plugin rules (tools/staticcheck/
rules.py) grounded in those seams, run as

    python -m tools.staticcheck            # full tree, exit 1 on findings
    python -m tools.staticcheck --fix-baseline

Escapes, in order of preference:
  1. fix the code (route through the seam);
  2. an inline pragma on the offending line, or on a comment-only
     line directly above it:
         # staticcheck: allow(<rule>[, <rule>...])
     with a justification comment — the explicit, reviewed decision;
  3. a per-rule file exemption in `rules.py` (whole files that are the
     seam's documented carve-out, e.g. p2p/mconn.py for wall-clock);
  4. a baseline entry (tools/staticcheck/baseline.txt) — grandfathered
     debt only. The baseline may only shrink: the checker fails on NEW
     findings and on STALE entries alike, so any drift in either
     direction must be committed deliberately.

See docs/STATICCHECK.md for rule descriptions and how to add a rule.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

_PRAGMA_RE = re.compile(r"#\s*staticcheck:\s*allow\(([\w\-, ]+)\)")
_ASSUME_RE = re.compile(r"#\s*staticcheck:\s*assume\(")


@dataclass(frozen=True)
class Assume:
    """One `# staticcheck: assume(var, lo, hi[, shape=...][, dtype=...])`
    pragma. Unlike allow(), an assume is CHECKED, not trusted: the
    interval rule re-verifies the claimed range at the assumption site
    (computed ⊆ assumed → proven; disjoint → contradiction finding;
    overlap → refined + registered as a runtime obligation that
    tools/interval_fuzz.py re-checks on concrete executions). On an
    entry parameter (pragma lines between `def` and the first body
    statement) it is the entry precondition the fuzzer samples inside.

    lo/hi accept pure arithmetic literals (`2**16 - 1`). shape= is a
    tuple of int literals and/or bare symbol names; the same symbol
    used across one def's assume block names the same dimension.
    dtype= is one of int32/uint32/uint8/bool (default int32)."""
    var: str
    lo: int
    hi: int
    shape: Optional[Tuple[object, ...]]   # ints and/or str dim symbols
    dtype: str
    line: int


def _const_int(node: ast.AST) -> int:
    """Evaluate a pure arithmetic literal (no names, no calls)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd, ast.Invert)):
        v = _const_int(node.operand)
        return -v if isinstance(node.op, ast.USub) else (
            ~v if isinstance(node.op, ast.Invert) else v)
    if isinstance(node, ast.BinOp):
        ops = {ast.Add: lambda a, b: a + b, ast.Sub: lambda a, b: a - b,
               ast.Mult: lambda a, b: a * b, ast.Pow: lambda a, b: a ** b,
               ast.LShift: lambda a, b: a << b,
               ast.RShift: lambda a, b: a >> b,
               ast.FloorDiv: lambda a, b: a // b,
               ast.Mod: lambda a, b: a % b,
               ast.BitOr: lambda a, b: a | b,
               ast.BitAnd: lambda a, b: a & b,
               ast.BitXor: lambda a, b: a ^ b}
        fn = ops.get(type(node.op))
        if fn is not None:
            return fn(_const_int(node.left), _const_int(node.right))
    raise ValueError(f"not a pure int literal: {ast.dump(node)}")


_ASSUME_DTYPES = {"int32", "uint32", "uint8", "int8", "bool"}


def parse_assume(text: str, line: int) -> Optional[Assume]:
    """Parse one source line's assume() pragma; raises ValueError on a
    malformed one (flagged by the stale-pragma audit — a half-written
    assume must not silently vanish). Returns None when no pragma."""
    m = _ASSUME_RE.search(text)
    if not m:
        return None
    # balanced-paren scan: shape=(...) nests inside the pragma parens
    depth, i = 1, m.end()
    while i < len(text) and depth:
        depth += {"(": 1, ")": -1}.get(text[i], 0)
        i += 1
    if depth:
        raise ValueError("unbalanced parens in assume()")
    argsrc = text[m.end():i - 1]
    try:
        call = ast.parse(f"_({argsrc})", mode="eval").body
    except SyntaxError as e:
        raise ValueError(f"unparseable assume args: {e}")
    if not isinstance(call, ast.Call) or len(call.args) != 3:
        raise ValueError("assume() wants (var, lo, hi[, shape=][, dtype=])")
    var_node = call.args[0]
    if not isinstance(var_node, ast.Name):
        raise ValueError("assume() first arg must be a bare name")
    lo, hi = _const_int(call.args[1]), _const_int(call.args[2])
    if lo > hi:
        raise ValueError(f"assume() empty range [{lo}, {hi}]")
    shape: Optional[Tuple[object, ...]] = None
    dtype = "int32"
    for kw in call.keywords:
        if kw.arg == "shape":
            if not isinstance(kw.value, (ast.Tuple, ast.List)):
                raise ValueError("shape= must be a tuple")
            dims: List[object] = []
            for el in kw.value.elts:
                if isinstance(el, ast.Name):
                    dims.append(el.id)
                else:
                    d = _const_int(el)
                    if d < 1:
                        raise ValueError(f"shape dim {d} < 1")
                    dims.append(d)
            shape = tuple(dims)
        elif kw.arg == "dtype":
            name = (kw.value.id if isinstance(kw.value, ast.Name)
                    else kw.value.value
                    if isinstance(kw.value, ast.Constant) else None)
            if name not in _ASSUME_DTYPES:
                raise ValueError(f"dtype= must be one of "
                                 f"{sorted(_ASSUME_DTYPES)}")
            dtype = name
        else:
            raise ValueError(f"unknown assume() keyword {kw.arg!r}")
    return Assume(var=var_node.id, lo=lo, hi=hi, shape=shape,
                  dtype=dtype, line=line)


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule: str
    path: str          # repo-root-relative, posix separators
    line: int          # 1-based
    message: str
    source_line: str = ""

    def fingerprint(self) -> str:
        """Line-number-free identity used for baseline matching: rule,
        path, and the whitespace-normalized source line survive code
        motion above the finding."""
        norm = " ".join(self.source_line.split())
        return f"{self.rule}|{self.path}|{norm}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class FileCtx:
    """Parsed view of one source file handed to every per-file rule."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path  # relative posix
        with open(os.path.join(root, path), encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=path)
        # import resolution: local alias -> top-level module it names
        # ("time", "random", "os", "datetime"), and from-imported
        # name -> "module.attr" ("sleep" -> "time.sleep")
        self.module_aliases: Dict[str, str] = {}
        self.from_imports: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.module_aliases[a.asname or a.name.split(".")[0]] = \
                        a.name
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.from_imports[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
        # pragma maps: 1-based line -> set of allowed rule names. A
        # pragma on a CODE line covers that line only; a pragma on a
        # comment-only line additionally covers the line below (the
        # justification-comment-above form). Without the comment-only
        # restriction, every same-line pragma would silently disable
        # its rule for the next statement too.
        self.pragmas: Dict[int, Set[str]] = {}
        self.comment_pragmas: Dict[int, Set[str]] = {}
        # assume() pragmas: line -> parsed spec; comment-only assume
        # lines cover code below them (same stacking rule as allow(),
        # except a RUN of comment-only assume lines covers the next
        # code line — entry preconditions are one pragma per param).
        self.assumes: Dict[int, Assume] = {}
        self.comment_assume_lines: Set[int] = set()
        self.assume_errors: List[Tuple[int, str]] = []
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.pragmas[i] = rules
                if text.lstrip().startswith("#"):
                    self.comment_pragmas[i] = rules
            try:
                spec = parse_assume(text, i)
            except ValueError as e:
                self.assume_errors.append((i, str(e)))
                continue
            if spec is not None:
                self.assumes[i] = spec
                if text.lstrip().startswith("#"):
                    self.comment_assume_lines.add(i)

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule=rule, path=self.path, line=line,
                       message=message, source_line=self.line_text(line))

    def suppressed(self, f: Finding) -> bool:
        """A pragma on the finding's line, or on a COMMENT-ONLY line
        directly above it, silences the finding. Rules must be named
        explicitly — there is deliberately no allow-everything
        wildcard."""
        return self.suppressing_pragma(f) is not None

    def suppressing_pragma(self, f: Finding) -> Optional[int]:
        """Line number of the pragma that silences this finding (None
        when nothing does) — the runner's stale-pragma audit records
        which pragmas actually earned their keep."""
        allowed = self.pragmas.get(f.line)
        if allowed and f.rule in allowed:
            return f.line
        allowed = self.comment_pragmas.get(f.line - 1)
        if allowed and f.rule in allowed:
            return f.line - 1
        return None

    def has_pragma(self, rule: str, line: int) -> bool:
        """Does a pragma for `rule` cover source line `line`? Used by
        whole-program rules that must honor an allow() at a location
        OTHER than where the eventual finding is reported (e.g. a
        deliberately un-canaried `return` inside a verify backend,
        whose taint would otherwise surface at a far-away sink)."""
        allowed = self.pragmas.get(line)
        if allowed and rule in allowed:
            return True
        allowed = self.comment_pragmas.get(line - 1)
        return bool(allowed and rule in allowed)

    def assumes_at(self, line: int) -> List[Assume]:
        """assume() pragmas covering the statement at `line`: one on
        the line itself, plus any contiguous run of comment-only
        assume lines directly above it."""
        out: List[Assume] = []
        if line in self.assumes and line not in self.comment_assume_lines:
            out.append(self.assumes[line])
        j = line - 1
        while j in self.comment_assume_lines:
            out.append(self.assumes[j])
            j -= 1
        out.reverse()
        return out

    def assumes_between(self, lo: int, hi: int) -> List[Assume]:
        """assume() pragmas on lines in [lo, hi] — the entry-
        precondition form (pragma lines between `def` and the first
        body statement)."""
        return [self.assumes[i] for i in sorted(self.assumes)
                if lo <= i <= hi]


@dataclass
class Result:
    findings: List[Finding] = field(default_factory=list)   # not baselined
    suppressed: int = 0            # pragma-silenced count
    baselined: List[Finding] = field(default_factory=list)  # matched baseline
    stale_baseline: List[str] = field(default_factory=list)  # unmatched entries
    # rule name -> wall seconds spent in check()+finalize() (the
    # "(project-graph)" pseudo-entry is the shared symbol-table/call-
    # graph build the whole-program rules ride) — run_suite/CI uses
    # this to attribute a slow run to the rule that caused it
    rule_seconds: Dict[str, float] = field(default_factory=dict)
    # (path, line, rule) inventory of every allow() pragma seen
    pragma_inventory: List[Tuple[str, int, str]] = field(
        default_factory=list)
    # (path, line, var) inventory of every assume() pragma seen
    assume_inventory: List[Tuple[str, int, str]] = field(
        default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline

    def to_json(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [{"rule": f.rule, "path": f.path, "line": f.line,
                          "message": f.message} for f in self.findings],
            "stale_baseline": list(self.stale_baseline),
            "suppressed": self.suppressed,
            "baselined": len(self.baselined),
            "rule_seconds": {k: round(v, 4)
                             for k, v in sorted(self.rule_seconds.items())},
            "assume_pragmas": len(self.assume_inventory),
        }


# --- baseline -------------------------------------------------------------

def default_baseline_path(root: str) -> str:
    return os.path.join(root, "tools", "staticcheck", "baseline.txt")


def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint -> justification comment ('' if none)."""
    entries: Dict[str, str] = {}
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            fp, sep, comment = line.partition("  ## ")
            entries[fp.strip()] = comment.strip() if sep else ""
    return entries


_BASELINE_HEADER = """\
# tools/staticcheck baseline — findings grandfathered when their rule
# landed. POLICY: this file may only shrink. The checker fails on NEW
# findings (fix the code, or pragma with justification) and on STALE
# entries (delete the line) alike; growing it requires an explicit
# `python -m tools.staticcheck --fix-baseline` commit, which review
# should treat as a fix-me-now flag. Every entry needs a trailing
# `  ## why this is temporarily acceptable` justification.
#
# Format: <rule>|<path>|<normalized source line>  ## <justification>
"""


def write_baseline(path: str, findings: Iterable[Finding],
                   old_comments: Optional[Dict[str, str]] = None) -> int:
    """Rewrite the baseline to exactly `findings`, preserving existing
    justification comments. Returns the entry count."""
    old_comments = old_comments or {}
    fps = sorted({f.fingerprint() for f in findings})
    with open(path, "w", encoding="utf-8") as f:
        f.write(_BASELINE_HEADER)
        for fp in fps:
            comment = old_comments.get(fp, "TODO: justify or fix")
            f.write(f"{fp}  ## {comment}\n")
    return len(fps)


# --- runner ---------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".claude"}


def _iter_py_files(root: str, roots: Tuple[str, ...]) -> List[str]:
    out: List[str] = []
    for top in roots:
        base = os.path.join(root, top)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
            for name in sorted(filenames):
                if name.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, name), root)
                    out.append(rel.replace(os.sep, "/"))
    return sorted(set(out))


STALE_PRAGMA_RULE = "stale-pragma"


def run_checks(root: str,
               baseline_path: Optional[str] = None,
               rules: Optional[list] = None,
               tree_rules: bool = True,
               only_paths: Optional[List[str]] = None) -> Result:
    """Run every rule over the tree rooted at `root`.

    `baseline_path=None` uses tools/staticcheck/baseline.txt under
    `root` (absent file = empty baseline). `tree_rules=False` skips
    whole-tree rules (fail-point registry, metrics drift, the v2
    whole-program families) — used when linting a path subset, where
    cross-file conclusions would be wrong. `only_paths` restricts
    scanning to the given repo-relative files or directory prefixes
    (posix separators) — files outside are never parsed.
    """
    import time as _time
    from . import rules as rules_mod
    # fresh instances every run: tree rules accumulate per-run state
    active = [cls() for cls in
              (rules if rules is not None else rules_mod.ALL_RULES)]
    if not tree_rules:
        active = [r for r in active if not r.tree_rule]

    result = Result()
    raw: List[Tuple[Finding, Optional[FileCtx]]] = []
    ctxs: Dict[str, FileCtx] = {}

    def _timed(name: str, fn):
        t0 = _time.perf_counter()
        out = fn()
        result.rule_seconds[name] = (result.rule_seconds.get(name, 0.0)
                                     + _time.perf_counter() - t0)
        return out

    scan_roots = tuple(sorted({top for r in active for top in r.roots}))
    for path in _iter_py_files(root, scan_roots):
        if only_paths is not None and not any(
                path == p or path.startswith(p.rstrip("/") + "/")
                for p in only_paths):
            continue
        applicable = [r for r in active if r.applies_to(path)]
        if not applicable:
            continue
        try:
            ctx = FileCtx(root, path)
        except (SyntaxError, UnicodeDecodeError) as e:
            raw.append((Finding("parse", path, getattr(e, "lineno", 1) or 1,
                                f"unparseable: {e}"), None))
            continue
        ctxs[path] = ctx
        for rule in applicable:
            for f in _timed(rule.name, lambda r=rule: list(r.check(ctx))):
                raw.append((f, ctx))

    # whole-program layer: built once, shared by every rule whose
    # finalize() wants project-wide resolution (lock-order, verdict-
    # taint, kernel-discipline, flow-aware guarded-by)
    project = None
    if tree_rules and any(getattr(r, "needs_project", False)
                          for r in active):
        from . import graph as graph_mod
        project = _timed("(project-graph)",
                         lambda: graph_mod.build_project(root, ctxs))

    for rule in active:
        for f in _timed(rule.name,
                        lambda r=rule: list(r.finalize(root, project))):
            raw.append((f, ctxs.get(f.path)))
    # whole-program rules may honor a pragma at a line other than the
    # eventual finding's (e.g. verdict-taint's allow() on a deliberate
    # un-gated return) — count those as used so the stale audit agrees
    rule_used: Set[Tuple[str, int, str]] = set()
    for rule in active:
        rule_used |= set(getattr(rule, "used_pragmas", ()))

    baseline = load_baseline(baseline_path
                             if baseline_path is not None
                             else default_baseline_path(root))
    # each baseline entry absorbs AT MOST ONE finding: a new violation
    # whose normalized source line happens to duplicate a grandfathered
    # one must fail, not ride the old entry. Deterministic consumption
    # order (path, line) so reruns agree on which site is "the" old one.
    matched: Set[str] = set()
    used_pragmas: Set[Tuple[str, int, str]] = set()
    ordered = sorted(raw, key=lambda t: (t[0].path, t[0].line, t[0].rule))
    deferred: List[Tuple[Finding, Optional[FileCtx]]] = []
    for f, ctx in ordered:
        if ctx is not None:
            at = ctx.suppressing_pragma(f)
            if at is not None:
                result.suppressed += 1
                used_pragmas.add((f.path, at, f.rule))
                continue
        deferred.append((f, ctx))

    # stale-pragma audit (shrink-only, mirroring the baseline policy):
    # an allow(<rule>) whose rule no longer fires on that line is dead
    # weight that would silently swallow the NEXT regression there —
    # it must be deleted. Only audited for rules that are active AND
    # scan the file (a subset/--rule run must not brand every other
    # rule's pragmas stale); a name matching no known rule is always a
    # finding (it never suppressed anything and never will).
    known = {cls.name for cls in rules_mod.ALL_RULES}
    known.add(STALE_PRAGMA_RULE)
    active_by_name = {r.name: r for r in active}
    # assume() pragmas are audited by the rule that consumes them (the
    # interval rule sets audits_assumes and records every applied
    # pragma in used_assumes) — an assume the analyzer never reached
    # is dead weight exactly like a dead allow().
    assume_rule = next((r for r in active
                        if getattr(r, "audits_assumes", False)), None)
    assume_used: Set[Tuple[str, int]] = set(
        getattr(assume_rule, "used_assumes", ()) or ())
    for path in sorted(ctxs):
        ctx = ctxs[path]
        for line, err in ctx.assume_errors:
            deferred.append((Finding(
                STALE_PRAGMA_RULE, path, line,
                f"malformed assume() pragma ({err}) — a half-written "
                f"assume is silently inert", ctx.line_text(line)), ctx))
        for line in sorted(ctx.assumes):
            spec = ctx.assumes[line]
            result.assume_inventory.append((path, line, spec.var))
            if assume_rule is None or not assume_rule.applies_to(path):
                continue  # not audited this run
            if getattr(assume_rule, "needs_project", False) \
                    and project is None:
                continue  # the consuming rule didn't really run
            if (path, line) not in assume_used:
                deferred.append((Finding(
                    STALE_PRAGMA_RULE, path, line,
                    f"stale assume({spec.var}, ...): the interval "
                    f"analyzer never reached this pragma — delete it "
                    f"(an unchecked assume is an unreviewed trust "
                    f"grant)", ctx.line_text(line)), ctx))
        for line in sorted(ctx.pragmas):
            for rule_name in sorted(ctx.pragmas[line]):
                result.pragma_inventory.append((path, line, rule_name))
                if rule_name not in known:
                    deferred.append((Finding(
                        STALE_PRAGMA_RULE, path, line,
                        f"pragma names unknown rule {rule_name!r} "
                        f"(known: see --list-rules)",
                        ctx.line_text(line)), ctx))
                    continue
                rule = active_by_name.get(rule_name)
                if rule is None or not rule.applies_to(path):
                    continue  # not audited this run
                if getattr(rule, "tree_rule", False) and not tree_rules:
                    continue
                if getattr(rule, "needs_project", False) \
                        and project is None:
                    continue  # whole-program rule didn't really run
                if (path, line, rule_name) not in used_pragmas \
                        and (path, line, rule_name) not in rule_used:
                    deferred.append((Finding(
                        STALE_PRAGMA_RULE, path, line,
                        f"stale pragma: allow({rule_name}) suppresses "
                        f"nothing here — delete it (a dead allow() "
                        f"would silently swallow the next regression "
                        f"on this line)", ctx.line_text(line)), ctx))

    for f, ctx in sorted(deferred,
                         key=lambda t: (t[0].path, t[0].line, t[0].rule)):
        fp = f.fingerprint()
        if fp in baseline and fp not in matched:
            matched.add(fp)
            result.baselined.append(f)
            continue
        result.findings.append(f)
    result.stale_baseline = sorted(set(baseline) - matched)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result
