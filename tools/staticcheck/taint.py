"""verdict-taint — device-produced verdicts must pass a canary gate (or
a CPU re-verify) before anything acts on them.

This pins the PR-3/PR-7 invariant ("device results are never trusted
un-canaried") STATICALLY instead of only by test: a device can answer
wrong without failing, so the only trustworthy paths from a device
answer to a state-changing decision run through `check_canaries`, a
canary-gated checker, or a CPU recomputation.

Model (interprocedural, over the shared Project graph):

SOURCES — expressions whose value is a raw device verdict:
  * `DeviceFuture.result()` / `DeviceClient.verify()` calls, resolved
    through the light type facts (a receiver is device-typed when it
    came from `shared_client()`, a `DeviceClient(...)` constructor, or
    a parameter/attribute annotated `DeviceClient`; `.submit()` on a
    device client returns a `DeviceFuture` via its return annotation);
  * `ops.bls12.final_exp_is_one_batch(...)` and
    `ops.bls12.miller_finalexp_is_one_batch(...)` (the FinalExpChecker
    and PairingChecker kernel feeds).

SANITIZERS / GATES — what clears taint:
  * assignment from `device.health.check_canaries(...)` (the verdicts
    come back stripped and length-checked);
  * calls into GATE functions whose *internal* canary discipline is
    pinned by tests (`FinalExpChecker.check`/`_kernel_check`,
    `PairingChecker.check`/`_kernel_check`,
    `PipelinedBlocksync._canary_check`): their returns are clean;
  * re-binding a name from any clean expression (a CPU re-verify).

SINKS — where a tainted verdict becomes consensus/cache state:
  * `SigCache.add` (type-resolved receiver),
  * attribute calls named `check_tx`, `_apply_one`, or
    `save_light_block` (mempool admission, block apply, farm decision
    commit) — name-matched, because the mempool/reactor seams pass
    these objects untyped.

A finding fires when a tainted value (1) is an argument to a sink or
to a resolved callee's SINK-CRITICAL parameter (a parameter that
itself flows into a sink, computed to fixpoint), or (2) guards —
directly or via an early-return — a call that reaches a sink.

Escape hatch: a `# staticcheck: allow(verdict-taint)` pragma on a
RETURN that deliberately forwards an un-gated verdict (the
canary-opt-out configuration) marks the function's summary clean, and
the runner's stale-pragma audit keeps that pragma honest — if the
return stops being tainted, the pragma must go. Unresolved calls are
treated as CLEAN (the conservative direction here would flood every
`.verify()` in the tree); the dynamic-dispatch seams this misses are
exactly the ones the canary/quarantine tests pin at runtime — see
docs/STATICCHECK.md for the soundness tradeoff.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from . import FileCtx, Finding

# label "T" = a real device verdict; "P<i>" = the value of parameter i
Labels = FrozenSet[str]
EMPTY: Labels = frozenset()
T: Labels = frozenset({"T"})

_PKG = "cometbft_tpu"

SOURCE_METHODS = {
    f"{_PKG}.device.client.DeviceClient.verify",
    f"{_PKG}.device.client.DeviceFuture.result",
}
SOURCE_FUNCS = {
    f"{_PKG}.ops.bls12.final_exp_is_one_batch",
    f"{_PKG}.ops.bls12.miller_finalexp_is_one_batch",
}
SANITIZERS = {
    f"{_PKG}.device.health.check_canaries",
}
# canary gates whose internal discipline is pinned by tests
# (test_aggsig: wrong canary -> quarantine + CPU re-verify;
# test_pipeline/test_device_health: tile canary mismatch -> quarantine
# + CPU re-verify): their RETURNS are trusted clean.
GATES = {
    f"{_PKG}.aggsig.verify.FinalExpChecker.check",
    f"{_PKG}.aggsig.verify.FinalExpChecker._kernel_check",
    f"{_PKG}.aggsig.verify.PairingChecker.check",
    f"{_PKG}.aggsig.verify.PairingChecker._kernel_check",
    f"{_PKG}.pipeline.scheduler.PipelinedBlocksync._canary_check",
}
SINK_QUALS = {
    f"{_PKG}.pipeline.cache.SigCache.add",
}
SINK_NAMES = {"check_tx", "_apply_one", "save_light_block",
              "install_adopted"}


class _Summary:
    __slots__ = ("returns", "critical", "reaches_sink")

    def __init__(self):
        self.returns: Labels = EMPTY       # labels a call may return
        self.critical: Set[int] = set()    # param indices flowing to a sink
        self.reaches_sink = False


class VerdictTaintRule:
    name = "verdict-taint"
    doc = ("un-canaried device verdict reaches mempool.check_tx / "
           "_apply_one / SigCache.add / a farm decision commit — gate "
           "it through check_canaries, a canary-gated checker, or a "
           "CPU re-verify (docs/STATICCHECK.md)")
    roots: Tuple[str, ...] = ("cometbft_tpu",)
    exempt: frozenset = frozenset()
    tree_rule = True
    needs_project = True

    def __init__(self):
        self.used_pragmas: Set[Tuple[str, int, str]] = set()

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return any(path == top or path.startswith(top + "/")
                   for top in self.roots)

    def check(self, ctx: FileCtx):
        return ()

    # --- driver -----------------------------------------------------------

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        if project is None:
            return
        from .lock_rules import _local_env
        funcs = [f for f in project.functions.values()
                 if self.applies_to(f.path)]
        envs = {f.qualname: _local_env(project, f) for f in funcs}
        # (env + call resolution are memoized on the project and
        # shared with lock-order/guarded-by — see lock_rules)
        summaries: Dict[str, _Summary] = {f.qualname: _Summary()
                                          for f in funcs}
        # fixpoint over summaries (returns / critical params / reaches)
        for _ in range(len(funcs)):
            changed = False
            for f in funcs:
                s = summaries[f.qualname]
                before = (s.returns, frozenset(s.critical),
                          s.reaches_sink)
                _Interp(self, project, f, envs[f.qualname], summaries,
                        emit=None).run()
                if (s.returns, frozenset(s.critical),
                        s.reaches_sink) != before:
                    changed = True
            if not changed:
                break
        findings: List[Finding] = []
        for f in funcs:
            ctx = project.ctxs.get(f.path)
            _Interp(self, project, f, envs[f.qualname], summaries,
                    emit=findings.append, ctx=ctx).run()
        seen = set()
        for fnd in sorted(findings, key=lambda x: (x.path, x.line,
                                                   x.message)):
            key = (fnd.path, fnd.line, fnd.message)
            if key not in seen:
                seen.add(key)
                yield fnd

    def record_pragma(self, ctx: FileCtx, line: int) -> bool:
        """True (and records the use for the stale-pragma audit) when
        an allow(verdict-taint) covers `line`."""
        if ctx is None:
            return False
        if ctx.has_pragma(self.name, line):
            at = line if self.name in ctx.pragmas.get(line, set()) \
                else line - 1
            self.used_pragmas.add((ctx.path, at, self.name))
            return True
        return False


class _Interp:
    """One pass of the labels-based abstract interpreter over a
    function body. With emit=None it only updates the function's
    summary; with an emit callback it reports sink findings."""

    def __init__(self, rule: VerdictTaintRule, project, func, env,
                 summaries: Dict[str, _Summary], emit, ctx=None):
        self.rule = rule
        self.project = project
        self.func = func
        self.env = env
        self.summaries = summaries
        self.emit = emit
        self.ctx = ctx if ctx is not None else project.ctxs.get(func.path)
        self.summary = summaries[func.qualname]
        from .lock_rules import _call_targets
        self._targets = _call_targets(project, func)
        self.params: List[str] = []
        args = getattr(func.node, "args", None)
        if args is not None:
            self.params = [a.arg for a in
                           args.posonlyargs + args.args]

    # --- entry ------------------------------------------------------------

    def run(self) -> None:
        state: Dict[str, Labels] = {}
        for i, p in enumerate(self.params):
            if p == "self":
                continue
            state[p] = frozenset({f"P{i}"})
        self.exec_block(self.func.node.body, state, EMPTY)

    # --- expression labels ------------------------------------------------

    def labels(self, node: ast.AST, state: Dict[str, Labels]) -> Labels:
        if isinstance(node, ast.Name):
            return state.get(node.id, EMPTY)
        if isinstance(node, ast.Call):
            return self.call_labels(node, state)
        if isinstance(node, ast.Lambda):
            return EMPTY
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return EMPTY
        out: Labels = EMPTY
        for child in ast.iter_child_nodes(node):
            out |= self.labels(child, state)
        return out

    def _resolve(self, call: ast.Call) -> List[str]:
        return self._targets.get(id(call), [])

    def call_labels(self, node: ast.Call,
                    state: Dict[str, Labels]) -> Labels:
        targets = self._resolve(node)
        arg_labels: Labels = EMPTY
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            arg_labels |= self.labels(a, state)
        self._check_sink(node, state, arg_labels, targets)
        if any(t in SANITIZERS or t in GATES for t in targets):
            return EMPTY
        out: Labels = EMPTY
        if any(t in SOURCE_FUNCS or t in SOURCE_METHODS
               for t in targets):
            out |= T
        fn = node.func
        resolved_fn = [t for t in targets
                       if t in self.project.functions]
        if resolved_fn:
            for t in resolved_fn:
                s = self.summaries.get(t)
                if s is not None:
                    out |= (s.returns & T)
            # resolved callees still pass their inputs through
            # (identity/transform helpers): assume arg labels survive
            out |= arg_labels
        else:
            # unresolved / builtin: pass-through of argument labels,
            # plus the receiver's labels for method calls
            out |= arg_labels
            if isinstance(fn, ast.Attribute):
                out |= self.labels(fn.value, state)
        return out

    # --- sinks ------------------------------------------------------------

    def _is_sink(self, node: ast.Call, targets: List[str]) -> bool:
        if any(t in SINK_QUALS for t in targets):
            return True
        fn = node.func
        return isinstance(fn, ast.Attribute) and fn.attr in SINK_NAMES

    def _check_sink(self, node: ast.Call, state: Dict[str, Labels],
                    arg_labels: Labels, targets: List[str]) -> None:
        sink = self._is_sink(node, targets)
        reaches = sink or any(
            self.summaries[t].reaches_sink
            for t in targets if t in self.summaries)
        if reaches:
            self.summary.reaches_sink = True
        # tainted ARGUMENT into a sink / a callee's sink-critical param
        crit_hit: Labels = EMPTY
        if sink:
            crit_hit |= arg_labels
        for t in targets:
            s = self.summaries.get(t)
            if s is None or not s.critical:
                continue
            callee = self.project.functions.get(t)
            offset = 1 if (callee is not None and callee.is_method
                           and not isinstance(node.func, ast.Name)) \
                else 0
            for j, a in enumerate(node.args):
                if j + offset in s.critical:
                    crit_hit |= self.labels(a, state)
            if callee is not None:
                names = [a.arg for a in
                         callee.node.args.posonlyargs
                         + callee.node.args.args]
                for kw in node.keywords:
                    if kw.arg in names and \
                            names.index(kw.arg) in s.critical:
                        crit_hit |= self.labels(kw.value, state)
        self._hit(node, crit_hit,
                  "flows into" if sink else "flows into a call that "
                  "reaches")
        # sink (or sink-reaching call) under a tainted guard
        if reaches and self.guard:
            self._hit(node, self.guard, "gates")

    def _hit(self, node: ast.Call, labels: Labels, how: str) -> None:
        for lbl in labels:
            if lbl == "T":
                if self.emit is not None:
                    name = ast.unparse(node.func) if hasattr(
                        ast, "unparse") else "<sink>"
                    self.emit(Finding(
                        self.rule.name, self.func.path, node.lineno,
                        f"un-canaried device verdict {how} "
                        f"`{name}(...)` — gate it through "
                        f"check_canaries / a canary-gated checker / a "
                        f"CPU re-verify first",
                        self.ctx.line_text(node.lineno)
                        if self.ctx else ""))
            elif lbl.startswith("P"):
                self.summary.critical.add(int(lbl[1:]))

    # --- statements -------------------------------------------------------

    def exec_block(self, body: List[ast.stmt], state: Dict[str, Labels],
                   guard: Labels) -> bool:
        """Returns True when the block terminates (return/raise/...).
        `guard` = labels controlling whether this block runs at all."""
        self.guard = guard
        for i, stmt in enumerate(body):
            self.guard = guard
            if self.exec_stmt(stmt, state, guard):
                return True
            # an early-terminating tainted If extends its guard over
            # the REST of the block (implicit control dependence)
            if isinstance(stmt, ast.If):
                test_labels = self.labels(stmt.test, state)
                if test_labels and (
                        _terminates(stmt.body)
                        or (stmt.orelse and _terminates(stmt.orelse))):
                    guard = guard | test_labels
        return False

    def exec_stmt(self, stmt: ast.stmt, state: Dict[str, Labels],
                  guard: Labels) -> bool:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False   # nested defs: analyzed conservatively never
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                lbls = self.labels(stmt.value, state)
                if "T" in lbls and self.rule.record_pragma(
                        self.ctx, stmt.lineno):
                    lbls = lbls - T
                self.summary.returns |= lbls | (guard & T)
            return True
        if isinstance(stmt, (ast.Raise, ast.Break, ast.Continue)):
            return True
        if isinstance(stmt, ast.Assign):
            lbls = self.labels(stmt.value, state)
            sanitized = self._is_sanitizer_call(stmt.value)
            for t in stmt.targets:
                self._bind(t, EMPTY if sanitized else lbls, state)
            return False
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, self.labels(stmt.value, state),
                       state)
            return False
        if isinstance(stmt, ast.AugAssign):
            lbls = self.labels(stmt.value, state)
            if isinstance(stmt.target, ast.Name):
                state[stmt.target.id] = \
                    state.get(stmt.target.id, EMPTY) | lbls
            else:
                self._bind(stmt.target, lbls, state)
            return False
        if isinstance(stmt, ast.If):
            test = self.labels(stmt.test, state)
            inner_guard = guard | (test & T)
            s1 = dict(state)
            t1 = self.exec_block(stmt.body, s1, inner_guard)
            s2 = dict(state)
            t2 = self.exec_block(stmt.orelse, s2, inner_guard)
            _merge(state, s1 if not t1 else None, s2 if not t2 else None)
            return t1 and t2 and bool(stmt.orelse)
        if isinstance(stmt, (ast.While,)):
            test = self.labels(stmt.test, state)
            inner_guard = guard | (test & T)
            for _ in range(2):          # quasi-fixpoint: labels grow
                s1 = dict(state)
                self.exec_block(stmt.body, s1, inner_guard)
                _merge(state, s1, None)
            self.exec_block(stmt.orelse, state, guard)
            return False
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            it = self.labels(stmt.iter, state)
            self._bind(stmt.target, it, state)
            for _ in range(2):
                s1 = dict(state)
                self.exec_block(stmt.body, s1, guard)
                _merge(state, s1, None)
            self.exec_block(stmt.orelse, state, guard)
            return False
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                lbls = self.labels(item.context_expr, state)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, lbls, state)
            return self.exec_block(stmt.body, state, guard)
        if isinstance(stmt, ast.Try):
            s1 = dict(state)
            self.exec_block(stmt.body, s1, guard)
            _merge(state, s1, None)
            for h in stmt.handlers:
                s2 = dict(state)
                self.exec_block(h.body, s2, guard)
                _merge(state, s2, None)
            self.exec_block(stmt.orelse, state, guard)
            self.exec_block(stmt.finalbody, state, guard)
            return False
        if isinstance(stmt, ast.Expr):
            self.labels(stmt.value, state)
            return False
        # default: evaluate embedded expressions for sink detection
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.labels(child, state)
        return False

    def _is_sanitizer_call(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and any(
            t in SANITIZERS for t in self._resolve(node))

    def _bind(self, target: ast.AST, lbls: Labels,
              state: Dict[str, Labels]) -> None:
        if isinstance(target, ast.Name):
            state[target.id] = lbls
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, lbls, state)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, lbls, state)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            # write through an object: taint sticks to the base name
            base = target.value
            while isinstance(base, (ast.Attribute, ast.Subscript)):
                base = base.value
            if isinstance(base, ast.Name) and lbls:
                state[base.id] = state.get(base.id, EMPTY) | lbls


def _terminates(body: List[ast.stmt]) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Return, ast.Raise, ast.Break, ast.Continue))


def _merge(state: Dict[str, Labels], a: Optional[Dict[str, Labels]],
           b: Optional[Dict[str, Labels]]) -> None:
    branches = [s for s in (a, b) if s is not None]
    if not branches:
        return   # both paths terminated; fall-through state unchanged
    keys = set(state)
    for src in branches:
        keys |= set(src)
    for k in keys:
        vals: Labels = EMPTY
        for s in branches:
            vals |= s.get(k, state.get(k, EMPTY))
        state[k] = vals
