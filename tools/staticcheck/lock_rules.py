"""Lock-discipline rule family: whole-program lock-order analysis and
the flow-aware `# guarded-by` contract.

Two rules, both riding the shared Project graph (graph.py):

`lock-order` — extracts the project's lock-acquisition graph: a node
per (owning scope, lock attribute), an edge A -> B wherever code
acquires B (directly, or by calling a function that transitively
acquires B) while holding A. Cycles are potential deadlocks (two
threads entering the ring at different points), and a SELF-edge is a
guaranteed one: every `threading.Lock` in this tree is non-reentrant.
Lock sites are `with self.<attr>` / `with <typed-expr>.<attr>` /
`with <module-name>` where the attribute/name contains "lock" — the
tree's uniform convention. Unresolved receivers and dynamic dispatch
are SKIPPED for edges: for deadlock detection, over-approximating
edges manufactures false cycles, so the graph only asserts what it can
resolve (the seams the convention tests pin cover the rest).

`guarded-by` — the PR-4 lexical rule promoted to flow-aware. A class
declares `# guarded-by: <lock>: attr, ...` in its body; every access
to a declared attribute must happen while the lock is held. v2 computes
each method's ENTRY-HELD set: a private, never-escaping method whose
every intraclass call site runs under `with self._lock` is itself
lock-held at entry — so `_shed_locked`-style helpers no longer need a
pragma — while a method reachable both with and without the lock (or
public, or passed as a callback, or called from another class) gets
the empty entry set, and any guarded access inside it on a path that
can skip the lock is a finding. Subset runs (no project graph) fall
back to the PR-4 lexical check.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from . import FileCtx, Finding

LockId = Tuple[str, str]      # (owning scope qualname, lock name)

_GUARD_RE = re.compile(
    r"#\s*guarded-by:\s*(\w+)\s*:\s*([A-Za-z_][A-Za-z0-9_,\s]*)")


def _is_lockish(name: str) -> bool:
    return "lock" in name.lower()


def _with_items_locks(node, func, project, env) -> List[LockId]:
    """Lock ids acquired by one With statement's context managers."""
    out: List[LockId] = []
    for item in node.items:
        e = item.context_expr
        if isinstance(e, ast.Attribute) and _is_lockish(e.attr):
            if isinstance(e.value, ast.Name) and e.value.id == "self" \
                    and func.cls:
                out.append((func.cls, e.attr))
            elif project is not None:
                for t in sorted(project.expr_types(e.value, func, env)):
                    out.append((t, e.attr))
        elif isinstance(e, ast.Name) and _is_lockish(e.id):
            out.append((f"mod:{func.module}", e.id))
    return out


def _local_env(project, func) -> Dict[str, Set[str]]:
    """Coarse local-variable type environment: one pass over the
    function body in source order, so `client = shared_client()` then
    `fut = client.submit(...)` resolves the chained method. Memoized
    on the project — lock-order, guarded-by, and verdict-taint all
    consume the same environments."""
    cache = getattr(project, "_env_cache", None)
    if cache is None:
        cache = project._env_cache = {}
    got = cache.get(func.qualname)
    if got is not None:
        return got
    env: Dict[str, Set[str]] = {}
    for node in ast.walk(func.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            t = project.expr_types(node.value, func, env)
            if t:
                env[node.targets[0].id] = (
                    env.get(node.targets[0].id, set()) | t)
    cache[func.qualname] = env
    return env


def _call_targets(project, func) -> Dict[int, List[str]]:
    """id(Call node) -> resolved function qualnames, for EVERY call in
    `func` (closures included — the edge walker analyzes those too,
    just with an empty held set). Memoized on the project."""
    cache = getattr(project, "_call_cache", None)
    if cache is None:
        cache = project._call_cache = {}
    got = cache.get(func.qualname)
    if got is not None:
        return got
    env = _local_env(project, func)
    out: Dict[int, List[str]] = {}
    for c in project.iter_calls(func):
        tgt = [q for q in project.resolve_call(func, c, env)
               if q in project.functions]
        if tgt:
            out[id(c)] = tgt
    cache[func.qualname] = out
    return out


def _own_nodes(root: ast.AST):
    """Walk a function's OWN body, never descending into nested
    defs/lambdas: a closure's acquisitions belong to whoever eventually
    CALLS it, not to the function that merely defines it (a callback
    registered under a lock must not fabricate a lock edge)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


class LockOrderRule:
    """Cross-function lock-acquisition-order cycles (potential
    deadlock) and non-reentrant self-acquisition."""

    name = "lock-order"
    doc = ("lock-acquisition cycle (or re-acquisition of a held "
           "non-reentrant lock) across the project call graph — "
           "potential deadlock; break the cycle or order the locks")
    roots: Tuple[str, ...] = ("cometbft_tpu",)
    exempt: frozenset = frozenset()
    tree_rule = True
    needs_project = True

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return any(path == top or path.startswith(top + "/")
                   for top in self.roots)

    def check(self, ctx: FileCtx):
        return ()

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        if project is None:
            return
        funcs = [f for f in project.functions.values()
                 if self.applies_to(f.path)]
        reentrant = _reentrant_locks(project)
        envs = {f.qualname: _local_env(project, f) for f in funcs}
        # resolved: EVERY call (closures included) for the edge walker;
        # own_calls/direct: the function's OWN statements only — a
        # closure's acquisitions are charged to its eventual caller,
        # never to the function that defines it
        resolved: Dict[str, Dict[int, List[str]]] = {}
        own_calls: Dict[str, List[List[str]]] = {}
        direct: Dict[str, List[LockId]] = {}
        for f in funcs:
            env = envs[f.qualname]
            resolved[f.qualname] = _call_targets(project, f)
            own_calls[f.qualname] = [
                resolved[f.qualname][id(n)]
                for n in _own_nodes(f.node)
                if isinstance(n, ast.Call)
                and id(n) in resolved[f.qualname]]
            direct[f.qualname] = [
                lid for node in _own_nodes(f.node)
                if isinstance(node, (ast.With, ast.AsyncWith))
                for lid in _with_items_locks(node, f, project, env)]

        # transitive acquisition summary, to fixpoint
        acquires: Dict[str, Set[LockId]] = {
            f.qualname: set(direct[f.qualname]) for f in funcs}
        changed = True
        while changed:
            changed = False
            for f in funcs:
                acc = acquires[f.qualname]
                before = len(acc)
                for targets in own_calls[f.qualname]:
                    for t in targets:
                        acc |= acquires.get(t, set())
                if len(acc) != before:
                    changed = True

        # edges: held-at-point -> acquired (direct or via a call)
        edges: Dict[Tuple[LockId, LockId],
                    Tuple[str, int, str]] = {}   # witness (path, line, via)

        def note(a: LockId, b: LockId, path: str, line: int,
                 via: str) -> None:
            edges.setdefault((a, b), (path, line, via))

        def walk(body, func, env, held: Tuple[LockId, ...]) -> None:
            for node in body:
                visit(node, func, env, held)

        def visit(node, func, env, held) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                got = _with_items_locks(node, func, project, env)
                for item in node.items:
                    visit(item.context_expr, func, env, held)
                for lid in got:
                    for h in held:
                        note(h, lid, func.path, node.lineno,
                             f"acquires {lid[1]}")
                inner = held + tuple(lid for lid in got
                                     if lid not in held)
                walk(node.body, func, env, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # a closure may run later, on another thread, without
                # the enclosing locks — analyze it unlocked
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                walk(body, func, env, ())
                return
            if isinstance(node, ast.Call) and held:
                for q in resolved[func.qualname].get(id(node), ()):
                    for lid in acquires.get(q, ()):
                        for h in held:
                            note(h, lid, func.path, node.lineno,
                                 f"call {q.rsplit('.', 1)[-1]}() "
                                 f"acquires {lid[1]}")
            for child in ast.iter_child_nodes(node):
                visit(child, func, env, held)

        for f in funcs:
            walk(f.node.body, f, envs[f.qualname], ())

        # self-edges: re-acquiring a held NON-REENTRANT lock wedges
        # (an RLock/Condition re-entry is by design and skipped)
        for (a, b), (path, line, via) in sorted(edges.items()):
            if a == b and a not in reentrant:
                yield Finding(
                    self.name, path, line,
                    f"{a[0].rsplit('.', 1)[-1]}.{a[1]} is re-acquired "
                    f"while already held ({via}) — threading.Lock is "
                    f"not reentrant; this deadlocks the thread")

        # cycles (length >= 2): Tarjan SCC over the lock digraph
        graph: Dict[LockId, Set[LockId]] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        for scc in _sccs(graph):
            if len(scc) < 2:
                continue
            ring = sorted(scc)
            witnesses = sorted(
                (edges[(a, b)], a, b) for (a, b) in edges
                if a in scc and b in scc and a != b)
            (path, line, _via), a, b = witnesses[0]
            names = ", ".join(f"{s.rsplit('.', 1)[-1]}.{l}"
                              for s, l in ring)
            detail = "; ".join(
                f"{wa[0].rsplit('.', 1)[-1]}.{wa[1]} -> "
                f"{wb[0].rsplit('.', 1)[-1]}.{wb[1]} at {w[0]}:{w[1]}"
                for w, wa, wb in witnesses[:4])
            yield Finding(
                self.name, path, line,
                f"lock-order cycle: {{{names}}} — two threads entering "
                f"this ring at different points deadlock ({detail})")


def _reentrant_locks(project) -> Set[LockId]:
    """(scope, name) pairs assigned from threading.RLock()/Condition()
    — re-entrant by construction, so a self-edge is not a deadlock."""
    out: Set[LockId] = set()

    def is_rlockish(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        f = value.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        return name in ("RLock", "Condition")

    for path, ctx in project.ctxs.items():
        from .graph import module_name
        mod = module_name(path)
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and is_rlockish(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add((f"mod:{mod}", t.id))
            elif isinstance(node, ast.ClassDef):
                cqn = f"{mod}.{node.name}"
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) \
                            and is_rlockish(sub.value):
                        for t in sub.targets:
                            if isinstance(t, ast.Attribute) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id == "self":
                                out.add((cqn, t.attr))
    return out


def _sccs(graph: Dict[LockId, Set[LockId]]) -> List[Set[LockId]]:
    """Iterative Tarjan strongly-connected components."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on: Set[LockId] = set()
    stack: List[LockId] = []
    out: List[Set[LockId]] = []
    counter = [0]

    for start in sorted(graph):
        if start in index:
            continue
        work = [(start, iter(sorted(graph.get(start, ()))))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on.add(start)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp: Set[LockId] = set()
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.add(w)
                    if w == v:
                        break
                out.append(comp)
    return out


class GuardedByRule:
    """Flow-aware `# guarded-by: <lock>: attrs` contract (see module
    docstring). Declared today across pipeline/cache, farm/session,
    farm/batcher, farm/service, ingest/admission, device/health,
    libs/jax_cache, p2p/switch, and aggsig/aggregate."""

    name = "guarded-by"
    doc = ("access to a `# guarded-by: <lock>: <attrs>`-declared "
           "attribute on a path that can skip `with self.<lock>` "
           "(flow-aware: helpers only ever called under the lock are "
           "lock-held at entry; __init__ exempt)")
    roots: Tuple[str, ...] = ("cometbft_tpu",)
    exempt: frozenset = frozenset()
    tree_rule = False          # subset runs still get the lexical check
    needs_project = True

    def __init__(self):
        self._ctxs: List[FileCtx] = []
        # callee method qualname -> caller class qualnames (None for
        # module-level callers); computed once per run, shared by every
        # declared class's entry-held analysis
        self._ext_calls: Optional[Dict[str, Set[Optional[str]]]] = None

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return any(path == top or path.startswith(top + "/")
                   for top in self.roots)

    def check(self, ctx: FileCtx):
        self._ctxs.append(ctx)
        return ()

    # --- declaration scan -------------------------------------------------

    @staticmethod
    def declared(ctx: FileCtx, cls: ast.ClassDef) -> Dict[str, str]:
        """attr -> lock-attr, from guarded-by comments in the class
        body's line span."""
        attr_lock: Dict[str, str] = {}
        end = getattr(cls, "end_lineno", cls.lineno) or cls.lineno
        for ln in range(cls.lineno, end + 1):
            m = _GUARD_RE.search(ctx.line_text(ln))
            if m:
                lock = m.group(1)
                for attr in m.group(2).split(","):
                    attr = attr.strip()
                    if attr:
                        attr_lock[attr] = lock
        return attr_lock

    # --- entry-held computation -------------------------------------------

    def _entry_held(self, project, cinfo, locks: Set[str]
                    ) -> Dict[str, FrozenSet[str]]:
        """Lock set provably held when each method is entered.

        Public methods, dunders, methods whose reference ESCAPES (read
        as a value — callback registration, Thread target), and methods
        resolvedly called from OUTSIDE the class start at the empty
        set. Private intraclass-only methods start optimistic (all
        declared locks) and shrink to the intersection over their call
        sites' held sets, to fixpoint."""
        methods = cinfo.methods
        escaped: Set[str] = set()
        for m in methods.values():
            for node in ast.walk(m.node):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.value, ast.Name) \
                        and node.value.id == "self" \
                        and node.attr in methods:
                    if not self._is_call_func(m.node, node):
                        escaped.add(node.attr)
        externally_called: Set[str] = set()
        if project is not None:
            if self._ext_calls is None:
                self._ext_calls = {}
                for f in project.functions.values():
                    for targets in _call_targets(project, f).values():
                        for q in targets:
                            self._ext_calls.setdefault(
                                q, set()).add(f.cls)
            for name in methods:
                callers = self._ext_calls.get(
                    f"{cinfo.qualname}.{name}", set())
                if callers - {cinfo.qualname}:
                    externally_called.add(name)

        def optimistic(name: str) -> FrozenSet[str]:
            if not name.startswith("_") or name.startswith("__"):
                return frozenset()
            if name in escaped or name in externally_called:
                return frozenset()
            return frozenset(locks)

        entry = {n: optimistic(n) for n in methods}
        for _ in range(len(methods) + 2):
            sites: Dict[str, List[FrozenSet[str]]] = {n: []
                                                      for n in methods}

            def scan(body, held: FrozenSet[str]) -> None:
                for node in body:
                    scan_node(node, held)

            def scan_node(node, held: FrozenSet[str]) -> None:
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    got = {item.context_expr.attr
                           for item in node.items
                           if isinstance(item.context_expr, ast.Attribute)
                           and isinstance(item.context_expr.value,
                                          ast.Name)
                           and item.context_expr.value.id == "self"}
                    scan(node.body, held | frozenset(got))
                    return
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                    body = node.body if isinstance(node.body, list) \
                        else [node.body]
                    scan(body, frozenset())
                    return
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == "self" \
                        and node.func.attr in methods:
                    sites[node.func.attr].append(held)
                for child in ast.iter_child_nodes(node):
                    scan_node(child, held)

            for name, m in methods.items():
                scan(m.node.body, entry[name])
            new = {}
            for name in methods:
                base = optimistic(name)
                if base and sites[name]:
                    inter = frozenset(locks)
                    for h in sites[name]:
                        inter &= h
                    new[name] = inter
                elif base and not sites[name]:
                    # never called inside the class: nothing proves the
                    # lock is held at entry
                    new[name] = frozenset()
                else:
                    new[name] = base
            if new == entry:
                break
            entry = new
        return entry

    @staticmethod
    def _is_call_func(scope: ast.AST, target: ast.Attribute) -> bool:
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and node.func is target:
                return True
        return False

    # --- the walk ---------------------------------------------------------

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        for ctx in self._ctxs:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(ctx, node, project)

    def _check_class(self, ctx: FileCtx, cls: ast.ClassDef,
                     project) -> Iterator[Finding]:
        attr_lock = self.declared(ctx, cls)
        if not attr_lock:
            return
        entry: Dict[str, FrozenSet[str]] = {}
        if project is not None:
            from .graph import module_name
            cqn = f"{module_name(ctx.path)}.{cls.name}"
            cinfo = project.classes.get(cqn)
            if cinfo is not None:
                entry = self._entry_held(project, cinfo,
                                         set(attr_lock.values()))
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and item.name != "__init__":
                held = frozenset(entry.get(item.name, frozenset()))
                yield from self._walk(ctx, item.body, attr_lock, held)

    def _with_locks(self, node: ast.With) -> Set[str]:
        got: Set[str] = set()
        for item in node.items:
            e = item.context_expr
            if isinstance(e, ast.Attribute) \
                    and isinstance(e.value, ast.Name) \
                    and e.value.id == "self":
                got.add(e.attr)
        return got

    def _walk(self, ctx: FileCtx, body, attr_lock: Dict[str, str],
              held: FrozenSet[str]) -> Iterator[Finding]:
        for node in body:
            yield from self._visit(ctx, node, attr_lock, held)

    def _visit(self, ctx: FileCtx, node: ast.AST,
               attr_lock: Dict[str, str],
               held: FrozenSet[str]) -> Iterator[Finding]:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held | self._with_locks(node)
            # the with-items themselves (self._lock) are evaluated
            # unlocked — fine, the lock attr is never a guarded attr
            yield from self._walk(ctx, node.body, attr_lock, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a closure may run later, outside the lock — conservative
            body = node.body if isinstance(node.body, list) else [node.body]
            yield from self._walk(ctx, body, attr_lock, frozenset())
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr in attr_lock \
                and attr_lock[node.attr] not in held:
            yield ctx.finding(
                self.name, node,
                f"self.{node.attr} is declared guarded-by "
                f"self.{attr_lock[node.attr]} but reachable outside "
                f"`with self.{attr_lock[node.attr]}`")
        for child in ast.iter_child_nodes(node):
            yield from self._visit(ctx, child, attr_lock, held)
