"""exception-contract — the typed-error vocabularies the docs promise
are what actually escapes the public seams.

The docs commit each subsystem seam to a small closed error
vocabulary: a caller of `plan_adoption` handles `SealChainError` and
nothing else; an RPC route maps EVERY typed error to an `RPCError`
with a -320xx code before it crosses the wire (docs/RPC_PARITY.md,
docs/MESH.md, docs/STORAGE.md, docs/SEALSYNC.md, docs/INGEST.md).
A new typed error that silently starts escaping one of those seams is
an API break no test catches until a peer sees a 500 instead of a
-32005.

Model (interprocedural, over the shared Project graph): for every
project function, the set of PROJECT-DEFINED exception classes it may
let escape — direct `raise X(...)`, bare `raise` inside a handler
(re-raises the caught types), and propagation from resolved callees —
computed to fixpoint, with `try/except` subtracting the types each
handler catches (a handler catches a class, its project subclasses,
and everything whose builtin ancestry it names; `except Exception` and
bare `except` catch all). Builtin exceptions are OUT of scope: the
vocabulary contract is about the typed errors this repo mints.
Unresolved calls contribute nothing (fail-fewer-assumptions, like
verdict-taint) — the dynamic seams this misses are pinned by the
suite's error-path tests.

A finding fires on a SEAM function whose escape set contains a type
outside its documented vocabulary (subclasses of a documented type are
fine — `SealRejected` IS-A `SealChainError`). The seam table below is
the machine-readable copy of the docs' promises; updating a doc's
error vocabulary means updating it here in the same PR.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from . import FileCtx, Finding

_PKG = "cometbft_tpu"

# seam (function/method/class qualname — a class means every public
# method) -> documented escape vocabulary (project exception
# qualnames). Source of truth: the docs cited per entry.
SEAMS: Dict[str, FrozenSet[str]] = {
    # docs/RPC_PARITY.md: every typed error is mapped to an RPCError
    # -320xx before it crosses the JSON-RPC wire
    f"{_PKG}.rpc.server.Routes": frozenset({
        f"{_PKG}.rpc.server.RPCError"}),
    # docs/MESH.md: shape refusal is MeshShapeError (defined in
    # parallel/mesh.py, re-exported by mesh/topology.py), queue shed
    # is MeshOverloaded — nothing else typed crosses the submit seam
    f"{_PKG}.mesh.executor.MeshExecutor.submit": frozenset({
        f"{_PKG}.mesh.executor.MeshOverloaded",
        f"{_PKG}.parallel.mesh.MeshShapeError"}),
    f"{_PKG}.mesh.topology.MeshTopology": frozenset({
        f"{_PKG}.parallel.mesh.MeshShapeError"}),
    # docs/STORAGE.md: unrepairable damage is a typed RecoveryError
    # refusing boot
    f"{_PKG}.store.recovery.run_doctor": frozenset({
        f"{_PKG}.store.recovery.RecoveryError"}),
    # docs/SEALSYNC.md: chain verification speaks SealChainError;
    # the provider sheds with SealsyncOverloaded
    f"{_PKG}.sealsync.chain.plan_adoption": frozenset({
        f"{_PKG}.sealsync.chain.SealChainError"}),
    f"{_PKG}.sealsync.chain.SealTuple.decode": frozenset({
        f"{_PKG}.sealsync.chain.SealChainError"}),
    f"{_PKG}.sealsync.provider.SealProvider": frozenset({
        f"{_PKG}.sealsync.provider.SealsyncOverloaded",
        f"{_PKG}.sealsync.chain.SealChainError"}),
    # docs/SEALSYNC.md: adoption failure is AdoptionError (the caller
    # logs and falls through to plain blocksync); seal rejection rides
    # the SealChainError family
    f"{_PKG}.sealsync.adopter.SealAdopter.adopt": frozenset({
        f"{_PKG}.sealsync.adopter.AdoptionError",
        f"{_PKG}.sealsync.chain.SealChainError"}),
    # docs/INGEST.md: the admission queue sheds with IngestShed;
    # a structurally-invalid envelope is MalformedTx (a ValueError —
    # RPC maps it to -32603 with the other malformed shapes)
    f"{_PKG}.ingest.admission.IngestPipeline.submit": frozenset({
        f"{_PKG}.ingest.admission.IngestShed",
        f"{_PKG}.ingest.tx.MalformedTx"}),
    f"{_PKG}.ingest.admission.IngestPipeline.submit_nowait": frozenset({
        f"{_PKG}.ingest.admission.IngestShed",
        f"{_PKG}.ingest.tx.MalformedTx"}),
}

_CATCH_ALL = {"Exception", "BaseException"}


class _Summary:
    __slots__ = ("raises",)

    def __init__(self):
        self.raises: Set[str] = set()   # project exception qualnames


class ExceptionContractRule:
    name = "exception-contract"
    doc = ("a project-typed exception escapes a documented public seam "
           "outside its promised vocabulary — catch it and map it "
           "(RPC: to an RPCError -320xx) per docs/STATICCHECK.md §v3")
    roots: Tuple[str, ...] = (f"{_PKG}",)
    exempt: frozenset = frozenset()
    tree_rule = True
    needs_project = True

    def __init__(self):
        self.used_pragmas: Set[Tuple[str, int, str]] = set()

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return any(path == top or path.startswith(top + "/")
                   for top in self.roots)

    def check(self, ctx: FileCtx):
        return ()

    # -- class facts -----------------------------------------------------

    def _build_hierarchy(self, project) -> None:
        """exception qualname -> its ancestor names, project qualnames
        AND builtin base names mixed (for handler matching)."""
        self._ancestors: Dict[str, Set[str]] = {}
        self._exc_classes: Set[str] = set()
        for qn, cls in project.classes.items():
            anc: Set[str] = {qn}
            stack = [qn]
            seen = set()
            while stack:
                c = stack.pop()
                if c in seen or c not in project.classes:
                    continue
                seen.add(c)
                info = project.classes[c]
                for b in info.bases:
                    anc.add(b)
                    stack.append(b)
                for bnode in info.node.bases:
                    if isinstance(bnode, ast.Name) \
                            and f"{info.module}.{bnode.id}" \
                            not in project.classes:
                        anc.add(bnode.id)   # builtin (or unresolved)
            self._ancestors[qn] = anc
            if anc & {"Exception", "BaseException", "ValueError",
                      "RuntimeError", "TypeError", "KeyError",
                      "OSError", "ConnectionError", "IOError",
                      "ArithmeticError", "LookupError"}:
                self._exc_classes.add(qn)

    def _resolve_class(self, project, func, node) -> Optional[str]:
        qn = project._symbol_for_expr(node, func.path)
        if qn in project.classes:
            return qn
        if isinstance(node, ast.Name):
            local = f"{func.module}.{node.id}"
            if local in project.classes:
                return local
        return None

    def _handler_catches(self, project, func,
                         handler: ast.ExceptHandler
                         ) -> Tuple[Set[str], bool]:
        """(builtin/base names this handler names, catches_all)."""
        if handler.type is None:
            return set(), True
        nodes = handler.type.elts \
            if isinstance(handler.type, ast.Tuple) else [handler.type]
        names: Set[str] = set()
        for n in nodes:
            qn = self._resolve_class(project, func, n)
            if qn is not None:
                names.add(qn)
                continue
            if isinstance(n, ast.Name):
                if n.id in _CATCH_ALL:
                    return set(), True
                names.add(n.id)
            elif isinstance(n, ast.Attribute):
                names.add(n.attr)
        return names, False

    def _caught(self, raised: str, handler_names: Set[str]) -> bool:
        return bool(self._ancestors.get(raised, {raised})
                    & handler_names)

    # -- per-function raise collection ------------------------------------

    def _collect(self, project, func, summaries, targets) -> Set[str]:
        out: Set[str] = set()

        def handled(types: Set[str],
                    stack: List[Tuple[Set[str], bool]]) -> Set[str]:
            surv = set(types)
            for names, all_ in stack:
                if all_:
                    return set()
                surv = {t for t in surv
                        if not self._caught(t, names)}
            return surv

        def calls_in(node, stack) -> None:
            """Propagate resolved callees' escape sets for every call
            under an EXPRESSION (never descends into nested defs)."""
            if node is None:
                return
            for n in ast.walk(node):
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                if isinstance(n, ast.Call):
                    for t in targets.get(id(n), ()):
                        s = summaries.get(t)
                        if s is not None:
                            out.update(handled(set(s.raises), stack))

        def walk(stmts, stack, caught_here: Set[str]) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.Try):
                    hspecs = [self._handler_catches(project, func, h)
                              for h in stmt.handlers]
                    inner = stack + hspecs
                    walk(stmt.body, inner, caught_here)
                    # a raise in `else` is not caught by this try's
                    # handlers — only the outer stack applies
                    walk(stmt.orelse, stack, caught_here)
                    for h, (names, all_) in zip(stmt.handlers, hspecs):
                        # types this arm may hold when a bare `raise`
                        # re-raises: the project exceptions it names
                        # (catch-all re-raise of an unresolved type is
                        # out of model)
                        held = {n for n in names
                                if n in project.classes}
                        walk(h.body, stack, held)
                    walk(stmt.finalbody, stack, caught_here)
                    continue
                if isinstance(stmt, ast.Raise):
                    if stmt.exc is None:
                        out.update(handled(set(caught_here), stack))
                    else:
                        exc = stmt.exc
                        target = exc.func \
                            if isinstance(exc, ast.Call) else exc
                        qn = self._resolve_class(project, func, target)
                        if qn is not None and qn in self._exc_classes:
                            out.update(handled({qn}, stack))
                        elif isinstance(exc, ast.Name):
                            # `raise e` of the handler's bound name
                            out.update(handled(set(caught_here),
                                               stack))
                        calls_in(stmt.exc, stack)
                        calls_in(stmt.cause, stack)
                    continue
                if isinstance(stmt, ast.If):
                    calls_in(stmt.test, stack)
                    walk(stmt.body, stack, caught_here)
                    walk(stmt.orelse, stack, caught_here)
                    continue
                if isinstance(stmt, ast.While):
                    calls_in(stmt.test, stack)
                    walk(stmt.body, stack, caught_here)
                    walk(stmt.orelse, stack, caught_here)
                    continue
                if isinstance(stmt, ast.For):
                    calls_in(stmt.iter, stack)
                    walk(stmt.body, stack, caught_here)
                    walk(stmt.orelse, stack, caught_here)
                    continue
                if isinstance(stmt, ast.With):
                    for item in stmt.items:
                        calls_in(item.context_expr, stack)
                    walk(stmt.body, stack, caught_here)
                    continue
                calls_in(stmt, stack)

        walk(func.node.body, [], set())
        return out

    # -- driver -----------------------------------------------------------

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        if project is None:
            return
        from .lock_rules import _call_targets
        self._build_hierarchy(project)
        funcs = [f for f in project.functions.values()
                 if self.applies_to(f.path)]
        targets = {f.qualname: _call_targets(project, f)
                   for f in funcs}
        summaries: Dict[str, _Summary] = {f.qualname: _Summary()
                                          for f in funcs}
        for _ in range(len(funcs)):
            changed = False
            for f in funcs:
                s = summaries[f.qualname]
                got = self._collect(project, f, summaries,
                                    targets[f.qualname])
                if got - s.raises:
                    s.raises |= got
                    changed = True
            if not changed:
                break
        findings: List[Finding] = []
        for f in funcs:
            allowed = self._allowed_for(f)
            if allowed is None:
                continue
            allowed_closure = {q for q in self._exc_classes
                               if self._ancestors.get(q, set())
                               & allowed} | allowed
            escaping = summaries[f.qualname].raises - allowed_closure
            if not escaping:
                continue
            ctx = project.ctxs.get(f.path)
            names = ", ".join(sorted(q.rsplit(".", 1)[-1]
                                     for q in escaping))
            findings.append(ctx.finding(
                self.name, f.node,
                f"{f.qualname.rsplit('.', 2)[-2]}."
                f"{f.name}() lets undocumented typed error(s) "
                f"escape: {names} — the documented vocabulary here "
                f"is {{{', '.join(sorted(a.rsplit('.', 1)[-1] for a in self._allowed_for(f)))}}}; "
                f"catch and map (or extend the docs AND the seam "
                f"table together)"))
        for fnd in sorted(findings,
                          key=lambda x: (x.path, x.line, x.message)):
            yield fnd

    def _allowed_for(self, func) -> Optional[FrozenSet[str]]:
        got = SEAMS.get(func.qualname)
        if got is not None:
            return got
        if func.cls is not None and func.cls in SEAMS \
                and not func.name.startswith("_"):
            return SEAMS[func.cls]
        return None
