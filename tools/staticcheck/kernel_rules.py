"""kernel-discipline — the ops/ int32-Montgomery contract, enforced.

The BASELINE >=1M sigs/s path exists because every ops/ kernel obeys
four rules the TPU layout depends on (ops/field.py's module
docstring): all integer work stays in int32/uint32 (TPU emulates s64
as u32 pairs), python ints never leak into traced code, control flow
inside a trace is static (shapes/dtypes only — data-dependent branches
either crash at trace time or silently unroll wrong, the r02
shape-broadcast crash class), and host<->device boundaries pin their
dtypes explicitly (`np.asarray` without a dtype makes platform-int64
constants on linux). Until now that was convention; this rule walks
the ops/ call graph from every `jax.jit` / `lax.scan` / `fori_loop` /
`pallas_call` entry and enforces it on exactly the functions a trace
can reach.

TRACED SCOPE: entry functions' parameters are traced except
`static_argnames`; tracedness propagates through call sites (an
argument computed from traced values marks the callee's parameter
traced, to fixpoint) — so `pt_decompress(pub, zip215=True)` keeps
`zip215` static while `pub` stays traced. Values derived from
`.shape` / `.ndim` / `.dtype` / `.size` / `len()` are STATIC (that is
the supported way to branch). Functions defined inside a traced
function (scan bodies, pallas kernels) are traced with all parameters.

FLAGGED inside traced scope:
  * `if`/`while` on a traced value        -> jnp.where / lax.cond
  * `int()` / `float()` / `bool()` on a traced value
  * any `int64` / `uint64` / `float64` dtype mention
  * `np.asarray` / `np.array` without an explicit dtype= (and any
    numpy materialization OF a traced value)
  * arithmetic mixing a traced value with a python-int literal >= 2^31
    (silent int64 promotion)

Host-side helpers in ops/ that no entry reaches (batch marshalling,
table precomputation, module constants) are deliberately out of scope.

SCOPE: ops/ plus the mesh data plane — parallel/ and mesh/ hold the
jit entries of the sharded production path (make_*_sharded_verifier's
nested @jax.jit closures) and their shard_map-mapped bodies, which
run per-device under exactly the same int32 contract. `shard_map` is
a tracing wrapper here (callable arg 0), including the from-imported
`_shard_map` alias parallel/verify uses across the jax API rename.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from . import FileCtx, Finding

OPS_PREFIX = "cometbft_tpu/ops/"
KERNEL_PREFIXES = (OPS_PREFIX, "cometbft_tpu/parallel/",
                   "cometbft_tpu/mesh/")

_JIT_NAMES = {"jax.jit", "jax.api.jit"}
_WRAP_ARGPOS = {          # callable-arg positions of tracing wrappers
    "scan": (0,),
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "pallas_call": (0,),
    "cond": (1, 2),
    "shard_map": (0,),
}
_WRAP_MODULES = ("jax.lax", "jax", "jax.experimental.pallas",
                 "jax.experimental.pallas.tpu",
                 "jax.experimental.shard_map")
_BAD_DTYPES = {"int64", "uint64", "float64"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_COERCIONS = {"int", "float", "bool"}
_INT32_MAX = 2 ** 31


class _Fn:
    """One analyzable function body: a project-level ops/ function or
    a nested def inside one."""

    __slots__ = ("key", "path", "node", "ctx", "parent", "nested",
                 "traced_params", "analyzed_with")

    def __init__(self, key: str, path: str, node, ctx: FileCtx,
                 parent: Optional["_Fn"]):
        self.key = key
        self.path = path
        self.node = node
        self.ctx = ctx
        self.parent = parent
        self.nested: Dict[str, "_Fn"] = {}
        self.traced_params: Set[str] = set()
        self.analyzed_with: Optional[frozenset] = None

    def params(self) -> List[str]:
        a = self.node.args
        return [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]


class KernelDisciplineRule:
    name = "kernel-discipline"
    doc = ("int64/python-int/data-dependent-control-flow/unpinned-"
           "dtype inside ops/ code reachable from a jax.jit / "
           "lax.scan / pallas entry — the int32 TPU contract "
           "(ops/field.py, docs/STATICCHECK.md)")
    roots: Tuple[str, ...] = tuple(p.rstrip("/")
                                   for p in KERNEL_PREFIXES)
    exempt: frozenset = frozenset()
    tree_rule = True
    needs_project = True

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return path.startswith(KERNEL_PREFIXES)

    def check(self, ctx: FileCtx):
        return ()

    # --- helpers: name resolution against a file's imports ----------------

    @staticmethod
    def _dotted(ctx: FileCtx, node: ast.AST) -> Optional[str]:
        """Fully-qualified dotted name of a Name/Attribute expression
        via the file's import aliases ('jnp.int64' -> 'jax.numpy.int64')."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = ctx.from_imports.get(node.id)
        if base is None:
            mod = ctx.module_aliases.get(node.id)
            base = mod if mod is not None else node.id
        parts.append(base)
        return ".".join(reversed(parts))

    def _is_jit(self, ctx: FileCtx, fn: ast.AST) -> bool:
        dn = self._dotted(ctx, fn)
        return dn in _JIT_NAMES or dn == "jit" \
            or (dn is not None and dn.endswith(".jit")
                and dn.startswith("jax"))

    def _wrap_positions(self, ctx: FileCtx,
                        fn: ast.AST) -> Optional[Tuple[int, ...]]:
        if isinstance(fn, ast.Name):
            # from-imported wrapper (`from jax import shard_map as
            # _shard_map`): resolve the alias to its dotted origin
            dn = ctx.from_imports.get(fn.id)
            if dn is not None and dn.startswith("jax"):
                leaf = dn.rsplit(".", 1)[-1]
                if leaf in _WRAP_ARGPOS:
                    return _WRAP_ARGPOS[leaf]
            return None
        if not isinstance(fn, ast.Attribute) \
                or fn.attr not in _WRAP_ARGPOS:
            return None
        base = self._dotted(ctx, fn.value)
        if base is not None and any(
                base == m or base.startswith(m + ".")
                for m in _WRAP_MODULES):
            return _WRAP_ARGPOS[fn.attr]
        return None

    @staticmethod
    def _static_argnames(call: ast.Call) -> Set[str]:
        out: Set[str] = set()
        for kw in call.keywords:
            if kw.arg != "static_argnames":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        out.add(e.value)
        return out

    @staticmethod
    def _callable_name(node: ast.AST) -> Optional[ast.AST]:
        """The function expression inside a wrapper arg — unwraps
        functools.partial(f, ...)."""
        if isinstance(node, ast.Call) and node.args:
            fn = node.func
            nm = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if nm == "partial":
                return node.args[0]
            return None
        return node

    # --- the analysis -----------------------------------------------------

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        if project is None:
            return
        # registry of every ops/ function INCLUDING nested defs
        fns: Dict[str, _Fn] = {}

        def register(path: str, ctx: FileCtx, node, parent,
                     prefix: str) -> None:
            for child in (node.body if hasattr(node, "body") else ()):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    key = f"{prefix}.{child.name}"
                    fn = _Fn(key, path, child, ctx,
                             parent if isinstance(parent, _Fn) else None)
                    fns[key] = fn
                    if isinstance(parent, _Fn):
                        parent.nested[child.name] = fn
                    register(path, ctx, child, fn, key)
                elif isinstance(child, ast.ClassDef):
                    register(path, ctx, child, None,
                             f"{prefix}.{child.name}")

        ops_ctxs = {p: c for p, c in project.ctxs.items()
                    if self.applies_to(p)}
        for path, ctx in sorted(ops_ctxs.items()):
            from .graph import module_name
            register(path, ctx, ctx.tree, None, module_name(path))

        # --- collect entries ---------------------------------------------
        # (fn key, traced param names)
        worklist: List[Tuple[_Fn, Set[str]]] = []

        def local_lookup(scope: Optional[_Fn], ctx: FileCtx, path: str,
                         name_node: ast.AST) -> Optional[_Fn]:
            target = self._callable_name(name_node)
            if not isinstance(target, ast.Name):
                return None
            name = target.id
            s = scope
            while s is not None:
                if name in s.nested:
                    return s.nested[name]
                s = s.parent
            from .graph import module_name
            return fns.get(f"{module_name(path)}.{name}")

        def entry(fn: _Fn, static: Set[str]) -> None:
            traced = {p for p in fn.params()
                      if p not in static and p != "self"}
            worklist.append((fn, traced))

        for path, ctx in sorted(ops_ctxs.items()):
            # decorators
            from .graph import module_name
            for key, fn in list(fns.items()):
                if fn.path != path:
                    continue
                for dec in fn.node.decorator_list:
                    if self._is_jit(ctx, dec):
                        entry(fn, set())
                    elif isinstance(dec, ast.Call):
                        inner = dec.args[0] if dec.args else None
                        if self._is_jit(ctx, dec.func):
                            entry(fn, self._static_argnames(dec))
                        elif inner is not None and \
                                self._is_jit(ctx, inner):
                            entry(fn, self._static_argnames(dec))
            # jit(...) calls anywhere in the file
            enclosing: Dict[int, _Fn] = {}
            for key, fn in fns.items():
                if fn.path != path:
                    continue
                for sub in ast.walk(fn.node):
                    enclosing.setdefault(id(sub), fn)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                scope = enclosing.get(id(node))
                if self._is_jit(ctx, node.func) and node.args:
                    target = local_lookup(scope, ctx, path,
                                          node.args[0])
                    if target is not None:
                        static = self._static_argnames(node)
                        traced = {p for p in target.params()
                                  if p not in static and p != "self"}
                        worklist.append((target, traced))
                    continue
                pos = self._wrap_positions(ctx, node.func)
                if pos is not None:
                    for i in pos:
                        if i < len(node.args):
                            target = local_lookup(scope, ctx, path,
                                                  node.args[i])
                            if target is not None:
                                worklist.append(
                                    (target, set(target.params())))

        # --- reachability + traced-param propagation ----------------------
        findings: List[Finding] = []
        while worklist:
            fn, traced = worklist.pop()
            want = traced | fn.traced_params
            key = frozenset(want)
            if fn.analyzed_with == key:
                continue
            fn.traced_params = set(want)
            fn.analyzed_with = key
            for callee, callee_traced in self._analyze(
                    project, fns, fn, findings):
                worklist.append((callee, callee_traced))

        seen = set()
        for f in sorted(findings, key=lambda x: (x.path, x.line,
                                                 x.message)):
            k = (f.path, f.line, f.message)
            if k not in seen:
                seen.add(k)
                yield f

    # --- per-function traced walk -----------------------------------------

    def _analyze(self, project, fns: Dict[str, _Fn], fn: _Fn,
                 findings: List[Finding]
                 ) -> List[Tuple[_Fn, Set[str]]]:
        ctx = fn.ctx
        traced: Set[str] = set(fn.traced_params)
        out_calls: List[Tuple[_Fn, Set[str]]] = []
        # resolution context: a nested def (scan body, pallas kernel)
        # is not in the project symbol table — climb to the enclosing
        # module-level function/method, whose file-scope imports and
        # module are identical
        pinfo = project.functions.get(fn.key)
        climb = fn
        while pinfo is None and climb.parent is not None:
            climb = climb.parent
            pinfo = project.functions.get(climb.key)

        def is_traced(node: ast.AST) -> bool:
            if isinstance(node, ast.Name):
                return node.id in traced
            if isinstance(node, ast.Attribute):
                if node.attr in _STATIC_ATTRS:
                    return False
                return is_traced(node.value)
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name) and f.id == "len":
                    return False
                for a in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    if is_traced(a):
                        return True
                if isinstance(f, ast.Attribute):
                    return is_traced(f.value)
                return False
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                return False
            return any(is_traced(c) for c in ast.iter_child_nodes(node))

        def flag(node: ast.AST, msg: str) -> None:
            findings.append(ctx.finding(self.name, node, msg))

        def resolve_callee(call: ast.Call) -> List[_Fn]:
            t = call.func
            got: List[_Fn] = []
            if isinstance(t, ast.Name):
                local = None
                s: Optional[_Fn] = fn
                while s is not None:
                    if t.id in s.nested:
                        local = s.nested[t.id]
                        break
                    s = s.parent
                if local is not None:
                    return [local]
            if pinfo is not None:
                for q in project.resolve_call(pinfo, call):
                    target = fns.get(q)
                    if target is not None:
                        got.append(target)
            return got

        def branch_traced(test: ast.AST) -> bool:
            # membership tests (`k not in acc`) stay python-side even
            # when the container holds traced values — dict/set keys
            # are static by construction in kernel code (a true
            # `x in jnp_array` fails loudly at trace time anyway)
            if isinstance(test, ast.Compare) and all(
                    isinstance(op, (ast.In, ast.NotIn))
                    for op in test.ops):
                return False
            return is_traced(test)

        class V(ast.NodeVisitor):
            def visit_If(self, node):         # noqa: N802
                if branch_traced(node.test):
                    flag(node, "data-dependent python `if` on a "
                               "traced value — a trace can't branch "
                               "on data; use jnp.where / lax.cond / "
                               "lax.select")
                self.generic_visit(node)

            def visit_While(self, node):      # noqa: N802
                if branch_traced(node.test):
                    flag(node, "data-dependent python `while` on a "
                               "traced value — use lax.while_loop / "
                               "lax.fori_loop")
                self.generic_visit(node)

            def visit_IfExp(self, node):      # noqa: N802
                if branch_traced(node.test):
                    flag(node, "data-dependent conditional expression "
                               "on a traced value — use jnp.where")
                self.generic_visit(node)

            def visit_Assign(self, node):     # noqa: N802
                if is_traced(node.value):
                    for t in node.targets:
                        _mark_target(t, traced)
                self.generic_visit(node)

            def visit_AugAssign(self, node):  # noqa: N802
                if is_traced(node.value) and \
                        isinstance(node.target, ast.Name):
                    traced.add(node.target.id)
                self.generic_visit(node)

            def visit_For(self, node):        # noqa: N802
                if is_traced(node.iter):
                    _mark_target(node.target, traced)
                self.generic_visit(node)

            def visit_Attribute(self, node):  # noqa: N802
                if node.attr in _BAD_DTYPES:
                    flag(node, f"{node.attr} in kernel code — ops/ is "
                               f"int32/uint32 only (TPU emulates 64-"
                               f"bit; ops/field.py layout contract)")
                self.generic_visit(node)

            def visit_Constant(self, node):   # noqa: N802
                if isinstance(node.value, str) \
                        and node.value in _BAD_DTYPES:
                    flag(node, f"dtype string {node.value!r} in "
                               f"kernel code — ops/ is int32/uint32 "
                               f"only")

            def visit_BinOp(self, node):      # noqa: N802
                for a, b in ((node.left, node.right),
                             (node.right, node.left)):
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, int) \
                            and not isinstance(a.value, bool) \
                            and abs(a.value) >= _INT32_MAX \
                            and is_traced(b):
                        flag(node, f"python-int literal {a.value} in "
                                   f"arithmetic with a traced value — "
                                   f"promotes to int64; split into "
                                   f"int32-safe limbs")
                        break
                self.generic_visit(node)

            def visit_Call(self, node):       # noqa: N802
                f = node.func
                if isinstance(f, ast.Name) and f.id in _COERCIONS \
                        and node.args and is_traced(node.args[0]):
                    flag(node, f"{f.id}() concretizes a traced value "
                               f"— python-scalar leakage breaks the "
                               f"trace (the r02 crash class)")
                dn = KernelDisciplineRule._dotted(ctx, f)
                if dn in ("numpy.asarray", "numpy.array"):
                    if any(is_traced(a) for a in node.args):
                        flag(node, "numpy materialization of a traced "
                                   "value inside a kernel — keep it "
                                   "jnp, or hoist to the host "
                                   "boundary")
                    elif not any(kw.arg == "dtype"
                                 for kw in node.keywords):
                        flag(node, "np.asarray/np.array without "
                                   "dtype= in traced code — platform-"
                                   "dependent int64 default; pin the "
                                   "dtype")
                # propagate tracedness into resolved callees
                for callee in resolve_callee(node):
                    cps = callee.params()
                    t: Set[str] = set()
                    for i, a in enumerate(node.args):
                        if i < len(cps) and is_traced(a):
                            t.add(cps[i])
                    for kw in node.keywords:
                        if kw.arg in cps and is_traced(kw.value):
                            t.add(kw.arg)
                    if not (t <= callee.traced_params
                            and callee.analyzed_with is not None):
                        out_calls.append((callee, t))
                self.generic_visit(node)

        V().visit(self.node_body_holder(fn))
        return out_calls

    @staticmethod
    def node_body_holder(fn: _Fn) -> ast.AST:
        # visit the function's own body only: nested defs are separate
        # _Fn entries analyzed when reached (locally called with traced
        # args, or force-traced when passed to a tracing wrapper)
        return ast.Module(
            body=[s for s in fn.node.body
                  if not isinstance(s, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))],
            type_ignores=[])


def _mark_target(t: ast.AST, traced: Set[str]) -> None:
    """Mark assignment-target base names traced — never a subscript
    INDEX (`acc[k] = traced` taints acc, not k)."""
    if isinstance(t, ast.Name):
        traced.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _mark_target(e, traced)
    elif isinstance(t, ast.Starred):
        _mark_target(t.value, traced)
    elif isinstance(t, (ast.Subscript, ast.Attribute)):
        base = t.value
        while isinstance(base, (ast.Subscript, ast.Attribute)):
            base = base.value
        if isinstance(base, ast.Name):
            traced.add(base.id)
