"""kernel-interval — interval-domain abstract interpretation proving
the int32 no-overflow contract over every ops/ kernel path.

PR 9's kernel-discipline rule pattern-matches the int32 Montgomery
discipline (no int64 mentions, no >= 2**31 literals); it cannot prove
that a limb product plus carry accumulator actually stays below 2**31
on every reachable path — the silent-wraparound class that corrupts a
verdict without tripping a canary. This rule interprets the kernel
sources abstractly, mirroring jax tracing: concrete python host values
execute concretely (unrolled range loops, shape arithmetic, module
constants), traced arrays carry integer intervals per dtype.

Domain
  - `IV(lo, hi)`: integer interval (python ints, saturating sentinels).
  - `Arr(dtype, shape, rows, iv)`: abstract array. `rows` tracks one
    interval per leading-axis index when the leading dim is concrete —
    load-bearing for CIOS fixpoint convergence (mont_mul's per-limb
    accumulator rows converge where a single hull would not).
  - Symbolic batch dims are `SymDim`s bounded [1, 2**40] by default;
    `assert` statements refine them (sc_dot_mod_l's
    `assert la + lb <= 30 and n <= (1 << 15)` is what makes its
    batch-sum provably int32-safe, exactly as its docstring claims).

Policy
  - int32-typed results escaping [-2**31, 2**31) are findings carrying
    the computed bounds and the interpretation call path.
  - uint32 arithmetic wraps mod 2**32 BY DESIGN (sha512's two-word
    adds); the transfer keeps the exact interval when it fits and
    silently widens to [0, 2**32) otherwise. uint32→int32 astype is
    still checked for fit.
  - `# staticcheck: assume(x, lo, hi[, shape=][, dtype=])` pragmas are
    checked, not trusted: computed ⊆ assumed proves the pragma;
    disjoint is a contradiction finding; overlap refines the value AND
    registers a runtime obligation that tools/interval_fuzz.py
    re-checks on concrete shadow executions. On entry params (pragma
    lines between `def` and the first body statement) they are the
    preconditions the fuzzer samples inside.
  - lax.scan / fori_loop / while_loop and python `while` on symbolic
    conditions run join-to-fixpoint (cap, then widening to the dtype
    range); small concrete fori/scan bodies unroll for precision.

Entries are every jax.jit target in ops/ (decorators, module-level
jit() assignments, and jit() closures inside lru_cached factories,
whose params seed from assume() pragmas or the unique module constant
every call site passes). See docs/STATICCHECK.md §v3.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field as dc_field
from typing import (Any, Callable, Dict, Iterable, Iterator, List,
                    Optional, Set, Tuple)

from . import Assume, FileCtx, Finding

INF = 1 << 140          # saturating "unbounded" sentinel
I32_LO, I32_HI = -(1 << 31), (1 << 31) - 1
DTYPE_RANGE: Dict[str, Tuple[int, int]] = {
    "int32": (I32_LO, I32_HI),
    "uint32": (0, (1 << 32) - 1),
    "int64": (-(1 << 63), (1 << 63) - 1),
    "uint64": (0, (1 << 64) - 1),
    "uint8": (0, 255),
    "int8": (-128, 127),
    "int16": (-(1 << 15), (1 << 15) - 1),
    "uint16": (0, (1 << 16) - 1),
    "bool": (0, 1),
}
# dtypes whose arithmetic wraps silently by design (modular packing);
# int32 is the CONTRACT dtype: escapes are findings, never wraps.
_WRAP_DTYPES = {"uint32", "uint8", "uint16", "uint64", "int8", "int16"}
DEFAULT_DIM_HI = 1 << 40    # unrefined symbolic batch dim upper bound
ROWS_MAX = 1024             # leading-axis row tracking cap
UNROLL_MAX = 128            # concrete fori/scan unroll cap
JOIN_CAP = 64               # plain fixpoint joins before widening
WIDEN_EXTRA = 8             # widened iterations before giving up
CONCRETE_WHILE_CAP = 8192   # concrete python-loop runaway guard


def _clamp(v: int) -> int:
    return -INF if v < -INF else (INF if v > INF else v)


class IV:
    """Closed integer interval [lo, hi], saturating at +-INF."""
    __slots__ = ("lo", "hi")

    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = _clamp(lo), _clamp(hi)

    def __repr__(self):
        def s(v):
            return "-inf" if v <= -INF else ("+inf" if v >= INF else str(v))
        return f"[{s(self.lo)}, {s(self.hi)}]"

    def __eq__(self, other):
        return isinstance(other, IV) and self.lo == other.lo \
            and self.hi == other.hi

    def __hash__(self):
        return hash((self.lo, self.hi))

    @property
    def exact(self) -> Optional[int]:
        return self.lo if self.lo == self.hi else None

    def join(self, o: "IV") -> "IV":
        return IV(min(self.lo, o.lo), max(self.hi, o.hi))

    def meet(self, o: "IV") -> Optional["IV"]:
        lo, hi = max(self.lo, o.lo), min(self.hi, o.hi)
        return IV(lo, hi) if lo <= hi else None

    def inside(self, lo: int, hi: int) -> bool:
        return lo <= self.lo and self.hi <= hi

    def widen(self, new: "IV", dtype: Optional[str]) -> "IV":
        dlo, dhi = DTYPE_RANGE.get(dtype or "", (-INF, INF))
        lo = self.lo if new.lo >= self.lo else min(dlo, new.lo)
        hi = self.hi if new.hi <= self.hi else max(dhi, new.hi)
        return IV(lo, hi)


def iv_of(v: Any) -> IV:
    if isinstance(v, IV):
        return v
    if isinstance(v, bool):
        return IV(int(v), int(v))
    if isinstance(v, int):
        return IV(v, v)
    if isinstance(v, SymDim):
        return v.bound
    if isinstance(v, Arr):
        return v.iv
    raise TypeError(f"no interval for {type(v).__name__}")


def _minmax(*vals: int) -> IV:
    return IV(min(vals), max(vals))


def iv_add(a: IV, b: IV) -> IV:
    return IV(a.lo + b.lo, a.hi + b.hi)


def iv_sub(a: IV, b: IV) -> IV:
    return IV(a.lo - b.hi, a.hi - b.lo)


def iv_mul(a: IV, b: IV) -> IV:
    return _minmax(a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi)


def iv_floordiv(a: IV, b: IV) -> Optional[IV]:
    # split the divisor range around zero; empty nonzero part -> None
    cands: List[int] = []
    for blo, bhi in ((max(b.lo, 1), b.hi), (b.lo, min(b.hi, -1))):
        if blo > bhi:
            continue
        cands += [a.lo // blo, a.lo // bhi, a.hi // blo, a.hi // bhi]
    return _minmax(*cands) if cands else None


def iv_mod(a: IV, b: IV) -> Optional[IV]:
    # python semantics: sign follows the divisor
    if b.lo >= 1:
        if a.lo >= 0 and a.hi < b.lo and b.exact is not None:
            return IV(a.lo, a.hi)      # already reduced
        return IV(0, b.hi - 1)
    if b.hi <= -1:
        return IV(b.lo + 1, 0)
    return None


def iv_lshift(a: IV, b: IV) -> Optional[IV]:
    if b.lo < 0 or b.hi >= 512:
        return None
    return _minmax(a.lo << b.lo, a.lo << b.hi,
                   a.hi << b.lo, a.hi << b.hi)


def iv_rshift(a: IV, b: IV) -> Optional[IV]:
    if b.lo < 0:
        return None
    bhi = min(b.hi, 512)
    return _minmax(a.lo >> b.lo, a.lo >> bhi,
                   a.hi >> b.lo, a.hi >> bhi)


def iv_and(a: IV, b: IV) -> IV:
    if a.exact is not None and b.exact is not None:
        v = a.exact & b.exact
        return IV(v, v)
    # a non-negative mask bounds the result in [0, mask] regardless of
    # the other side's sign (two's complement)
    if b.lo >= 0:
        return IV(0, b.hi if a.lo < 0 else min(a.hi, b.hi))
    if a.lo >= 0:
        return IV(0, a.hi if b.lo < 0 else min(a.hi, b.hi))
    return IV(min(a.lo, b.lo), max(a.hi, b.hi))


def _pow2_ceil(v: int) -> int:
    return (1 << v.bit_length()) - 1 if v > 0 else 0


def iv_or(a: IV, b: IV) -> IV:
    if a.exact is not None and b.exact is not None:
        v = a.exact | b.exact
        return IV(v, v)
    if a.lo >= 0 and b.lo >= 0:
        return IV(max(a.lo, b.lo), _pow2_ceil(max(a.hi, b.hi)))
    return IV(min(a.lo, b.lo), max(a.hi, b.hi, -1))


def iv_xor(a: IV, b: IV) -> IV:
    if a.exact is not None and b.exact is not None:
        v = a.exact ^ b.exact
        return IV(v, v)
    if a.lo >= 0 and b.lo >= 0:
        return IV(0, _pow2_ceil(max(a.hi, b.hi)))
    m = max(abs(a.lo), abs(a.hi), abs(b.lo), abs(b.hi))
    bound = _pow2_ceil(m) + 1
    return IV(-bound, bound)


_IV_BINOPS: Dict[type, Callable[[IV, IV], Optional[IV]]] = {
    ast.Add: iv_add, ast.Sub: iv_sub, ast.Mult: iv_mul,
    ast.FloorDiv: iv_floordiv, ast.Mod: iv_mod,
    ast.LShift: iv_lshift, ast.RShift: iv_rshift,
    ast.BitAnd: iv_and, ast.BitOr: iv_or, ast.BitXor: iv_xor,
}


class SymDim:
    """A symbolic array dimension with a refinable bound. Identity is
    object identity: the same assume() shape symbol within one entry
    names the same dim. `assert` comparisons tighten `bound` — sound
    because a trace-time assert guards every concrete execution."""
    __slots__ = ("name", "bound")

    def __init__(self, name: str, bound: Optional[IV] = None):
        self.name = name
        self.bound = bound or IV(1, DEFAULT_DIM_HI)

    def __repr__(self):
        return f"<{self.name}{self.bound}>"


Dim = Any   # int | SymDim | IV


def dim_iv(d: Dim) -> IV:
    return iv_of(d)


def dim_eq(a: Dim, b: Dim) -> bool:
    if isinstance(a, int) and isinstance(b, int):
        return a == b
    return a is b


def unify_dim(a: Dim, b: Dim) -> Optional[Dim]:
    """Broadcast-unify two dims (1 broadcasts; equal survives; a
    concrete int refines a symbolic dim — jax would have raised on a
    real mismatch, so taking the concrete side is sound)."""
    if isinstance(a, int):
        if a == 1:
            return b
        if isinstance(b, int):
            return a if (a == b or b == 1) else None
        return a
    if isinstance(b, int):
        return unify_dim(b, a)
    return a    # two symbolic dims: assume equal (trace would check)


def broadcast_shapes(*shapes: Tuple[Dim, ...]) -> Optional[Tuple[Dim, ...]]:
    rank = max((len(s) for s in shapes), default=0)
    out: List[Dim] = []
    for i in range(rank):
        d: Dim = 1
        for s in shapes:
            j = i - (rank - len(s))
            if j < 0:
                continue
            u = unify_dim(d, s[j])
            if u is None:
                return None
            d = u
        out.append(d)
    return tuple(out)


def shape_numel(shape: Tuple[Dim, ...]) -> Optional[int]:
    n = 1
    for d in shape:
        if not isinstance(d, int):
            return None
        n *= d
    return n


class Arr:
    """Abstract jax array: dtype tag, shape, optional per-leading-axis
    row intervals, and the hull interval. Immutable — every transfer
    returns a new Arr."""
    __slots__ = ("dtype", "shape", "rows", "iv")

    def __init__(self, dtype: str, shape: Tuple[Dim, ...],
                 rows: Optional[List[IV]], iv: IV):
        self.dtype = dtype
        self.shape = tuple(shape)
        if rows is not None and (not self.shape
                                 or not isinstance(self.shape[0], int)
                                 or len(rows) != self.shape[0]
                                 or len(rows) > ROWS_MAX):
            rows = None
        self.rows = rows
        if rows:
            iv = rows[0]
            for r in rows[1:]:
                iv = iv.join(r)
        self.iv = iv

    def __repr__(self):
        return f"Arr({self.dtype}, {self.shape}, {self.iv})"

    def row_list(self) -> Optional[List[IV]]:
        """Rows, materializing a uniform list when the leading dim is
        concrete and small — lets strided slices stay exact even after
        a row-discarding op."""
        if self.rows is not None:
            return list(self.rows)
        if self.shape and isinstance(self.shape[0], int) \
                and self.shape[0] <= ROWS_MAX:
            return [self.iv] * self.shape[0]
        return None

    @property
    def ndim(self) -> int:
        return len(self.shape)

    def sig(self):
        return ("a", self.dtype, shape_sig(self.shape),
                tuple((r.lo, r.hi) for r in self.rows)
                if self.rows is not None else None,
                (self.iv.lo, self.iv.hi))


def shape_sig(shape: Tuple[Dim, ...]):
    return tuple(d if isinstance(d, int)
                 else ("s", id(d)) if isinstance(d, SymDim)
                 else ("v", d.lo, d.hi) for d in shape)


class Opaque:
    """Analysis hole. Creating one inside an entry interpretation is a
    reportable gap in the proof (the creator calls Interp.unknown)."""
    __slots__ = ("reason",)

    def __init__(self, reason: str):
        self.reason = reason

    def __repr__(self):
        return f"Opaque({self.reason})"


class Unknown:
    """Three-valued truth for static flags (zip215/interpret) and
    undecidable comparisons: `if` joins both branches."""
    __slots__ = ("why",)

    def __init__(self, why: str = ""):
        self.why = why

    def __repr__(self):
        return f"Unknown({self.why})"


class ModuleVal:
    """Reference to an accelerator-API module namespace (jnp/lax/...)."""
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name


class DtypeVal:
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name


class Clo:
    """A function value: AST + captured scopes + home module."""
    __slots__ = ("node", "scopes", "mod", "qual", "path")

    def __init__(self, node, scopes, mod, qual, path):
        self.node = node          # FunctionDef | Lambda
        self.scopes = scopes      # captured enclosing scopes (inner first)
        self.mod = mod            # ModScope
        self.qual = qual
        self.path = path


class RealFn:
    """Host function executed for real when every argument is concrete
    (numpy/math/libs helpers and ops host helpers)."""
    __slots__ = ("fn", "name")

    def __init__(self, fn, name):
        self.fn, self.name = fn, name


class Bound:
    """Bound method / intrinsic attribute awaiting its call."""
    __slots__ = ("kind", "recv", "name")

    def __init__(self, kind: str, recv: Any, name: str):
        self.kind, self.recv, self.name = kind, recv, name


class Partial:
    __slots__ = ("fn", "args", "kwargs")

    def __init__(self, fn, args, kwargs):
        self.fn, self.args, self.kwargs = fn, args, kwargs


class Jitted:
    """jax.jit(f) result; calling it calls f. The rule also treats its
    creation as an analysis entry."""
    __slots__ = ("clo", "static")

    def __init__(self, clo: Clo, static: Tuple[str, ...]):
        self.clo, self.static = clo, static


class SDS:
    """jax.ShapeDtypeStruct."""
    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape, self.dtype = tuple(shape), dtype


class BlockSpec:
    __slots__ = ("block_shape", "index_map")

    def __init__(self, block_shape=None, index_map=None):
        self.block_shape = tuple(block_shape) if block_shape else None
        self.index_map = index_map


class VMEM:
    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape, self.dtype = tuple(shape), dtype


_BOTTOM = IV(INF, -INF)     # "never written" ref-row sentinel


class Ref:
    """Mutable pallas ref cell: per-row content with strong updates on
    concrete leading-axis indices, weak (join) updates otherwise."""
    __slots__ = ("dtype", "shape", "rows", "hull", "written")

    def __init__(self, dtype: str, shape: Tuple[Dim, ...],
                 init: Optional[Arr] = None):
        self.dtype = dtype
        self.shape = tuple(shape)
        n = shape[0] if shape and isinstance(shape[0], int) \
            and shape[0] <= ROWS_MAX else None
        if init is not None:
            self.rows = init.row_list() if n else None
            self.hull: Optional[IV] = init.iv
            self.written = True
        else:
            self.rows = [_BOTTOM] * n if n else None
            self.hull = None
            self.written = False

    def value(self) -> Optional[Arr]:
        if not self.written:
            return None
        rows = None
        if self.rows is not None:
            live = [r for r in self.rows if r is not _BOTTOM]
            if not live:
                return None
            hull = live[0]
            for r in live[1:]:
                hull = hull.join(r)
            rows = [hull if r is _BOTTOM else r for r in self.rows]
            return Arr(self.dtype, self.shape, rows, hull)
        return Arr(self.dtype, self.shape, None, self.hull or _BOTTOM)


# --- value plumbing -------------------------------------------------------

def vjoin(a: Any, b: Any) -> Any:
    """Structural join of two abstract values."""
    if a is None and b is None:
        return None
    if isinstance(a, Opaque):
        return a
    if isinstance(b, Opaque):
        return b
    if a is b:
        return a
    if isinstance(a, Arr) and isinstance(b, Arr):
        shape = broadcast_shapes(a.shape, b.shape)
        if shape is None or a.dtype != b.dtype:
            return Arr(a.dtype, a.shape, None, a.iv.join(b.iv))
        ra, rb = a.rows, b.rows
        rows = None
        if ra is not None and rb is not None and len(ra) == len(rb):
            rows = [x.join(y) for x, y in zip(ra, rb)]
        return Arr(a.dtype, shape, rows, a.iv.join(b.iv))
    if isinstance(a, (int, bool, IV, SymDim)) \
            and isinstance(b, (int, bool, IV, SymDim)):
        ia, ib = iv_of(a), iv_of(b)
        if isinstance(a, int) and isinstance(b, int) and a == b:
            return a
        return ia.join(ib)
    if isinstance(a, tuple) and isinstance(b, tuple) and len(a) == len(b):
        return tuple(vjoin(x, y) for x, y in zip(a, b))
    if isinstance(a, list) and isinstance(b, list) and len(a) == len(b):
        return [vjoin(x, y) for x, y in zip(a, b)]
    if isinstance(a, dict) and isinstance(b, dict) \
            and set(a.keys()) == set(b.keys()):
        return {k: vjoin(a[k], b[k]) for k in a}
    if isinstance(a, str) and a == b:
        return a
    if isinstance(a, Unknown) or isinstance(b, Unknown):
        return Unknown("join")
    return Opaque(f"join of {type(a).__name__}/{type(b).__name__}")


def veq(a: Any, b: Any) -> bool:
    if a is b:
        return True
    if isinstance(a, Arr) and isinstance(b, Arr):
        return a.dtype == b.dtype and a.iv == b.iv \
            and shape_sig(a.shape) == shape_sig(b.shape) \
            and a.rows == b.rows
    if isinstance(a, IV) and isinstance(b, IV):
        return a == b
    if type(a) is not type(b):
        return isinstance(a, (int, bool)) and isinstance(b, (int, bool)) \
            and a == b
    if isinstance(a, (int, bool, str)) or a is None:
        return a == b
    if isinstance(a, (tuple, list)):
        return len(a) == len(b) and all(veq(x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        return set(a) == set(b) and all(veq(a[k], b[k]) for k in a)
    return False


def vwiden(old: Any, new: Any) -> Any:
    """Widen `old` toward `new` (dtype range for arrays)."""
    j = vjoin(old, new)
    if isinstance(j, Arr) and isinstance(old, Arr) and not veq(old, j):
        return Arr(j.dtype, j.shape, None, old.iv.widen(j.iv, j.dtype))
    if isinstance(j, IV) and isinstance(old, IV) and j != old:
        return old.widen(j, None)
    return j


def sig_of(v: Any):
    """Hashable memo signature; raises TypeError on unmemoizable
    values (Refs and friends)."""
    if isinstance(v, Arr):
        return v.sig()
    if isinstance(v, bool):
        return ("b", v)
    if isinstance(v, int):
        return ("i", v)
    if isinstance(v, IV):
        return ("v", v.lo, v.hi)
    if isinstance(v, SymDim):
        return ("d", id(v))
    if isinstance(v, (tuple, list)):
        return ("t", tuple(sig_of(x) for x in v))
    if isinstance(v, dict):
        return ("m", tuple(sorted((k, sig_of(x)) for k, x in v.items())))
    if isinstance(v, str):
        return ("s", v)
    if v is None:
        return ("n",)
    if isinstance(v, Clo):
        return ("c", id(v.node))
    if isinstance(v, DtypeVal):
        return ("dt", v.name)
    if isinstance(v, Unknown):
        return ("u",)
    if isinstance(v, slice):
        return ("sl", sig_of(v.start), sig_of(v.stop), sig_of(v.step))
    raise TypeError(f"unmemoizable {type(v).__name__}")


# --- module scopes --------------------------------------------------------

_JAX_MODULES = {
    "jax": "jax", "jax.numpy": "jax.numpy", "jax.lax": "jax.lax",
    "jax.experimental.pallas": "pallas",
    "jax.experimental.pallas.tpu": "pallas.tpu",
    "jax.tree_util": "jax.tree_util",
    "jax.experimental": "jax.experimental",
}
# modules safe to import for real inside the linter process (no jax)
_REAL_IMPORT_OK = ("numpy", "math", "functools", "cometbft_tpu.libs.",
                   "cometbft_tpu.crypto.")


def _posix_module(path: str) -> str:
    return path[:-3].replace("/", ".") if path.endswith(".py") else path


def _load_of(node: ast.expr) -> ast.expr:
    """Store-context target rewritten as a load expression (AugAssign)."""
    import copy
    n2 = copy.deepcopy(node)
    for sub in ast.walk(n2):
        if hasattr(sub, "ctx"):
            sub.ctx = ast.Load()
    return n2


def _decide(a: IV, op: ast.cmpop, b: IV) -> Any:
    if isinstance(op, ast.Lt):
        if a.hi < b.lo:
            return True
        if a.lo >= b.hi:
            return False
        return Unknown("cmp")
    if isinstance(op, ast.LtE):
        if a.hi <= b.lo:
            return True
        if a.lo > b.hi:
            return False
        return Unknown("cmp")
    if isinstance(op, ast.Gt):
        return _decide(b, ast.Lt(), a)
    if isinstance(op, ast.GtE):
        return _decide(b, ast.LtE(), a)
    if isinstance(op, ast.Eq):
        if a.exact is not None and a.exact == b.exact:
            return True
        if a.hi < b.lo or a.lo > b.hi:
            return False
        return Unknown("cmp")
    if isinstance(op, ast.NotEq):
        r = _decide(a, ast.Eq(), b)
        return (not r) if isinstance(r, bool) else r
    return Unknown("cmp")


_DT_ORDER = {"bool": 0, "uint8": 1, "int8": 1, "uint16": 2, "int16": 2,
             "int32": 3, "uint32": 3, "int64": 4, "uint64": 4}


def promote(da: Optional[str], db: Optional[str]) -> str:
    """Result dtype of a two-array op. Mixed int32/uint32 does not
    occur in the kernels (uint32 work is explicitly astype-bracketed);
    resolve it to int32 so the stricter contract applies."""
    if da is None:
        return db or "int32"
    if db is None or da == db:
        return da
    if {"int32", "uint32"} == {da, db}:
        return "int32"
    return da if _DT_ORDER.get(da, 3) >= _DT_ORDER.get(db, 3) else db


def DT_IV(dtype: str) -> IV:
    lo, hi = DTYPE_RANGE.get(dtype, (-INF, INF))
    return IV(lo, hi)


class ModScope:
    """Lazy namespace of one ops module: AST defs become Clo values,
    module-level constant assignments are evaluated by the interpreter
    itself (host python executes concretely — limbs_from_int and
    friends return exact values without importing jax)."""

    def __init__(self, analysis: "Analysis", ctx: FileCtx):
        self.analysis = analysis
        self.ctx = ctx
        self.path = ctx.path
        self.modname = _posix_module(ctx.path)
        self.names: Dict[str, Any] = {}
        self.assigns: Dict[str, ast.stmt] = {}
        self.defs: Dict[str, ast.FunctionDef] = {}
        self.imports: Dict[str, Any] = {}       # name -> resolver thunk
        self._evaluating: Set[str] = set()
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[node.name] = node
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.assigns[n.id] = node
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) and node.value:
                self.assigns[node.target.id] = node
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                self._register_import(node)

    def _register_import(self, node) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                target = a.name if a.asname else a.name.split(".")[0]
                self.imports[local] = ("module", target)
            return
        mod = node.module or ""
        if node.level:
            base = self.modname.rsplit(".", node.level)[0]
            mod = f"{base}.{mod}" if mod else base
        for a in node.names:
            self.imports[a.asname or a.name] = ("from", mod, a.name)

    def resolve_module(self, dotted: str) -> Any:
        a = self.analysis
        if dotted in _JAX_MODULES:
            return ModuleVal(_JAX_MODULES[dotted])
        peer = a.modscopes.get(dotted)
        if peer is not None:
            return peer
        if dotted.startswith(_REAL_IMPORT_OK) or dotted in (
                "numpy", "math", "functools"):
            try:
                import importlib
                return importlib.import_module(dotted)
            except Exception as e:        # noqa: BLE001 — any import
                return Opaque(f"import {dotted}: {e}")
        return Opaque(f"unmodeled module {dotted}")

    def get(self, name: str) -> Any:
        if name in self.names:
            return self.names[name]
        val: Any
        if name in self.defs:
            val = Clo(self.defs[name], [], self, name, self.path)
        elif name in self.imports:
            spec = self.imports[name]
            if spec[0] == "module":
                val = self.resolve_module(spec[1])
            else:
                _, mod, attr = spec
                dotted = f"{mod}.{attr}"
                if dotted in _JAX_MODULES \
                        or dotted in self.analysis.modscopes:
                    # `from . import edwards as ed` — the imported
                    # name is itself a module (peer or jax namespace)
                    val = self.resolve_module(dotted)
                else:
                    holder = self.resolve_module(mod)
                    val = self.analysis.interp.attr_of(holder, attr)
                    if isinstance(val, Opaque) \
                            and dotted.startswith(_REAL_IMPORT_OK):
                        val = self.resolve_module(dotted)
        elif name in self.assigns:
            if name in self._evaluating:
                return Opaque(f"circular module constant {name}")
            self._evaluating.add(name)
            try:
                val = self.analysis.interp.eval_module_assign(
                    self, self.assigns[name], name)
            finally:
                self._evaluating.discard(name)
        else:
            return Opaque(f"{self.modname} has no {name}")
        self.names[name] = val
        return val


# --- interpreter ----------------------------------------------------------

class Frame:
    __slots__ = ("scopes", "mod", "ctx", "qual", "ret", "dims")

    def __init__(self, scopes, mod: ModScope, qual: str,
                 dims: Optional[Dict[str, SymDim]] = None):
        self.scopes = scopes          # [locals, *captured]
        self.mod = mod
        self.ctx = mod.ctx
        self.qual = qual
        self.ret: Any = _NO_RET
        self.dims = dims if dims is not None else {}


class _NoRet:
    def __repr__(self):
        return "<no-return>"


_NO_RET = _NoRet()


class AnalysisError(Exception):
    """Internal interpreter bail-out; surfaces as a finding."""


_PY_BUILTINS = ("len", "range", "min", "max", "abs", "int", "bool",
                "sum", "tuple", "list", "dict", "zip", "enumerate",
                "reversed", "sorted", "bin", "pow", "divmod", "all",
                "any", "isinstance", "float", "str", "set", "round")


class Interp:
    """The abstract evaluator. One instance per Analysis run."""

    def __init__(self, analysis: "Analysis"):
        self.a = analysis
        self.stack: List[str] = []
        self.memo: Dict[Any, Tuple[Any, list]] = {}
        self.call_depth = 0
        self._host_fns: Dict[int, Any] = {}

    # -- reporting --------------------------------------------------------

    def report(self, node: Optional[ast.AST], kind: str, msg: str,
               ctx: Optional[FileCtx] = None) -> None:
        frame_ctx = ctx or (self.a.cur_ctx() if self.a else None)
        if frame_ctx is None:
            return
        line = getattr(node, "lineno", 1) if node is not None else 1
        path = frame_ctx.path
        chain = " > ".join(self.stack[-4:]) or "<module>"
        self.a.add_finding(path, line, kind, f"{msg} [via {chain}]",
                           frame_ctx)

    def unknown(self, node: Optional[ast.AST], reason: str) -> Opaque:
        if self.a.in_entry:
            self.report(node, "interval-unknown",
                        f"cannot bound this value ({reason}) — the "
                        f"int32 proof has a hole here")
        return Opaque(reason)

    # -- entry points ------------------------------------------------------

    def eval_module_assign(self, mod: ModScope, stmt: ast.stmt,
                           name: str) -> Any:
        frame = Frame([{}], mod, f"{mod.modname}:<module>")
        self.a.push_ctx(mod.ctx)
        was = self.a.in_entry
        self.a.in_entry = False     # module constants never hole the proof
        try:
            val = self.eval(stmt.value, frame)
        except AnalysisError as e:
            val = Opaque(str(e))
        except RecursionError:
            val = Opaque("recursion evaluating module constant")
        finally:
            self.a.in_entry = was
            self.a.pop_ctx()
        tgt = stmt.targets[0] if isinstance(stmt, ast.Assign) \
            else stmt.target
        if isinstance(tgt, ast.Name):
            return val
        # tuple-target module assign: bind all, then answer for `name`
        tmp = Frame([{}], mod, frame.qual)
        try:
            self.assign(tgt, val, tmp)
        except AnalysisError as e:
            return Opaque(str(e))
        return tmp.scopes[0].get(name, Opaque(f"unbound {name}"))

    def _host_fn_for(self, clo: Clo) -> Any:
        """Compile a PURE-HOST helper (touches only builtins/math/np —
        no jax, no module globals) to a real python function. Abstract
        interpretation of e.g. the cube-root fixup loop in sha512's
        round-constant derivation would need ~57k concrete iterations;
        native execution is exact and instant."""
        key = id(clo.node)
        if key in self._host_fns:
            return self._host_fns[key]
        fn = None
        fnode = clo.node
        if isinstance(fnode, ast.FunctionDef) \
                and not fnode.decorator_list \
                and not any(isinstance(n, (ast.Yield, ast.YieldFrom,
                                           ast.Await, ast.Global,
                                           ast.Nonlocal))
                            for n in ast.walk(fnode)):
            bound = {a.arg for a in (fnode.args.posonlyargs
                                     + fnode.args.args
                                     + fnode.args.kwonlyargs)}
            for n in ast.walk(fnode):
                if isinstance(n, ast.Name) \
                        and isinstance(n.ctx, (ast.Store, ast.Del)):
                    bound.add(n.id)
                elif isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and n is not fnode:
                    bound.add(n.name)
            used = {n.id for n in ast.walk(fnode)
                    if isinstance(n, ast.Name)}
            allowed = set(_PY_BUILTINS) | {"math", "np", "numpy",
                                           "Tuple", "List", "Optional"}
            if used - bound <= allowed:
                import math as _math
                ns: Dict[str, Any] = {"math": _math, "Tuple": tuple,
                                      "List": list, "Optional": None}
                try:
                    import numpy as _np
                    ns["np"] = ns["numpy"] = _np
                except ImportError:
                    pass
                mod = ast.Module(body=[fnode], type_ignores=[])
                ast.fix_missing_locations(mod)
                try:
                    exec(compile(mod, clo.path, "exec"), ns)  # noqa: S102
                    fn = ns.get(fnode.name)
                except Exception:       # noqa: BLE001
                    fn = None
        self._host_fns[key] = fn
        return fn

    def call_clo(self, clo: Clo, args: List[Any],
                 kwargs: Dict[str, Any], node: Optional[ast.AST]) -> Any:
        self.a.covered.add(f"{clo.path}::{clo.qual}")
        host = self._host_fn_for(clo)
        if host is not None:
            try:
                cargs = [self.to_concrete(a) for a in args]
                ckw = {k: self.to_concrete(v)
                       for k, v in kwargs.items()}
            except TypeError:
                host = None
            if host is not None:
                try:
                    return self.to_abstract(host(*cargs, **ckw))
                except AnalysisError:
                    raise
                except Exception as e:  # noqa: BLE001
                    raise AnalysisError(
                        f"host helper {clo.qual} raised: {e}")
        key = None
        try:
            # scope-dict identity distinguishes closures of the same
            # def captured from different factory invocations
            key = (id(clo.node),
                   tuple(id(s) for s in clo.scopes),
                   tuple(sig_of(a) for a in args),
                   tuple(sorted((k, sig_of(v)) for k, v in kwargs.items())))
        except TypeError:
            pass
        if key is not None and key in self.memo:
            ret, recorded = self.memo[key][:2]
            for rec in recorded:
                self.a.replay(rec)
            return ret
        if self.call_depth > 60:
            raise AnalysisError(f"call depth exceeded at {clo.qual}")
        frame = Frame([{}] + list(clo.scopes), clo.mod, clo.qual)
        self.bind_params(clo, args, kwargs, frame, node)
        self.stack.append(clo.qual)
        self.call_depth += 1
        self.a.push_ctx(clo.mod.ctx)
        cap = self.a.push_capture()
        try:
            if isinstance(clo.node, ast.Lambda):
                ret = self.eval(clo.node.body, frame)
            else:
                flow = self.exec_block(clo.node.body, frame)
                ret = frame.ret if frame.ret is not _NO_RET else None
                if flow == "fall" and frame.ret is not _NO_RET:
                    ret = vjoin(frame.ret, None) \
                        if self._may_fall_off(clo.node) else frame.ret
        except AnalysisError as e:
            if not getattr(e, "stack", None):
                e.stack = list(self.stack)
            raise
        finally:
            recorded = self.a.pop_capture(cap)
            self.a.pop_ctx()
            self.call_depth -= 1
            self.stack.pop()
        if key is not None:
            # pin every object whose id() appears in the key (scope dicts,
            # SymDims/Clos inside args) — otherwise GC can recycle an address
            # and a later closure aliases a dead frame's memo entry
            self.memo[key] = (ret, recorded, (clo.scopes, args, kwargs))
        return ret

    @staticmethod
    def _may_fall_off(node) -> bool:
        last = node.body[-1] if node.body else None
        return not isinstance(last, ast.Return)

    def bind_params(self, clo: Clo, args: List[Any],
                    kwargs: Dict[str, Any], frame: Frame,
                    node: Optional[ast.AST]) -> None:
        a = clo.node.args
        names = [p.arg for p in a.posonlyargs + a.args]
        local = frame.scopes[0]
        if len(args) > len(names) and a.vararg is None:
            raise AnalysisError(
                f"too many args for {clo.qual}: {len(args)}")
        for i, name in enumerate(names):
            if i < len(args):
                local[name] = args[i]
            elif name in kwargs:
                local[name] = kwargs.pop(name)
        if a.vararg is not None:
            local[a.vararg.arg] = tuple(args[len(names):])
        # defaults for the tail
        defaults = a.defaults
        for i, d in enumerate(defaults):
            name = names[len(names) - len(defaults) + i]
            if name not in local:
                local[name] = self.eval(d, frame)
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            if p.arg in kwargs:
                local[p.arg] = kwargs.pop(p.arg)
            elif d is not None:
                local[p.arg] = self.eval(d, frame)
            else:
                raise AnalysisError(
                    f"missing kwonly {p.arg} for {clo.qual}")
        if a.kwarg is not None:
            local[a.kwarg.arg] = dict(kwargs)
            kwargs.clear()
        if kwargs:
            raise AnalysisError(
                f"unexpected kwargs {sorted(kwargs)} for {clo.qual}")
        missing = [n for n in names if n not in local]
        if missing:
            raise AnalysisError(
                f"missing args {missing} for {clo.qual}")

    # -- statements --------------------------------------------------------

    def exec_block(self, stmts: List[ast.stmt], frame: Frame) -> str:
        for stmt in stmts:
            flow = self.exec_stmt(stmt, frame)
            if flow != "fall":
                return flow
        return "fall"

    def exec_stmt(self, stmt: ast.stmt, frame: Frame) -> str:
        if isinstance(stmt, ast.Assign):
            val = self.eval(stmt.value, frame)
            val = self.apply_assumes(stmt, val, frame)
            for t in stmt.targets:
                self.assign(t, val, frame)
            return "fall"
        if isinstance(stmt, ast.AugAssign):
            cur = self.eval(_load_of(stmt.target), frame)
            rhs = self.eval(stmt.value, frame)
            val = self.binop(cur, stmt.op, rhs, stmt)
            val = self.apply_assumes(stmt, val, frame)
            self.assign(stmt.target, val, frame)
            return "fall"
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                val = self.eval(stmt.value, frame)
                val = self.apply_assumes(stmt, val, frame)
                self.assign(stmt.target, val, frame)
            return "fall"
        if isinstance(stmt, ast.Expr):
            if not isinstance(stmt.value, ast.Constant):
                self.eval(stmt.value, frame)
            return "fall"
        if isinstance(stmt, ast.Return):
            val = self.eval(stmt.value, frame) \
                if stmt.value is not None else None
            val = self.apply_assumes(stmt, val, frame, returning=True)
            frame.ret = val if frame.ret is _NO_RET \
                else vjoin(frame.ret, val)
            return "return"
        if isinstance(stmt, ast.If):
            return self.exec_if(stmt, frame)
        if isinstance(stmt, ast.For):
            return self.exec_for(stmt, frame)
        if isinstance(stmt, ast.While):
            return self.exec_while(stmt, frame)
        if isinstance(stmt, ast.Assert):
            self.exec_assert(stmt.test, frame)
            return "fall"
        if isinstance(stmt, ast.FunctionDef):
            frame.scopes[0][stmt.name] = Clo(
                stmt, frame.scopes, frame.mod,
                f"{frame.qual}.{stmt.name}", frame.mod.path)
            return "fall"
        if isinstance(stmt, (ast.Pass, ast.Import, ast.ImportFrom)):
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self.exec_import(stmt, frame)
            return "fall"
        if isinstance(stmt, ast.Break):
            return "break"
        if isinstance(stmt, ast.Continue):
            return "continue"
        if isinstance(stmt, ast.Raise):
            return "return"     # abandon the path; no value joins
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    frame.scopes[0].pop(t.id, None)
            return "fall"
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                v = self.eval(item.context_expr, frame)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, v, frame)
            return self.exec_block(stmt.body, frame)
        if isinstance(stmt, ast.Try):
            flow = self.exec_block(stmt.body, frame)
            if flow == "fall":
                flow = self.exec_block(stmt.orelse, frame)
            f2 = self.exec_block(stmt.finalbody, frame)
            return f2 if f2 != "fall" else flow
        raise AnalysisError(
            f"unhandled statement {type(stmt).__name__} at "
            f"{frame.ctx.path}:{stmt.lineno}")

    def exec_import(self, stmt, frame: Frame) -> None:
        """Function-local import: resolve through the module machinery
        (ed25519's local `from .pallas_verify import ...`)."""
        tmp = ModScope.__new__(ModScope)
        tmp.analysis = self.a
        tmp.modname = frame.mod.modname
        tmp.imports = {}
        ModScope._register_import(tmp, stmt)
        for local, spec in tmp.imports.items():
            if spec[0] == "module":
                frame.scopes[0][local] = frame.mod.resolve_module(spec[1])
            else:
                _, mod, attr = spec
                holder = frame.mod.resolve_module(mod)
                frame.scopes[0][local] = self.attr_of(holder, attr)

    def apply_assumes(self, stmt: ast.stmt, val: Any, frame: Frame,
                      returning: bool = False) -> Any:
        """Check (never trust) assume() pragmas on this statement:
        computed ⊆ assumed proves it; disjoint is a contradiction;
        overlap refines + registers a runtime obligation for
        tools/interval_fuzz.py."""
        specs = frame.ctx.assumes_at(stmt.lineno)
        if not specs:
            return val
        names: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)) \
                and isinstance(stmt.target, ast.Name):
            names.add(stmt.target.id)
        for spec in specs:
            if not returning and spec.var not in names:
                continue
            self.a.used_assumes.add((frame.ctx.path, spec.line))
            try:
                got = iv_of(val)
            except TypeError:
                self.report(stmt, "assume-unverifiable",
                            f"assume({spec.var}, ...) on a value with "
                            f"no interval ({type(val).__name__})")
                continue
            want = IV(spec.lo, spec.hi)
            if got.inside(spec.lo, spec.hi):
                continue    # statically proven; nothing to refine
            met = got.meet(want)
            if met is None:
                self.report(stmt, "assume-contradiction",
                            f"assume({spec.var}, {spec.lo}, {spec.hi}) "
                            f"contradicts computed bounds {got}")
                continue
            self.a.add_obligation(frame, spec, stmt, got)
            if isinstance(val, Arr):
                rows = None if val.rows is None else \
                    [r.meet(want) or IV(spec.lo, spec.lo)
                     for r in val.rows]
                val = Arr(val.dtype, val.shape, rows, met)
            elif isinstance(val, (int, IV)):
                val = met
        return val

    def assign(self, target: ast.expr, val: Any, frame: Frame) -> None:
        if isinstance(target, ast.Name):
            frame.scopes[0][target.id] = val
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            items = self.unpack(val, len(target.elts), target)
            star = [i for i, e in enumerate(target.elts)
                    if isinstance(e, ast.Starred)]
            if star:
                raise AnalysisError("starred unpack unsupported")
            for el, item in zip(target.elts, items):
                self.assign(el, item, frame)
            return
        if isinstance(target, ast.Subscript):
            recv = self.eval(target.value, frame)
            idx = self.eval_index(target.slice, frame)
            self.store_item(recv, idx, val, target)
            return
        raise AnalysisError(
            f"unhandled assign target {type(target).__name__}")

    def unpack(self, val: Any, n: int, node) -> List[Any]:
        if isinstance(val, (tuple, list)):
            if len(val) != n:
                raise AnalysisError(
                    f"unpack arity {len(val)} != {n}")
            return list(val)
        if isinstance(val, Arr) and val.shape \
                and isinstance(val.shape[0], int) and val.shape[0] == n:
            return [self.index_axis0(val, i, node) for i in range(n)]
        if isinstance(val, Opaque):
            return [val] * n
        raise AnalysisError(f"cannot unpack {type(val).__name__}")

    def store_item(self, recv: Any, idx: Any, val: Any, node) -> None:
        if isinstance(recv, Ref):
            self.ref_store(recv, idx, val, node)
            return
        if isinstance(recv, list):
            if isinstance(idx, bool) or not isinstance(idx, int):
                raise AnalysisError("abstract list index store")
            recv[idx] = val
            return
        if isinstance(recv, dict):
            try:
                hash(idx)
            except TypeError:
                raise AnalysisError("unhashable dict key")
            recv[idx] = val
            return
        if isinstance(recv, Opaque):
            return
        if isinstance(recv, Arr):
            # host-numpy arrays alias their buffer, so an in-place store is
            # the faithful model; only concrete int/slice leading-axis
            # indices are handled — anything else stays a hard error
            rows = recv.row_list()
            if rows is not None:
                if isinstance(idx, slice):
                    try:
                        rng = range(*idx.indices(len(rows)))
                    except TypeError:
                        rng = None
                    if rng is not None:
                        if isinstance(val, Arr) and val.ndim == recv.ndim:
                            vrows = val.row_list()
                            if vrows is None or len(vrows) != len(rng):
                                vrows = [val.iv] * len(rng)
                        else:
                            vrows = [iv_of(val)] * len(rng)
                        for k, i in enumerate(rng):
                            rows[i] = vrows[k]
                        self._rewrite_rows(recv, rows)
                        return
                elif isinstance(idx, int) and not isinstance(idx, bool):
                    n = len(rows)
                    if -n <= idx < n:
                        rows[idx] = val.iv if isinstance(val, Arr) \
                            else iv_of(val)
                        self._rewrite_rows(recv, rows)
                        return
                    raise AnalysisError(
                        f"store index {idx} out of range for ({n}, ...)")
        raise AnalysisError(
            f"cannot store into {type(recv).__name__} at line "
            f"{getattr(node, 'lineno', '?')}")

    @staticmethod
    def _rewrite_rows(recv: "Arr", rows: List[IV]) -> None:
        recv.rows = rows
        iv = rows[0]
        for r in rows[1:]:
            iv = iv.join(r)
        recv.iv = iv

    # -- control flow ------------------------------------------------------

    def snapshot(self, frame: Frame) -> Dict[str, Any]:
        out = {}
        for k, v in frame.scopes[0].items():
            if isinstance(v, list):
                v = list(v)
            elif isinstance(v, dict):
                v = dict(v)
            out[k] = v
        return out

    def restore(self, frame: Frame, snap: Dict[str, Any]) -> None:
        frame.scopes[0] = {
            k: (list(v) if isinstance(v, list)
                else dict(v) if isinstance(v, dict) else v)
            for k, v in snap.items()}

    @staticmethod
    def join_env(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        out = {}
        for k in set(a) | set(b):
            if k in a and k in b:
                out[k] = vjoin(a[k], b[k])
            # a name bound on only one path stays unbound in the join
        return out

    @staticmethod
    def env_eq(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
        return set(a) == set(b) and all(veq(a[k], b[k]) for k in a)

    @staticmethod
    def widen_env(old: Dict[str, Any], new: Dict[str, Any]) \
            -> Dict[str, Any]:
        out = {}
        for k in set(old) & set(new):
            out[k] = vwiden(old[k], new[k])
        return out

    def truth(self, v: Any) -> Optional[bool]:
        if isinstance(v, bool):
            return v
        if isinstance(v, int):
            return v != 0
        if v is None:
            return False
        if isinstance(v, (str, tuple, list, dict)):
            return bool(v)
        if isinstance(v, (IV, SymDim)):
            iv = iv_of(v)
            if iv.lo > 0 or iv.hi < 0:
                return True
            if iv.lo == iv.hi == 0:
                return False
            return None
        if isinstance(v, (Unknown, Opaque, Arr)):
            return None
        return None

    def exec_if(self, stmt: ast.If, frame: Frame) -> str:
        t = self.truth(self.eval(stmt.test, frame))
        if t is True:
            return self.exec_block(stmt.body, frame)
        if t is False:
            return self.exec_block(stmt.orelse, frame)
        base = self.snapshot(frame)
        flow1 = self.exec_block(stmt.body, frame)
        env1 = self.snapshot(frame)
        self.restore(frame, base)
        flow2 = self.exec_block(stmt.orelse, frame)
        env2 = self.snapshot(frame)
        if flow1 == "fall" and flow2 == "fall":
            self.restore(frame, self.join_env(env1, env2))
            return "fall"
        if flow1 == "fall":
            self.restore(frame, env1)
            return "fall"
        if flow2 == "fall":
            self.restore(frame, env2)
            return "fall"
        if flow1 == flow2:
            return flow1
        # mixed return/break/continue across an unknown branch: treat
        # as falling through with the join — over-approximate but sound
        self.restore(frame, self.join_env(env1, env2))
        return "fall"

    def exec_for(self, stmt: ast.For, frame: Frame) -> str:
        it = self.eval(stmt.iter, frame)
        items = self.concrete_iter(it)
        if items is not None:
            if len(items) > CONCRETE_WHILE_CAP:
                raise AnalysisError("concrete for-loop too long")
            for item in items:
                self.assign(stmt.target, item, frame)
                flow = self.exec_block(stmt.body, frame)
                if flow == "break":
                    return "fall"
                if flow == "return":
                    return "return"
            return self.exec_block(stmt.orelse, frame)
        # symbolic iterable: fixpoint with the target bound to a hull
        hull = self.iter_hull(it, stmt)

        def body_once() -> str:
            self.assign(stmt.target, hull, frame)
            return self.exec_block(stmt.body, frame)

        self.fix_loop(body_once, frame)
        return "fall"

    def exec_while(self, stmt: ast.While, frame: Frame) -> str:
        for _ in range(CONCRETE_WHILE_CAP):
            t = self.truth(self.eval(stmt.test, frame))
            if t is None:
                break
            if t is False:
                return self.exec_block(stmt.orelse, frame)
            flow = self.exec_block(stmt.body, frame)
            if flow == "break":
                return "fall"
            if flow == "return":
                return "return"
        else:
            raise AnalysisError("concrete while-loop did not terminate")

        def body_once() -> str:
            self.eval(stmt.test, frame)
            return self.exec_block(stmt.body, frame)

        self.fix_loop(body_once, frame)
        return "fall"

    def fix_loop(self, body_once: Callable[[], str],
                 frame: Frame) -> None:
        """Join-to-fixpoint on the innermost scope; findings recorded
        along the way overwrite earlier, smaller-bound duplicates (the
        findings store dedups by site), so the stabilized iteration's
        report is the one that survives."""
        inv = self.snapshot(frame)
        for it in range(JOIN_CAP + WIDEN_EXTRA):
            self.restore(frame, inv)
            flow = body_once()
            if flow == "return":
                # a symbolic-loop return joins into frame.ret already
                pass
            after = self.snapshot(frame)
            new = self.join_env(inv, after)
            if self.env_eq(new, inv):
                break
            inv = self.widen_env(inv, new) if it >= JOIN_CAP else new
        else:
            raise AnalysisError("loop fixpoint did not converge")
        self.restore(frame, inv)

    def concrete_iter(self, it: Any) -> Optional[List[Any]]:
        if isinstance(it, (list, tuple)):
            return list(it)
        if isinstance(it, str):
            return list(it)
        if isinstance(it, dict):
            return list(it.keys())
        if isinstance(it, range):
            return list(it)
        return None

    def iter_hull(self, it: Any, node) -> Any:
        if isinstance(it, Arr):
            return self.index_axis0(it, None, node)
        if isinstance(it, Opaque):
            return it
        raise AnalysisError(
            f"cannot iterate {type(it).__name__}")

    def exec_assert(self, test: ast.expr, frame: Frame) -> None:
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            for clause in test.values:
                self.exec_assert(clause, frame)
            return
        t = self.truth(self.eval(test, frame))
        if t is False:
            self.report(test, "assert-false",
                        "assert provably fails under computed bounds")
        if t is not None:
            return
        # refinement: `n <= C`, `n < C`, `C >= n`, `n == C` on a local
        # whose value is a SymDim or IV tightens the bound — a trace-
        # time assert guards every concrete execution, so leaning on it
        # is sound (sc_dot_mod_l's batch-sum proof needs exactly this).
        if not isinstance(test, ast.Compare) or len(test.ops) != 1:
            return
        left, op, right = test.left, test.ops[0], test.comparators[0]
        if not isinstance(left, ast.Name):
            if isinstance(right, ast.Name):
                left, right = right, left
                flip = {ast.Lt: ast.Gt, ast.Gt: ast.Lt,
                        ast.LtE: ast.GtE, ast.GtE: ast.LtE,
                        ast.Eq: ast.Eq, ast.NotEq: ast.NotEq}
                if type(op) not in flip:
                    return
                op = flip[type(op)]()
            else:
                return
        try:
            bound = iv_of(self.eval(right, frame))
        except (TypeError, AnalysisError):
            return
        cur = frame.scopes[0].get(left.id)
        if cur is None:
            return
        if isinstance(op, ast.LtE):
            ref = IV(-INF, bound.hi)
        elif isinstance(op, ast.Lt):
            ref = IV(-INF, bound.hi - 1)
        elif isinstance(op, ast.GtE):
            ref = IV(bound.lo, INF)
        elif isinstance(op, ast.Gt):
            ref = IV(bound.lo + 1, INF)
        elif isinstance(op, ast.Eq):
            ref = bound
        else:
            return
        if isinstance(cur, SymDim):
            met = cur.bound.meet(ref)
            if met is not None:
                cur.bound = met
        elif isinstance(cur, IV):
            met = cur.meet(ref)
            if met is not None:
                frame.scopes[0][left.id] = met

    # -- expressions -------------------------------------------------------

    def eval(self, node: ast.expr, frame: Frame) -> Any:
        if isinstance(node, ast.Constant):
            return node.value
        if isinstance(node, ast.Name):
            return self.lookup(node, frame)
        if isinstance(node, ast.Tuple):
            return tuple(self.eval_splice(node.elts, frame))
        if isinstance(node, ast.List):
            return list(self.eval_splice(node.elts, frame))
        if isinstance(node, ast.Dict):
            out = {}
            for k, v in zip(node.keys, node.values):
                if k is None:
                    raise AnalysisError("dict ** splat unsupported")
                out[self.eval(k, frame)] = self.eval(v, frame)
            return out
        if isinstance(node, ast.Set):
            return set(self.eval_splice(node.elts, frame))
        if isinstance(node, ast.BinOp):
            return self.binop(self.eval(node.left, frame), node.op,
                              self.eval(node.right, frame), node)
        if isinstance(node, ast.UnaryOp):
            return self.unaryop(node, frame)
        if isinstance(node, ast.BoolOp):
            return self.boolop(node, frame)
        if isinstance(node, ast.Compare):
            return self.compare(node, frame)
        if isinstance(node, ast.IfExp):
            t = self.truth(self.eval(node.test, frame))
            if t is True:
                return self.eval(node.body, frame)
            if t is False:
                return self.eval(node.orelse, frame)
            return vjoin(self.eval(node.body, frame),
                         self.eval(node.orelse, frame))
        if isinstance(node, ast.Call):
            return self.call(node, frame)
        if isinstance(node, ast.Attribute):
            return self.attr_of(self.eval(node.value, frame),
                                node.attr, node)
        if isinstance(node, ast.Subscript):
            recv = self.eval(node.value, frame)
            idx = self.eval_index(node.slice, frame)
            return self.load_item(recv, idx, node)
        if isinstance(node, ast.Lambda):
            return Clo(node, frame.scopes, frame.mod,
                       f"{frame.qual}.<lambda>@{node.lineno}",
                       frame.mod.path)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            vals = self.comprehension(node, frame)
            return set(vals) if isinstance(node, ast.SetComp) else \
                (list(vals) if isinstance(node, ast.ListComp)
                 else tuple(vals))
        if isinstance(node, ast.DictComp):
            out = {}
            for env in self.comp_envs(node.generators, frame):
                out[self.eval(node.key, env)] = \
                    self.eval(node.value, env)
            return out
        if isinstance(node, ast.NamedExpr):
            val = self.eval(node.value, frame)
            self.assign(node.target, val, frame)
            return val
        if isinstance(node, ast.Starred):
            raise AnalysisError("bare starred expression")
        if isinstance(node, ast.JoinedStr):
            return "<fstring>"
        raise AnalysisError(
            f"unhandled expression {type(node).__name__} at "
            f"{frame.ctx.path}:{getattr(node, 'lineno', '?')}")

    def eval_splice(self, elts, frame: Frame) -> List[Any]:
        out: List[Any] = []
        for el in elts:
            if isinstance(el, ast.Starred):
                seq = self.eval(el.value, frame)
                if not isinstance(seq, (tuple, list)):
                    raise AnalysisError("starred non-sequence")
                out.extend(seq)
            else:
                out.append(self.eval(el, frame))
        return out

    def comprehension(self, node, frame: Frame) -> List[Any]:
        return [self.eval(node.elt, env)
                for env in self.comp_envs(node.generators, frame)]

    def comp_envs(self, gens, frame: Frame,
                  i: int = 0) -> Iterator[Frame]:
        if i == len(gens):
            yield frame
            return
        g = gens[i]
        items = self.concrete_iter(self.eval(g.iter, frame))
        if items is None:
            raise AnalysisError("comprehension over symbolic iterable")
        for item in items:
            self.assign(g.target, item, frame)
            if all(self.truth(self.eval(cond, frame)) is True
                   for cond in g.ifs):
                yield from self.comp_envs(gens, frame, i + 1)

    def lookup(self, node: ast.Name, frame: Frame) -> Any:
        for scope in frame.scopes:
            if node.id in scope:
                return scope[node.id]
        if node.id in frame.dims:
            return frame.dims[node.id]
        mod_val = frame.mod.get(node.id)
        if not isinstance(mod_val, Opaque):
            return mod_val
        if node.id in _PY_BUILTINS:
            return Bound("builtin", None, node.id)
        if node.id in ("True", "False", "None"):
            return {"True": True, "False": False, "None": None}[node.id]
        return self.unknown(node, f"unresolved name {node.id!r}")

    # -- operators ---------------------------------------------------------

    def binop(self, a: Any, op: ast.operator, b: Any, node) -> Any:
        if isinstance(a, Opaque) or isinstance(b, Opaque):
            return a if isinstance(a, Opaque) else b
        # pure host python: lists/tuples/strings concatenate, repeat
        if isinstance(op, ast.Add) and isinstance(a, (list, tuple, str)) \
                and isinstance(b, (list, tuple, str)):
            return a + b
        if isinstance(op, ast.Mult) and (
                isinstance(a, (list, tuple, str)) and isinstance(b, int)):
            return a * b
        if isinstance(op, ast.Mult) and (
                isinstance(b, (list, tuple, str)) and isinstance(a, int)):
            return b * a
        if isinstance(a, (int, bool)) and isinstance(b, (int, bool)):
            return self.concrete_binop(a, op, b, node)
        if isinstance(a, (int, bool, float)) \
                and isinstance(b, (int, bool, float)):
            if self.a.in_entry:
                # floats never enter the int32 contract; host module
                # constants (frac(cbrt(p)) seeds etc.) compute freely
                raise AnalysisError("float arithmetic in kernel path")
            return self.concrete_binop(a, op, b, node)
        if isinstance(a, float) or isinstance(b, float):
            raise AnalysisError("float arithmetic in kernel path")
        if isinstance(a, Arr) or isinstance(b, Arr):
            return self.arr_binop(a, op, b, node)
        # scalar abstract (IV / SymDim mixed with int)
        try:
            ia, ib = iv_of(a), iv_of(b)
        except TypeError:
            raise AnalysisError(
                f"binop on {type(a).__name__}/{type(b).__name__}")
        fn = _IV_BINOPS.get(type(op))
        if fn is None:
            raise AnalysisError(
                f"unhandled operator {type(op).__name__}")
        out = fn(ia, ib)
        if out is None:
            return self.unknown(node, "unbounded scalar op")
        return out.exact if out.exact is not None else out

    def concrete_binop(self, a, op, b, node) -> Any:
        try:
            return {
                ast.Add: lambda: a + b, ast.Sub: lambda: a - b,
                ast.Mult: lambda: a * b, ast.FloorDiv: lambda: a // b,
                ast.Mod: lambda: a % b, ast.Pow: lambda: a ** b,
                ast.LShift: lambda: a << b, ast.RShift: lambda: a >> b,
                ast.BitAnd: lambda: a & b, ast.BitOr: lambda: a | b,
                ast.BitXor: lambda: a ^ b,
                ast.Div: lambda: a / b,
            }[type(op)]()
        except KeyError:
            raise AnalysisError(
                f"unhandled operator {type(op).__name__}")
        except ZeroDivisionError:
            raise AnalysisError("host division by zero")

    def arr_binop(self, a: Any, op: ast.operator, b: Any, node) -> Any:
        fn = _IV_BINOPS.get(type(op))
        if fn is None:
            if isinstance(op, ast.Pow):
                fn = lambda x, y: iv_mul(x, x) if y.exact == 2 else None
            else:
                raise AnalysisError(
                    f"unhandled array operator {type(op).__name__}")
        arr_a = a if isinstance(a, Arr) else None
        arr_b = b if isinstance(b, Arr) else None
        dtype = promote(arr_a.dtype if arr_a else None,
                        arr_b.dtype if arr_b else None)
        shape = broadcast_shapes(arr_a.shape if arr_a else (),
                                 arr_b.shape if arr_b else ())
        if shape is None:
            raise AnalysisError(
                "unbroadcastable shapes "
                f"{arr_a and arr_a.shape} vs {arr_b and arr_b.shape} "
                f"at line {getattr(node, 'lineno', '?')}")
        try:
            ia, ib = iv_of(a), iv_of(b)
        except TypeError:
            raise AnalysisError("array op with non-interval operand")
        rows = self.zip_rows(arr_a, arr_b, a, b, shape,
                             lambda x, y: fn(x, y))
        hull = fn(ia, ib)
        if hull is None or (rows is not None and any(
                r is None for r in rows)):
            return self.finish(Arr(dtype, shape, None,
                                   DT_IV(dtype)), node, wrapped=True)
        return self.finish(Arr(dtype, shape, rows, hull), node)

    def zip_rows(self, arr_a: Optional[Arr], arr_b: Optional[Arr],
                 a: Any, b: Any, shape: Tuple[Dim, ...],
                 fn: Callable[[IV, IV], Optional[IV]]) \
            -> Optional[List[Optional[IV]]]:
        """Per-leading-axis transfer when row alignment is sound: both
        operands span the result's axis 0 (equal concrete length or
        broadcast from rank-deficient / length-1)."""
        if not shape or not isinstance(shape[0], int) \
                or shape[0] > ROWS_MAX:
            return None
        n = shape[0]

        def rows_for(arr: Optional[Arr], other: Any) -> Optional[List[IV]]:
            if arr is None:
                iv = iv_of(other)
                return [iv] * n
            if arr.ndim < len(shape) or (
                    arr.shape and arr.shape[0] == 1 and n != 1):
                return [arr.iv] * n
            rl = arr.row_list()
            if rl is None or len(rl) != n:
                return None
            return rl
        ra = rows_for(arr_a, a)
        rb = rows_for(arr_b, b)
        if ra is None or rb is None:
            return None
        return [fn(x, y) for x, y in zip(ra, rb)]

    def finish(self, arr: Arr, node, wrapped: bool = False) -> Arr:
        """Dtype-lattice clamp: int32 escapes are findings; wrap
        dtypes silently reduce to their range (by-design modular
        packing); bool clamps."""
        lo, hi = DTYPE_RANGE.get(arr.dtype, (-INF, INF))
        if arr.iv.inside(lo, hi):
            return arr
        if arr.dtype == "int32":
            self.report(node, "int32-escape",
                        f"int32 value may reach {arr.iv}, escaping "
                        f"[-2**31, 2**31)")
            return Arr(arr.dtype, arr.shape, None, IV(lo, hi))
        if arr.dtype in _WRAP_DTYPES or arr.dtype == "bool":
            rows = None
            if arr.rows is not None:
                rows = [r if r.inside(lo, hi) else IV(lo, hi)
                        for r in arr.rows]
            return Arr(arr.dtype, arr.shape, rows, IV(lo, hi))
        self.report(node, "int32-escape",
                    f"{arr.dtype} value may reach {arr.iv}")
        return Arr(arr.dtype, arr.shape, None, IV(lo, hi))

    def unaryop(self, node: ast.UnaryOp, frame: Frame) -> Any:
        v = self.eval(node.operand, frame)
        if isinstance(node.op, ast.Not):
            t = self.truth(v)
            return Unknown("not") if t is None else (not t)
        if isinstance(v, Opaque):
            return v
        if isinstance(v, (int, bool)):
            return {ast.USub: lambda: -v, ast.UAdd: lambda: v,
                    ast.Invert: lambda: ~v}[type(node.op)]()
        if isinstance(v, (IV, SymDim)):
            iv = iv_of(v)
            if isinstance(node.op, ast.USub):
                return IV(-iv.hi, -iv.lo)
            if isinstance(node.op, ast.Invert):
                return IV(-iv.hi - 1, -iv.lo - 1)
            return iv
        if isinstance(v, Arr):
            iv = v.iv
            if isinstance(node.op, ast.USub):
                out, rows = IV(-iv.hi, -iv.lo), None
                if v.rows is not None:
                    rows = [IV(-r.hi, -r.lo) for r in v.rows]
            elif isinstance(node.op, ast.Invert):
                out, rows = IV(-iv.hi - 1, -iv.lo - 1), None
                if v.rows is not None:
                    rows = [IV(-r.hi - 1, -r.lo - 1) for r in v.rows]
            else:
                return v
            return self.finish(Arr(v.dtype, v.shape, rows, out), node)
        raise AnalysisError(f"unary on {type(v).__name__}")

    def boolop(self, node: ast.BoolOp, frame: Frame) -> Any:
        is_and = isinstance(node.op, ast.And)
        last: Any = None
        saw_unknown = False
        for clause in node.values:
            v = self.eval(clause, frame)
            t = self.truth(v)
            if t is None:
                saw_unknown = True
                last = v
                continue
            if is_and and t is False:
                return v
            if not is_and and t is True:
                return v
            last = v
        return Unknown("boolop") if saw_unknown else last

    def compare(self, node: ast.Compare, frame: Frame) -> Any:
        left = self.eval(node.left, frame)
        result: Any = True
        for op, rnode in zip(node.ops, node.comparators):
            right = self.eval(rnode, frame)
            r = self.compare_one(left, op, right, node)
            if r is False:
                return False
            if not isinstance(r, bool):
                result = r
            left = right
        return result

    def compare_one(self, a: Any, op: ast.cmpop, b: Any, node) -> Any:
        if isinstance(op, (ast.In, ast.NotIn)):
            if isinstance(b, (dict, list, tuple, set, str)):
                try:
                    hit = a in b
                except TypeError:
                    return Unknown("in")
                return (not hit) if isinstance(op, ast.NotIn) else hit
            return Unknown("in")
        if isinstance(op, (ast.Is, ast.IsNot)):
            if a is None or b is None:
                hit = a is b
                return (not hit) if isinstance(op, ast.IsNot) else hit
            return Unknown("is")
        if isinstance(a, Opaque) or isinstance(b, Opaque):
            return Unknown("opaque compare")
        if isinstance(a, Arr) or isinstance(b, Arr):
            return self.arr_compare(a, op, b, node)
        if isinstance(a, str) and isinstance(b, str):
            return {ast.Eq: a == b, ast.NotEq: a != b}.get(
                type(op), Unknown("str compare"))
        if isinstance(a, (tuple, list)) or isinstance(b, (tuple, list)):
            if isinstance(op, (ast.Eq, ast.NotEq)) \
                    and type(a) is type(b):
                hit = veq(a, b)
                return (not hit) if isinstance(op, ast.NotEq) else hit
            return Unknown("sequence compare")
        if isinstance(a, (int, bool)) and isinstance(b, (int, bool)):
            # concrete host ints compare EXACTLY — routing them through
            # IV would saturate crypto-sized constants at +-INF and
            # "prove" a true comparison false
            return {ast.Eq: a == b, ast.NotEq: a != b, ast.Lt: a < b,
                    ast.LtE: a <= b, ast.Gt: a > b,
                    ast.GtE: a >= b}[type(op)]
        try:
            ia, ib = iv_of(a), iv_of(b)
        except TypeError:
            return Unknown(f"compare {type(a).__name__}/"
                           f"{type(b).__name__}")
        return _decide(ia, op, ib)

    def arr_compare(self, a: Any, op: ast.cmpop, b: Any, node) -> Arr:
        arr_a = a if isinstance(a, Arr) else None
        arr_b = b if isinstance(b, Arr) else None
        shape = broadcast_shapes(arr_a.shape if arr_a else (),
                                 arr_b.shape if arr_b else ()) or ()

        def cmp_iv(x: IV, y: IV) -> IV:
            d = _decide(x, op, y)
            if d is True:
                return IV(1, 1)
            if d is False:
                return IV(0, 0)
            return IV(0, 1)
        rows = self.zip_rows(arr_a, arr_b, a, b, shape, cmp_iv)
        try:
            hull = cmp_iv(iv_of(a), iv_of(b))
        except TypeError:
            hull = IV(0, 1)
        if rows is not None and any(r is None for r in rows):
            rows = None
        return Arr("bool", shape, rows, hull)

    # -- attributes --------------------------------------------------------

    _DTYPE_ATTRS = {"int32": "int32", "uint32": "uint32",
                    "uint8": "uint8", "int8": "int8", "bool_": "bool",
                    "int16": "int16", "uint16": "uint16",
                    "int64": "int64", "uint64": "uint64",
                    "float32": "float32"}

    def attr_of(self, recv: Any, name: str, node=None) -> Any:
        if isinstance(recv, Opaque):
            return recv
        if isinstance(recv, Bound) and recv.kind in ("atview",
                                                     "refatview"):
            if name in ("set", "add", "max", "min"):
                return Bound(recv.kind + "op", recv.recv, name)
            raise AnalysisError(f"unmodeled .at[].{name}")
        if isinstance(recv, ModScope):
            return recv.get(name)
        if isinstance(recv, ModuleVal):
            return self.module_attr(recv, name, node)
        if isinstance(recv, Arr):
            if name == "shape":
                return tuple(recv.shape)
            if name == "ndim":
                return recv.ndim
            if name == "dtype":
                return DtypeVal(recv.dtype)
            if name == "at":
                return Bound("at", recv, "at")
            if name in ("astype", "reshape", "sum", "min", "max",
                        "transpose", "squeeze", "ravel", "view"):
                return Bound("arrmethod", recv, name)
            if name == "T":
                return self.intrinsic_transpose(recv, None, node)
            raise AnalysisError(f"unknown array attribute .{name}")
        if isinstance(recv, Ref):
            if name == "shape":
                return tuple(recv.shape)
            if name == "dtype":
                return DtypeVal(recv.dtype)
            if name == "at":
                return Bound("refat", recv, "at")
            raise AnalysisError(f"unknown ref attribute .{name}")
        if isinstance(recv, DtypeVal):
            return recv
        if isinstance(recv, dict) and name in ("get", "items", "keys",
                                               "values", "setdefault",
                                               "pop"):
            return Bound("dictmethod", recv, name)
        if isinstance(recv, list) and name in ("append", "extend",
                                               "insert", "pop"):
            return Bound("listmethod", recv, name)
        if isinstance(recv, str):
            return Bound("strmethod", recv, name)
        if isinstance(recv, SDS):
            if name == "shape":
                return tuple(recv.shape)
            if name == "dtype":
                return recv.dtype
        if hasattr(recv, name) and not isinstance(
                recv, (Arr, Ref, Clo, IV, SymDim)):
            # real host object (imported module, numpy array, ...)
            try:
                return self.to_abstract(getattr(recv, name))
            except Exception as e:      # noqa: BLE001
                return self.unknown(node, f"host attr .{name}: {e}")
        raise AnalysisError(
            f"attribute .{name} on {type(recv).__name__}")

    def module_attr(self, mod: ModuleVal, name: str, node) -> Any:
        if mod.name == "jax":
            if name == "jit":
                return Bound("jit", None, "jit")
            if name == "numpy":
                return ModuleVal("jax.numpy")
            if name == "lax":
                return ModuleVal("jax.lax")
            if name == "tree_util":
                return ModuleVal("jax.tree_util")
            if name == "experimental":
                return ModuleVal("jax.experimental")
            if name == "ShapeDtypeStruct":
                return Bound("intrinsic", "jax", "ShapeDtypeStruct")
            if name in ("Array", "config"):
                return Opaque(f"jax.{name}")
        if mod.name == "jax.experimental":
            if name == "pallas":
                return ModuleVal("pallas")
        if mod.name == "jax.numpy":
            if name in self._DTYPE_ATTRS:
                return DtypeVal(self._DTYPE_ATTRS[name])
            return Bound("jnp", None, name)
        if mod.name == "jax.lax":
            return Bound("lax", None, name)
        if mod.name == "jax.tree_util":
            return Bound("intrinsic", "tree", name)
        if mod.name == "pallas":
            if name == "BlockSpec":
                return Bound("intrinsic", "pl", "BlockSpec")
            if name == "pallas_call":
                return Bound("intrinsic", "pl", "pallas_call")
            if name == "program_id":
                return Bound("intrinsic", "pl", "program_id")
            if name == "tpu":
                return ModuleVal("pallas.tpu")
            if name in ("ANY", "MemorySpace"):
                return Opaque(f"pl.{name}")
        if mod.name == "pallas.tpu":
            if name == "VMEM":
                return Bound("intrinsic", "pltpu", "VMEM")
            return Opaque(f"pltpu.{name}")
        if mod.name == "functools":
            if name == "partial":
                return Bound("intrinsic", "functools", "partial")
            if name in ("lru_cache", "cache", "wraps"):
                return Bound("intrinsic", "functools", "lru_cache")
        raise AnalysisError(f"unmodeled {mod.name}.{name}")

    # -- indexing ----------------------------------------------------------

    def eval_index(self, node: ast.expr, frame: Frame) -> Any:
        if isinstance(node, ast.Slice):
            return slice(
                self.eval(node.lower, frame) if node.lower else None,
                self.eval(node.upper, frame) if node.upper else None,
                self.eval(node.step, frame) if node.step else None)
        if isinstance(node, ast.Tuple):
            return tuple(self.eval_index(e, frame) for e in node.elts)
        return self.eval(node, frame)

    def load_item(self, recv: Any, idx: Any, node) -> Any:
        if isinstance(recv, Opaque):
            return recv
        if isinstance(recv, (list, tuple)):
            if isinstance(idx, slice):
                return recv[self._host_slice(idx, len(recv))]
            if isinstance(idx, bool) or not isinstance(idx, int):
                if isinstance(idx, (IV, SymDim)):
                    iv = iv_of(idx)
                    lo = max(iv.lo, -len(recv))
                    hi = min(iv.hi, len(recv) - 1)
                    if lo > hi:
                        raise AnalysisError("index out of range")
                    out = recv[lo]
                    for i in range(lo + 1, hi + 1):
                        out = vjoin(out, recv[i])
                    return out
                raise AnalysisError(
                    f"abstract sequence index {type(idx).__name__}")
            return recv[idx]
        if isinstance(recv, dict):
            try:
                return recv[idx]
            except (KeyError, TypeError):
                raise AnalysisError(f"missing dict key {idx!r}")
        if isinstance(recv, str):
            if isinstance(idx, int):
                return recv[idx]
            if isinstance(idx, slice):
                return recv[self._host_slice(idx, len(recv))]
            raise AnalysisError("abstract string index")
        if isinstance(recv, Arr):
            return self.arr_getitem(recv, idx, node)
        if isinstance(recv, Ref):
            val = recv.value()
            if val is None:
                return self.unknown(node, "read of unwritten ref")
            return self.arr_getitem(val, idx, node)
        if isinstance(recv, range):
            if isinstance(idx, int):
                return recv[idx]
            raise AnalysisError("abstract range index")
        if isinstance(recv, Bound) and recv.name == "at":
            # x.at[idx] / ref.at[idx] -> view awaiting .set/.add
            kind = "atview" if recv.kind == "at" else "refatview"
            return Bound(kind, (recv.recv, idx), "view")
        raise AnalysisError(
            f"cannot index {type(recv).__name__}")

    @staticmethod
    def _host_slice(s: slice, n: int) -> slice:
        def ok(v):
            return v is None or isinstance(v, int)
        if not (ok(s.start) and ok(s.stop) and ok(s.step)):
            raise AnalysisError("abstract host slice")
        return s

    def index_axis0(self, arr: Arr, i: Optional[Any], node) -> Any:
        """arr[i] on the leading axis; i=None or abstract -> row hull."""
        if not arr.shape:
            raise AnalysisError("indexing a rank-0 array")
        rows = arr.row_list()
        shape = arr.shape[1:]
        if isinstance(i, bool):
            i = int(i)
        if isinstance(i, int) and rows is not None:
            if not -len(rows) <= i < len(rows):
                raise AnalysisError(f"row index {i} out of range")
            return Arr(arr.dtype, shape, None, rows[i])
        if i is None or isinstance(i, (IV, SymDim, Arr)):
            if rows is not None and i is not None \
                    and isinstance(i, (IV, SymDim)):
                iv = iv_of(i)
                lo = max(iv.lo, 0)
                hi = min(iv.hi, len(rows) - 1)
                if lo <= hi:
                    hull = rows[lo]
                    for r in rows[lo + 1:hi + 1]:
                        hull = hull.join(r)
                    return Arr(arr.dtype, shape, None, hull)
            return Arr(arr.dtype, shape, None, arr.iv)
        if isinstance(i, int):
            return Arr(arr.dtype, shape, None, arr.iv)
        raise AnalysisError(
            f"unhandled axis-0 index {type(i).__name__}")

    def arr_getitem(self, arr: Arr, idx: Any, node) -> Arr:
        if not isinstance(idx, tuple):
            idx = (idx,)
        # expand Ellipsis to full slices
        n_spec = sum(1 for i in idx if i is not None
                     and not isinstance(i, type(Ellipsis)))
        n_real = sum(1 for i in idx
                     if i is not None and i is not Ellipsis)
        if any(i is Ellipsis for i in idx):
            fill = arr.ndim - n_real
            out: List[Any] = []
            for i in idx:
                if i is Ellipsis:
                    out.extend([slice(None)] * fill)
                else:
                    out.append(i)
            idx = tuple(out)
        _ = n_spec
        # leading-axis handling drives row precision; everything past
        # axis 0 only reshapes within rows (row hulls stay sound)
        shape: List[Dim] = []
        rows = arr.row_list()
        axis = 0
        first_real = True
        out_rows: Optional[List[IV]] = rows
        leading_new_axes = 0
        iv = arr.iv
        for item in idx:
            if item is None:
                shape.append(1)
                if first_real:
                    leading_new_axes += 1
                continue
            if axis >= arr.ndim:
                raise AnalysisError("too many indices")
            dim = arr.shape[axis]
            if isinstance(item, slice):
                start, stop, step = item.start, item.stop, item.step
                if axis == 0 and first_real and rows is not None \
                        and all(x is None or isinstance(x, int)
                                for x in (start, stop, step)):
                    sel = rows[slice(start, stop, step)]
                    out_rows = sel
                    shape.append(len(sel))
                else:
                    shape.append(self._slice_dim(dim, item))
                    if axis == 0:
                        out_rows = None
                first_real = False
            elif isinstance(item, (int, bool)):
                if axis == 0 and first_real:
                    sub = self.index_axis0(arr, int(item), node)
                    rest = idx[idx.index(item) + 1:]
                    if rest:
                        return self.arr_getitem(sub, tuple(rest), node)
                    return sub
                # dropping a non-leading axis keeps rows sound
                first_real = False
            elif isinstance(item, (IV, SymDim, Arr, Opaque)):
                if axis == 0 and first_real:
                    sub = self.index_axis0(
                        arr, item if not isinstance(item, Opaque)
                        else None, node)
                    if isinstance(item, Arr):
                        # gather: indexed result keeps the index shape
                        sub = Arr(arr.dtype,
                                  tuple(item.shape) + tuple(sub.shape),
                                  None, sub.iv)
                    rest = idx[idx.index(item) + 1:]
                    if rest:
                        return self.arr_getitem(sub, tuple(rest), node)
                    return sub
                if isinstance(item, Arr):
                    shape.extend(item.shape)
                first_real = False
            else:
                raise AnalysisError(
                    f"unhandled index {type(item).__name__}")
            axis += 1
        shape.extend(arr.shape[axis:])
        if leading_new_axes:
            # x[None] / x[None, :]: old hull becomes the single row
            out_rows = [arr.iv] if shape and shape[0] == 1 else None
        if out_rows is not None and (not shape
                                     or not isinstance(shape[0], int)
                                     or len(out_rows) != shape[0]):
            out_rows = None
        return Arr(arr.dtype, tuple(shape), out_rows, iv)

    @staticmethod
    def _slice_dim(dim: Dim, s: slice) -> Dim:
        if isinstance(dim, int) and all(
                x is None or isinstance(x, int)
                for x in (s.start, s.stop, s.step)):
            return len(range(dim)[s])
        if s.start is None and s.stop is None and s.step is None:
            return dim
        # symbolic dim sliced with concrete bounds: length unknown
        if isinstance(s.stop, int) and (s.start is None
                                        or isinstance(s.start, int)) \
                and s.stop >= 0 and s.step is None:
            return s.stop - (s.start or 0)
        return IV(0, dim_iv(dim).hi)

    # -- ref updates -------------------------------------------------------

    def ref_store(self, ref: Ref, idx: Any, val: Any, node) -> None:
        try:
            viv = iv_of(val)
        except TypeError:
            if isinstance(val, Opaque):
                viv = DT_IV(ref.dtype)
            else:
                raise AnalysisError(
                    f"storing {type(val).__name__} into ref")
        if isinstance(val, Arr):
            self.finish(Arr(ref.dtype, val.shape, val.rows, val.iv),
                        node)
        ref.written = True
        idx_t = idx if isinstance(idx, tuple) else (idx,)
        first = idx_t[0] if idx_t else slice(None)
        full0 = isinstance(first, slice) and first.start is None \
            and first.stop is None and first.step is None
        rest_full = all(isinstance(i, slice) and i.start is None
                        and i.stop is None and i.step is None
                        or i is Ellipsis
                        for i in idx_t[1:])
        if ref.rows is None:
            ref.hull = viv if ref.hull is None else ref.hull.join(viv)
            return
        if full0 and rest_full:
            # o_ref[:] = v — strong whole-block update
            if isinstance(val, Arr) and val.rows is not None \
                    and len(val.rows) == len(ref.rows):
                ref.rows = list(val.rows)
            else:
                ref.rows = [viv] * len(ref.rows)
            return
        if isinstance(first, bool):
            first = int(first)
        if isinstance(first, int) and -len(ref.rows) <= first \
                < len(ref.rows):
            if rest_full:
                # strong single-row update (tab_ref[j] = acc, j concrete)
                row = viv
                if isinstance(val, Arr) and val.rows is not None \
                        and len(idx_t) == 1 and False:
                    pass
                ref.rows[first] = row
            else:
                old = ref.rows[first]
                ref.rows[first] = viv if old is _BOTTOM \
                    else old.join(viv)
            return
        if isinstance(first, slice):
            try:
                sel = range(len(ref.rows))[self._host_slice(
                    first, len(ref.rows))]
            except AnalysisError:
                sel = range(len(ref.rows))
            for i in sel:
                if rest_full:
                    ref.rows[i] = viv
                else:
                    old = ref.rows[i]
                    ref.rows[i] = viv if old is _BOTTOM \
                        else old.join(viv)
            return
        # abstract leading index: weak update on every row
        ref.rows = [viv if r is _BOTTOM else r.join(viv)
                    for r in ref.rows]

    # -- calls -------------------------------------------------------------

    def call(self, node: ast.Call, frame: Frame) -> Any:
        fn = self.eval(node.func, frame)
        args = []
        for a in node.args:
            if isinstance(a, ast.Starred):
                star = self.eval(a.value, frame)
                if not isinstance(star, (list, tuple)):
                    raise AnalysisError("abstract *args splat")
                args.extend(star)
            else:
                args.append(self.eval(a, frame))
        kwargs: Dict[str, Any] = {}
        for kw in node.keywords:
            if kw.arg is None:
                d = self.eval(kw.value, frame)
                if not isinstance(d, dict):
                    raise AnalysisError("abstract **kwargs splat")
                kwargs.update(d)
            else:
                kwargs[kw.arg] = self.eval(kw.value, frame)
        try:
            return self.apply(fn, args, kwargs, node, frame)
        except (TypeError, ValueError, AttributeError, IndexError,
                KeyError, ZeroDivisionError, OverflowError) as e:
            # abstract value reached a construct the model can't take
            # it through — surface as an analysis hole, not a crash
            raise AnalysisError(
                f"{type(e).__name__} at line {node.lineno}: {e}")

    def apply(self, fn: Any, args: list, kwargs: dict,
              node, frame: Frame) -> Any:
        if isinstance(fn, Opaque):
            return self.unknown(node, f"call of opaque {fn.reason}")
        if isinstance(fn, Clo):
            return self.call_clo(fn, args, kwargs, node)
        if isinstance(fn, Jitted):
            return self.call_clo(fn.clo, args, kwargs, node)
        if isinstance(fn, Partial):
            return self.apply(fn.fn, list(fn.args) + args,
                              {**fn.kwargs, **kwargs}, node, frame)
        if isinstance(fn, RealFn):
            return self.call_real(fn, args, kwargs, node, frame)
        if isinstance(fn, Bound):
            return self.call_bound(fn, args, kwargs, node, frame)
        if isinstance(fn, str) and fn in _PY_BUILTINS:
            return self.call_builtin(fn, args, kwargs, node, frame)
        if isinstance(fn, DtypeVal):
            # jnp.uint32(x) style cast
            return self.cast(args[0], fn.name, node)
        raise AnalysisError(f"call of {type(fn).__name__}")

    def call_real(self, fn: RealFn, args: list, kwargs: dict,
                  node, frame: Optional[Frame] = None) -> Any:
        try:
            cargs = [self.to_concrete(a) for a in args]
            ckw = {k: self.to_concrete(v) for k, v in kwargs.items()}
        except TypeError:
            # numpy structural fns with abstract (Arr) operands fall
            # back to the jnp transfer functions — np.stack over limb
            # constants mixed with traced rows is idiomatic host code
            if fn.name in ("stack", "concatenate", "asarray", "array",
                           "broadcast_to", "where", "minimum",
                           "maximum") and frame is not None:
                return self.jnp_call(fn.name, args, kwargs, node,
                                     frame)
            return self.unknown(
                node, f"abstract arg to host fn {fn.name}")
        try:
            out = fn.fn(*cargs, **ckw)
        except Exception as e:          # noqa: BLE001
            raise AnalysisError(f"host fn {fn.name} raised: {e}")
        return self.to_abstract(out)

    def to_concrete(self, v: Any) -> Any:
        if isinstance(v, (bool, int, str, bytes, float)) or v is None:
            return v
        if isinstance(v, tuple):
            return tuple(self.to_concrete(x) for x in v)
        if isinstance(v, list):
            return [self.to_concrete(x) for x in v]
        if isinstance(v, dict):
            return {k: self.to_concrete(x) for k, x in v.items()}
        if isinstance(v, IV) and v.exact:
            return v.lo
        if isinstance(v, SymDim) and v.bound is not None \
                and v.bound.exact:
            return v.bound.lo
        if isinstance(v, RealFn):
            return v.fn
        if isinstance(v, (Arr, IV, SymDim, Opaque, Unknown, Clo,
                          Bound, Partial, Jitted, ModuleVal, DtypeVal,
                          SDS, BlockSpec, VMEM, Ref, ModScope)):
            raise TypeError("abstract")
        # anything else is already a real host object (numpy dtype,
        # ndarray, imported module) — hand it through untouched
        return v

    def to_abstract(self, v: Any) -> Any:
        if isinstance(v, bool) or v is None:
            return v
        if isinstance(v, int):
            return v
        if isinstance(v, (str, bytes, float)):
            return v
        if isinstance(v, tuple):
            return tuple(self.to_abstract(x) for x in v)
        if isinstance(v, list):
            return [self.to_abstract(x) for x in v]
        if isinstance(v, dict):
            return {k: self.to_abstract(x) for k, x in v.items()}
        try:
            import numpy as _np
            if isinstance(v, _np.ndarray):
                if v.dtype.kind in "iub":
                    dt = str(v.dtype) if str(v.dtype) in DTYPE_RANGE \
                        else "int64"
                    flat = v.reshape(v.shape[0], -1) if v.ndim > 1 \
                        else v.reshape(-1, 1)
                    rows = None
                    if v.ndim >= 1 and v.shape[0] <= ROWS_MAX:
                        rows = [IV(int(r.min()), int(r.max()))
                                for r in flat]
                    iv = IV(int(v.min()), int(v.max())) if v.size \
                        else IV(0, 0)
                    return Arr(dt, tuple(int(d) for d in v.shape),
                               rows, iv)
                raise TypeError("non-integer ndarray")
            if isinstance(v, _np.integer):
                return int(v)
        except ImportError:
            pass
        if callable(v):
            return RealFn(v, getattr(v, "__name__", "<fn>"))
        raise TypeError(f"unconvertible host value {type(v).__name__}")

    def cast(self, v: Any, dtype: str, node) -> Any:
        if isinstance(v, Opaque):
            return Arr(dtype, (), None, DT_IV(dtype))
        if isinstance(v, bool):
            v = int(v)
        if isinstance(v, int):
            return self.finish(Arr(dtype, (), None, IV(v, v)), node)
        if isinstance(v, (IV, SymDim)):
            return self.finish(Arr(dtype, (), None, iv_of(v)), node)
        if isinstance(v, Arr):
            return self.finish(
                Arr(dtype, v.shape, v.rows, v.iv), node)
        if isinstance(v, (list, tuple)):
            arr = self.from_nested(v, dtype, node)
            return self.finish(arr, node)
        raise AnalysisError(f"cast of {type(v).__name__}")

    def from_nested(self, v: Any, dtype: str, node) -> Arr:
        """Build an exact Arr from a (nested) python list/tuple."""
        def scan(x, depth):
            if isinstance(x, (list, tuple)):
                if not x:
                    raise AnalysisError("empty array literal")
                subs = [scan(e, depth + 1) for e in x]
                sh = subs[0][0]
                for s, _ in subs[1:]:
                    if s != sh:
                        raise AnalysisError("ragged array literal")
                iv = subs[0][1]
                for _, i2 in subs[1:]:
                    iv = iv.join(i2)
                return (len(x),) + sh, iv
            return (), iv_of(x)
        shape, iv = scan(v, 0)
        rows = None
        if shape and isinstance(v, (list, tuple)) \
                and len(v) <= ROWS_MAX:
            rows = [scan(e, 1)[1] for e in v]
        return Arr(dtype, shape, rows, iv)

    # -- python builtins ---------------------------------------------------

    def call_builtin(self, name: str, args: list, kwargs: dict,
                     node, frame: Frame) -> Any:
        a = args
        if name == "round":
            if all(isinstance(v, (int, bool, float)) for v in a):
                return round(*a)
            raise AnalysisError("round of abstract value")
        if name == "len":
            v = a[0]
            if isinstance(v, (list, tuple, str, dict, range, set)):
                return len(v)
            if isinstance(v, Arr):
                return v.shape[0] if v.shape else \
                    self._die("len of rank-0")
            if isinstance(v, Ref):
                return v.shape[0]
            raise AnalysisError(f"len of {type(v).__name__}")
        if name == "range":
            ints = []
            for v in a:
                if isinstance(v, bool):
                    v = int(v)
                if not isinstance(v, int):
                    raise AnalysisError("abstract range bound")
                ints.append(v)
            return range(*ints)
        if name in ("min", "max"):
            pick = min if name == "min" else max
            vals = list(a[0]) if len(a) == 1 \
                and isinstance(a[0], (list, tuple, range)) else a
            if all(isinstance(v, (int, bool)) for v in vals):
                return pick(vals)
            ivs = [iv_of(v) for v in vals]
            if name == "min":
                return IV(pick(i.lo for i in ivs),
                          pick(i.hi for i in ivs))
            return IV(pick(i.lo for i in ivs),
                      pick(i.hi for i in ivs))
        if name == "abs":
            v = a[0]
            if isinstance(v, (int, bool)):
                return abs(int(v))
            iv = iv_of(v)
            lo = 0 if iv.lo <= 0 <= iv.hi else min(abs(iv.lo),
                                                   abs(iv.hi))
            return IV(lo, max(abs(iv.lo), abs(iv.hi)))
        if name == "int":
            v = a[0]
            if isinstance(v, (int, bool)):
                return int(v)
            if isinstance(v, str):
                return int(v, *a[1:])
            if isinstance(v, IV):
                return v
            if isinstance(v, Arr) and not v.shape:
                return v.iv
            raise AnalysisError("abstract int()")
        if name == "bool":
            t = self.truth(a[0])
            return t if t is not None else Unknown("bool()")
        if name == "float":
            raise AnalysisError("float() in kernel path")
        if name == "sum":
            v = a[0]
            start = a[1] if len(a) > 1 else kwargs.get("start", 0)
            if isinstance(v, (list, tuple)):
                out = start
                for x in v:
                    out = self.binop_vals(out, ast.Add(), x, node)
                return out
            if isinstance(v, range):
                return sum(v) + (start if isinstance(start, int)
                                 else 0)
            raise AnalysisError("sum of abstract iterable")
        if name == "tuple":
            if not a:
                return ()
            v = a[0]
            if isinstance(v, (list, tuple, range, str)):
                return tuple(v)
            raise AnalysisError("tuple() of abstract value")
        if name == "list":
            if not a:
                return []
            v = a[0]
            if isinstance(v, (list, tuple, range, str, set)):
                return list(v)
            raise AnalysisError("list() of abstract value")
        if name == "dict":
            d = dict(kwargs)
            if a and isinstance(a[0], dict):
                d = {**a[0], **d}
            return d
        if name == "set":
            if not a:
                return set()
            if isinstance(a[0], (list, tuple, range, str)):
                return set(a[0])
            raise AnalysisError("set() of abstract value")
        if name == "zip":
            seqs = []
            for v in a:
                if not isinstance(v, (list, tuple, range, str)):
                    raise AnalysisError("zip of abstract iterable")
                seqs.append(list(v))
            return [tuple(t) for t in zip(*seqs)]
        if name == "enumerate":
            v = a[0]
            start = a[1] if len(a) > 1 else kwargs.get("start", 0)
            if not isinstance(v, (list, tuple, range, str)):
                raise AnalysisError("enumerate of abstract iterable")
            if not isinstance(start, int):
                raise AnalysisError("abstract enumerate start")
            return [(start + i, x) for i, x in enumerate(v)]
        if name == "reversed":
            v = a[0]
            if isinstance(v, (list, tuple, range, str)):
                return list(reversed(v))
            raise AnalysisError("reversed of abstract iterable")
        if name == "sorted":
            v = a[0]
            if isinstance(v, (list, tuple, range)) and all(
                    isinstance(x, (int, bool, str)) for x in v):
                return sorted(v, **{k: self.to_concrete(x)
                                    for k, x in kwargs.items()})
            raise AnalysisError("sorted of abstract iterable")
        if name == "bin":
            v = a[0]
            if isinstance(v, (int, bool)):
                return bin(v)
            raise AnalysisError("bin of abstract value")
        if name == "pow":
            if all(isinstance(v, (int, bool)) for v in a):
                return pow(*[int(v) for v in a])
            raise AnalysisError("abstract pow()")
        if name == "divmod":
            x, y = a
            q = self.binop_vals(x, ast.FloorDiv(), y, node)
            r = self.binop_vals(x, ast.Mod(), y, node)
            return (q, r)
        if name in ("all", "any"):
            v = a[0]
            if isinstance(v, (list, tuple)):
                acc: Any = (name == "all")
                for x in v:
                    t = self.truth(x)
                    if t is None:
                        acc = Unknown(name)
                    elif name == "all" and not t:
                        return False
                    elif name == "any" and t:
                        return True
                return acc
            raise AnalysisError(f"{name} of abstract iterable")
        if name == "isinstance":
            return Unknown("isinstance")
        if name == "str":
            v = a[0]
            if isinstance(v, (int, bool, str)):
                return str(v)
            return "<abstract>"
        raise AnalysisError(f"unmodeled builtin {name}")

    @staticmethod
    def _die(msg: str):
        raise AnalysisError(msg)

    def binop_vals(self, a: Any, op: ast.operator, b: Any,
                   node) -> Any:
        """binop on already-evaluated values (helper for builtins)."""
        return self.binop(a, op, b, node)

    # -- bound methods -----------------------------------------------------

    def call_bound(self, b: Bound, args: list, kwargs: dict,
                   node, frame: Frame) -> Any:
        k = b.kind
        if k == "builtin":
            return self.call_builtin(b.name, args, kwargs, node, frame)
        if k == "jit":
            return self.make_jit(args, kwargs, node)
        if k == "jnp":
            return self.jnp_call(b.name, args, kwargs, node, frame)
        if k == "lax":
            return self.lax_call(b.name, args, kwargs, node, frame)
        if k == "intrinsic":
            return self.intrinsic_call(b, args, kwargs, node, frame)
        if k == "pallascall":
            return self.call_pallas(b.recv, args, node, frame)
        if k == "arrmethod":
            return self.arr_method(b.recv, b.name, args, kwargs, node)
        if k in ("atviewop", "refatviewop"):
            recv, idx = b.recv
            if k == "refatviewop":
                if b.name == "set":
                    self.ref_store(recv, idx, args[0], node)
                    return None
                cur = self.load_item(recv, idx, node)
                if b.name == "add":
                    upd = self.binop(cur, ast.Add(), args[0], node)
                else:
                    upd = vjoin(cur, args[0])
                self.ref_store(recv, idx, upd, node)
                return None
            return self.at_set(recv, idx, args[0], b.name, node)
        if k == "dictmethod":
            return self.dict_method(b.recv, b.name, args, kwargs, node)
        if k == "listmethod":
            m = b.name
            if m == "append":
                b.recv.append(args[0])
                return None
            if m == "extend":
                v = args[0]
                if not isinstance(v, (list, tuple, range)):
                    raise AnalysisError("extend with abstract iterable")
                b.recv.extend(v)
                return None
            if m == "insert":
                if not isinstance(args[0], int):
                    raise AnalysisError("abstract insert position")
                b.recv.insert(args[0], args[1])
                return None
            if m == "pop":
                i = args[0] if args else -1
                if not isinstance(i, int):
                    raise AnalysisError("abstract pop position")
                return b.recv.pop(i)
        if k == "strmethod":
            try:
                cargs = [self.to_concrete(x) for x in args]
                return self.to_abstract(
                    getattr(b.recv, b.name)(*cargs))
            except (TypeError, AttributeError) as e:
                raise AnalysisError(f"str.{b.name}: {e}")
        raise AnalysisError(f"unmodeled bound {k}.{b.name}")

    def dict_method(self, d: dict, m: str, args: list, kwargs: dict,
                    node) -> Any:
        if m == "get":
            try:
                return d.get(args[0],
                             args[1] if len(args) > 1 else None)
            except TypeError:
                raise AnalysisError("abstract dict key")
        if m == "items":
            return [(k, v) for k, v in d.items()]
        if m == "keys":
            return list(d.keys())
        if m == "values":
            return list(d.values())
        if m == "setdefault":
            try:
                return d.setdefault(args[0],
                                    args[1] if len(args) > 1 else None)
            except TypeError:
                raise AnalysisError("abstract dict key")
        if m == "pop":
            try:
                return d.pop(*args)
            except (TypeError, KeyError) as e:
                raise AnalysisError(f"dict.pop: {e}")
        raise AnalysisError(f"unmodeled dict.{m}")

    def make_jit(self, args: list, kwargs: dict, node) -> Any:
        fn = args[0]
        static = kwargs.get("static_argnames", ())
        if isinstance(static, str):
            static = (static,)
        elif isinstance(static, (list, tuple)):
            static = tuple(str(s) for s in static)
        else:
            static = ()
        if isinstance(fn, Jitted):
            fn = fn.clo
        if isinstance(fn, Clo):
            j = Jitted(fn, static)
            self.a.register_entry(j, node)
            return j
        if isinstance(fn, Partial) and isinstance(fn.fn, Clo):
            # jit(partial(f, const)): entry sees the bound prefix
            j = Jitted(fn.fn, static)
            self.a.register_entry(j, node, prefix=tuple(fn.args),
                                  prekw=dict(fn.kwargs))
            return Partial(j, fn.args, fn.kwargs)
        raise AnalysisError("jit of non-closure")

    def arr_method(self, arr: Any, m: str, args: list, kwargs: dict,
                   node) -> Any:
        if isinstance(arr, Ref):
            v = arr.value()
            if v is None:
                raise AnalysisError(f".{m} on unwritten ref")
            arr = v
        if m == "astype":
            dt = args[0]
            if isinstance(dt, DtypeVal):
                dt = dt.name
            elif isinstance(dt, Bound) and dt.kind == "builtin" \
                    and dt.name == "bool":
                dt = "bool"                    # .astype(bool)
            elif isinstance(dt, RealFn):
                try:
                    import numpy as _np
                    dt = str(_np.dtype(dt.fn))
                except Exception:              # noqa: BLE001
                    pass
            if not isinstance(dt, str):
                raise AnalysisError("abstract astype dtype")
            return self.cast(arr, dt, node)
        if m == "reshape":
            shape = args[0] if len(args) == 1 and isinstance(
                args[0], (tuple, list)) else tuple(args)
            return self.intrinsic_reshape(arr, tuple(shape), node)
        if m == "sum":
            return self.intrinsic_sum(
                arr, args[0] if args else kwargs.get("axis"), node)
        if m in ("min", "max"):
            return Arr(arr.dtype, (), None, arr.iv)
        if m == "transpose":
            return self.intrinsic_transpose(
                arr, tuple(args) if args else None, node)
        if m == "squeeze":
            shape = tuple(d for d in arr.shape
                          if not (isinstance(d, int) and d == 1))
            rows = arr.rows if arr.shape and dim_eq(
                arr.shape[0], (shape[0] if shape else 1)) else None
            return Arr(arr.dtype, shape, rows, arr.iv)
        if m == "ravel":
            n = shape_numel(arr.shape)
            return Arr(arr.dtype,
                       (n if n is not None else IV(0, INF),),
                       None, arr.iv)
        if m == "view":
            raise AnalysisError(".view() reinterprets bits")
        raise AnalysisError(f"unmodeled array method .{m}")

    def at_set(self, arr: Arr, idx: Any, val: Any, opname: str,
               node) -> Arr:
        idx_t = idx if isinstance(idx, tuple) else (idx,)
        if opname in ("add", "max", "min"):
            cur = self.arr_getitem(arr, idx, node)
            if opname == "add":
                val = self.binop(cur, ast.Add(), val, node)
            else:
                val = vjoin(cur, val)
        try:
            viv = iv_of(val)
        except TypeError:
            viv = DT_IV(arr.dtype)
        rows = arr.row_list()
        first = idx_t[0] if idx_t else slice(None)
        rest_full = all(
            (isinstance(i, slice) and i.start is None
             and i.stop is None and i.step is None) or i is Ellipsis
            for i in idx_t[1:])
        if isinstance(first, bool):
            first = int(first)
        if rows is not None and isinstance(first, int) \
                and rest_full and -len(rows) <= first < len(rows):
            rows = list(rows)
            rows[first] = viv
            out = Arr(arr.dtype, arr.shape, rows, viv)
        elif rows is not None and isinstance(first, slice) \
                and rest_full:
            try:
                sel = range(len(rows))[self._host_slice(
                    first, len(rows))]
                rows = list(rows)
                for i in sel:
                    rows[i] = viv
                out = Arr(arr.dtype, arr.shape, rows, viv)
            except AnalysisError:
                out = Arr(arr.dtype, arr.shape, None,
                          arr.iv.join(viv))
        else:
            out = Arr(arr.dtype, arr.shape, None, arr.iv.join(viv))
        return self.finish(out, node)

    # -- jnp intrinsics ----------------------------------------------------

    _JNP_BINOP = {"add": ast.Add, "subtract": ast.Sub,
                  "multiply": ast.Mult, "floor_divide": ast.FloorDiv,
                  "mod": ast.Mod, "remainder": ast.Mod,
                  "left_shift": ast.LShift, "right_shift": ast.RShift,
                  "bitwise_and": ast.BitAnd, "bitwise_or": ast.BitOr,
                  "bitwise_xor": ast.BitXor, "power": ast.Pow}
    _JNP_CMP = {"equal": ast.Eq, "not_equal": ast.NotEq,
                "less": ast.Lt, "less_equal": ast.LtE,
                "greater": ast.Gt, "greater_equal": ast.GtE}

    def jnp_call(self, name: str, args: list, kwargs: dict,
                 node, frame: Frame) -> Any:
        if name in self._JNP_BINOP:
            return self.binop(args[0], self._JNP_BINOP[name](),
                              args[1], node)
        if name in self._JNP_CMP:
            return self.compare_one(args[0], self._JNP_CMP[name](),
                                    args[1], node)
        if name == "broadcast_shapes":
            out: Tuple[Any, ...] = ()
            for s in args:
                if not isinstance(s, tuple):
                    raise AnalysisError("abstract broadcast_shapes arg")
                b = broadcast_shapes(out, s)
                if b is None:
                    raise AnalysisError("incompatible broadcast_shapes")
                out = b
            return out
        if name in ("asarray", "array"):
            v = args[0]
            dt = kwargs.get("dtype",
                            args[1] if len(args) > 1 else None)
            dt = dt.name if isinstance(dt, DtypeVal) else dt
            if isinstance(v, Arr):
                return self.cast(v, dt or v.dtype, node)
            if isinstance(v, (int, bool, IV, SymDim)):
                return self.cast(v, dt or "int32", node)
            if isinstance(v, (list, tuple)):
                return self.finish(
                    self.from_nested(v, dt or "int32", node), node)
            if isinstance(v, Opaque):
                d = dt or "int32"
                return Arr(d, (), None, DT_IV(d))
            raise AnalysisError(f"asarray of {type(v).__name__}")
        if name == "stack":
            return self.intrinsic_stack(
                args[0], kwargs.get("axis",
                                    args[1] if len(args) > 1 else 0),
                node)
        if name == "concatenate":
            return self.intrinsic_concat(
                args[0], kwargs.get("axis",
                                    args[1] if len(args) > 1 else 0),
                node)
        if name in ("zeros", "ones", "full"):
            shape = args[0]
            if isinstance(shape, (int, IV, SymDim)):
                shape = (shape,)
            fill: Any = 0 if name == "zeros" else 1
            if name == "full":
                fill = args[1]
            dt = kwargs.get("dtype",
                            args[2] if len(args) > 2 else None)
            dt = dt.name if isinstance(dt, DtypeVal) else (dt
                                                           or "int32")
            iv = iv_of(fill)
            rows = None
            if shape and isinstance(shape[0], int) \
                    and shape[0] <= ROWS_MAX:
                rows = [iv] * shape[0]
            return self.finish(Arr(dt, tuple(shape), rows, iv), node)
        if name in ("zeros_like", "ones_like", "full_like"):
            a = args[0]
            if isinstance(a, Ref):
                a = Arr(a.dtype, a.shape, None, IV(0, 0))
            if not isinstance(a, Arr):
                a = Arr("int32", (), None, IV(0, 0))
            fill = 0 if name == "zeros_like" else 1
            if name == "full_like":
                fill = args[1]
            dt = kwargs.get("dtype")
            dt = dt.name if isinstance(dt, DtypeVal) else (dt
                                                           or a.dtype)
            iv = iv_of(fill)
            rows = None
            if a.shape and isinstance(a.shape[0], int) \
                    and a.shape[0] <= ROWS_MAX:
                rows = [iv] * a.shape[0]
            return Arr(dt, a.shape, rows, iv)
        if name in ("where", "select"):
            cond, x, y = args[0], args[1], args[2]
            return self.intrinsic_where(cond, x, y, node)
        if name == "sum":
            return self.intrinsic_sum(
                args[0],
                kwargs.get("axis", args[1] if len(args) > 1 else None),
                node)
        if name in ("all", "any"):
            a = args[0]
            sh = ()
            ax = kwargs.get("axis", args[1] if len(args) > 1 else None)
            if isinstance(a, Arr) and ax is not None:
                sh = tuple(d for i, d in enumerate(a.shape)
                           if i != (ax if ax >= 0 else len(a.shape)
                                    + ax))
            return Arr("bool", sh, None, IV(0, 1))
        if name in ("minimum", "maximum"):
            return self.intrinsic_minmax(args[0], args[1],
                                         name == "minimum", node)
        if name == "abs":
            a = args[0]
            if isinstance(a, (int, bool)):
                return abs(int(a))
            iv = iv_of(a)
            lo = 0 if iv.lo <= 0 <= iv.hi else min(abs(iv.lo),
                                                   abs(iv.hi))
            out = IV(lo, max(abs(iv.lo), abs(iv.hi)))
            if isinstance(a, Arr):
                rows = a.row_list()
                if rows is not None:
                    rows = [IV(0 if r.lo <= 0 <= r.hi
                               else min(abs(r.lo), abs(r.hi)),
                               max(abs(r.lo), abs(r.hi)))
                            for r in rows]
                return self.finish(Arr(a.dtype, a.shape, rows, out),
                                   node)
            return out
        if name == "clip":
            a = args[0]
            lo = iv_of(args[1]) if len(args) > 1 and args[1] is not None \
                else None
            hi = iv_of(args[2]) if len(args) > 2 and args[2] is not None \
                else None
            iv = iv_of(a)
            clo = max(iv.lo, lo.lo) if lo else iv.lo
            chi = min(iv.hi, hi.hi) if hi else iv.hi
            if clo > chi:
                clo, chi = chi, clo
            if isinstance(a, Arr):
                return Arr(a.dtype, a.shape, None, IV(clo, chi))
            return IV(clo, chi)
        if name == "take":
            a, i = args[0], args[1]
            ax = kwargs.get("axis", args[2] if len(args) > 2 else None)
            if not isinstance(a, Arr):
                raise AnalysisError("take of non-array")
            if ax in (0, None) and not isinstance(i, Arr):
                return self.index_axis0(
                    a, i if isinstance(i, (int, IV, SymDim)) else None,
                    node)
            ish = i.shape if isinstance(i, Arr) else ()
            if ax is None:
                return Arr(a.dtype, tuple(ish), None, a.iv)
            if not isinstance(ax, int):
                raise AnalysisError("abstract take axis")
            ax %= a.ndim
            sh = a.shape[:ax] + tuple(ish) + a.shape[ax + 1:]
            return Arr(a.dtype, sh, None, a.iv)
        if name == "broadcast_arrays":
            bsh: Tuple[Any, ...] = ()
            for a in args:
                s = a.shape if isinstance(a, Arr) else ()
                b = broadcast_shapes(bsh, s)
                if b is None:
                    raise AnalysisError("incompatible broadcast_arrays")
                bsh = b
            out = []
            for a in args:
                if isinstance(a, Arr):
                    keep = shape_sig(a.shape) == shape_sig(bsh)
                    out.append(Arr(a.dtype, bsh,
                                   a.rows if keep else None, a.iv))
                else:
                    out.append(Arr("int32", bsh, None, iv_of(a)))
            return out
        if name == "arange":
            if args and isinstance(args[0], (SymDim, IV)) \
                    and all(isinstance(x, DtypeVal) for x in args[1:]):
                # arange over a symbolic length: shape keeps the
                # symbol, values span [0, n-1]
                d = args[0]
                dtv = kwargs.get("dtype")
                for x in args[1:]:
                    dtv = x
                dtn = dtv.name if isinstance(dtv, DtypeVal) \
                    else "int32"
                hi = dim_iv(d).hi
                dim = d if isinstance(d, SymDim) else SymDim("_n", d)
                return self.finish(
                    Arr(dtn, (dim,), None, IV(0, max(0, hi - 1))),
                    node)
            ints = []
            for v in args:
                if isinstance(v, DtypeVal):
                    break
                if isinstance(v, bool):
                    v = int(v)
                if isinstance(v, IV) and v.exact is not None:
                    v = v.exact
                if not isinstance(v, int):
                    raise AnalysisError("abstract arange bound")
                ints.append(v)
            dt = kwargs.get("dtype")
            for v in args:
                if isinstance(v, DtypeVal):
                    dt = v
            dt = dt.name if isinstance(dt, DtypeVal) else (dt
                                                           or "int32")
            r = list(range(*ints))
            rows = [IV(v, v) for v in r] if len(r) <= ROWS_MAX else None
            iv = IV(min(r), max(r)) if r else IV(0, 0)
            return self.finish(Arr(dt, (len(r),), rows, iv), node)
        if name == "reshape":
            shape = args[1]
            if isinstance(shape, (int, IV, SymDim)):
                shape = (shape,)
            return self.intrinsic_reshape(args[0], tuple(shape), node)
        if name == "broadcast_to":
            a, shape = args[0], tuple(args[1])
            iv = iv_of(a)
            dt = a.dtype if isinstance(a, Arr) else "int32"
            rows = None
            if isinstance(a, Arr):
                arows = a.row_list()
                if arows is not None and shape \
                        and dim_eq(a.shape[0] if a.shape else 1,
                                   shape[0]) \
                        and len(a.shape) == len(shape):
                    rows = arows
                elif shape and isinstance(shape[0], int) \
                        and shape[0] <= ROWS_MAX \
                        and (a.ndim < len(shape)
                             or (a.shape and a.shape[0] == 1)):
                    rows = [iv] * shape[0]
            return Arr(dt, shape, rows, iv)
        if name in ("expand_dims",):
            a = args[0]
            ax = args[1] if len(args) > 1 else kwargs.get("axis", 0)
            if not isinstance(a, Arr):
                a = self.cast(a, "int32", node)
            if not isinstance(ax, int):
                raise AnalysisError("abstract expand_dims axis")
            if ax < 0:
                ax = a.ndim + 1 + ax
            sh = a.shape[:ax] + (1,) + a.shape[ax:]
            rows = [a.iv] if ax == 0 else a.rows
            return Arr(a.dtype, sh, rows, a.iv)
        if name in ("moveaxis", "swapaxes"):
            a, src, dst = args[0], args[1], args[2]
            if not isinstance(a, Arr) or not isinstance(src, int) \
                    or not isinstance(dst, int):
                raise AnalysisError("abstract moveaxis")
            nd = a.ndim
            src %= nd
            dst %= nd
            order = [i for i in range(nd) if i != src]
            order.insert(dst, src)
            if name == "swapaxes":
                order = list(range(nd))
                order[src], order[dst] = order[dst], order[src]
            sh = tuple(a.shape[i] for i in order)
            rows = a.rows if order and order[0] == 0 else None
            return Arr(a.dtype, sh, rows, a.iv)
        if name == "transpose":
            return self.intrinsic_transpose(
                args[0], tuple(args[1]) if len(args) > 1 else None,
                node)
        if name == "squeeze":
            return self.arr_method(args[0], "squeeze", [], {}, node)
        if name in ("logical_and", "logical_or", "logical_xor"):
            sh = broadcast_shapes(
                *(a.shape for a in args if isinstance(a, Arr))) or ()
            return Arr("bool", sh, None, IV(0, 1))
        if name == "logical_not":
            a = args[0]
            sh = a.shape if isinstance(a, Arr) else ()
            return Arr("bool", sh, None, IV(0, 1))
        if name == "invert":
            return self.unary_invert(args[0], node)
        if name == "dot":
            return self.intrinsic_dot(args[0], args[1], node)
        if name == "cumsum":
            a = args[0]
            if not isinstance(a, Arr):
                raise AnalysisError("cumsum of non-array")
            n = dim_iv(a.shape[0] if a.shape else 1)
            iv = iv_mul(a.iv, IV(min(1, n.hi), max(1, n.hi)))
            return self.finish(Arr(a.dtype, a.shape, None, iv), node)
        raise AnalysisError(f"unmodeled jnp.{name}")

    def unary_invert(self, a: Any, node) -> Any:
        iv = iv_of(a)
        out = IV(-iv.hi - 1, -iv.lo - 1)
        if isinstance(a, Arr):
            rows = a.row_list()
            if rows is not None:
                rows = [IV(-r.hi - 1, -r.lo - 1) for r in rows]
            return self.finish(Arr(a.dtype, a.shape, rows, out), node)
        return out

    def intrinsic_stack(self, seq: Any, axis: Any, node) -> Arr:
        if not isinstance(seq, (list, tuple)):
            raise AnalysisError("stack of abstract sequence")
        if not seq:
            raise AnalysisError("stack of empty sequence")
        elems = [e if isinstance(e, Arr)
                 else Arr("int32", (), None, iv_of(e)) for e in seq]
        dt = None
        for e in elems:
            dt = promote(dt, e.dtype)
        sh = elems[0].shape
        for e in elems[1:]:
            u = []
            if len(e.shape) != len(sh):
                raise AnalysisError(
                    f"ragged stack {shape_sig(sh)} vs "
                    f"{shape_sig(e.shape)} at line "
                    f"{getattr(node, 'lineno', '?')}")
            for d1, d2 in zip(sh, e.shape):
                ud = unify_dim(d1, d2)
                if ud is None:
                    raise AnalysisError("ragged stack dims")
                u.append(ud)
            sh = tuple(u)
        iv = elems[0].iv
        for e in elems[1:]:
            iv = iv.join(e.iv)
        if not isinstance(axis, int):
            raise AnalysisError("abstract stack axis")
        nd = len(sh) + 1
        if axis < 0:
            axis += nd
        if not 0 <= axis < nd:
            raise AnalysisError(f"stack axis={axis}")
        out_sh = sh[:axis] + (len(elems),) + sh[axis:]
        rows = None
        if axis == 0 and len(elems) <= ROWS_MAX:
            rows = [e.iv for e in elems]
        elif axis > 0 and elems[0].rows is not None \
                and all(e.rows is not None
                        and len(e.rows) == len(elems[0].rows)
                        for e in elems):
            # stacking along a later axis keeps the leading axis —
            # per-row bounds survive as the joins across elements
            rows = [elems[0].rows[i]
                    for i in range(len(elems[0].rows))]
            for e in elems[1:]:
                rows = [r.join(er) for r, er in zip(rows, e.rows)]
        return self.finish(
            Arr(dt or "int32", out_sh, rows, iv), node)

    def intrinsic_concat(self, seq: Any, axis: Any, node) -> Arr:
        if not isinstance(seq, (list, tuple)) or not seq:
            raise AnalysisError("concatenate of abstract sequence")
        elems = [e for e in seq if isinstance(e, Arr)]
        if len(elems) != len(seq):
            raise AnalysisError("concatenate of non-arrays")
        dt = None
        for e in elems:
            dt = promote(dt, e.dtype)
        nd = elems[0].ndim
        if axis is None:
            axis = 0
        if axis < 0:
            axis += nd
        iv = elems[0].iv
        for e in elems[1:]:
            iv = iv.join(e.iv)
        if axis == 0:
            rows: Optional[List[IV]] = []
            total: Any = 0
            for e in elems:
                er = e.row_list()
                d0 = e.shape[0]
                if rows is not None and er is not None:
                    rows.extend(er)
                else:
                    rows = None
                if isinstance(total, int) and isinstance(d0, int):
                    total += d0
                else:
                    total = iv_add(dim_iv(total) if not isinstance(
                        total, IV) else total, dim_iv(d0))
            if rows is not None and (not isinstance(total, int)
                                     or len(rows) != total
                                     or total > ROWS_MAX):
                rows = None
            sh = (total,) + elems[0].shape[1:]
            return Arr(dt or "int32", sh, rows, iv)
        # non-leading axis: axis-0 length unchanged; join rows
        rows2 = elems[0].row_list()
        for e in elems[1:]:
            er = e.row_list()
            if rows2 is None or er is None or len(er) != len(rows2):
                rows2 = None
                break
            rows2 = [r1.join(r2) for r1, r2 in zip(rows2, er)]
        dim: Any = 0
        for e in elems:
            d = e.shape[axis]
            if isinstance(dim, int) and isinstance(d, int):
                dim += d
            else:
                dim = IV(0, INF)
        sh = elems[0].shape[:axis] + (dim,) + elems[0].shape[axis + 1:]
        return Arr(dt or "int32", sh, rows2, iv)

    def intrinsic_where(self, cond: Any, x: Any, y: Any, node) -> Arr:
        shapes = [v.shape for v in (cond, x, y) if isinstance(v, Arr)]
        sh = broadcast_shapes(*shapes) if shapes else ()
        if sh is None:
            raise AnalysisError("where: unbroadcastable shapes")
        dt = promote(x.dtype if isinstance(x, Arr) else None,
                     y.dtype if isinstance(y, Arr) else None)
        xa = x if isinstance(x, Arr) else Arr(dt, (), None, iv_of(x))
        ya = y if isinstance(y, Arr) else Arr(dt, (), None, iv_of(y))
        rows = self.zip_rows(xa, ya, xa, ya, sh,
                             lambda p, q: p.join(q))
        if rows is not None and any(r is None for r in rows):
            rows = None
        return self.finish(Arr(dt, sh, rows, xa.iv.join(ya.iv)), node)

    def intrinsic_minmax(self, x: Any, y: Any, is_min: bool,
                         node) -> Any:
        def mm(p: IV, q: IV) -> IV:
            if is_min:
                return IV(min(p.lo, q.lo), min(p.hi, q.hi))
            return IV(max(p.lo, q.lo), max(p.hi, q.hi))
        if not isinstance(x, Arr) and not isinstance(y, Arr):
            return mm(iv_of(x), iv_of(y))
        dt = promote(x.dtype if isinstance(x, Arr) else None,
                     y.dtype if isinstance(y, Arr) else None)
        xa = x if isinstance(x, Arr) else Arr(dt, (), None, iv_of(x))
        ya = y if isinstance(y, Arr) else Arr(dt, (), None, iv_of(y))
        sh = broadcast_shapes(xa.shape, ya.shape)
        if sh is None:
            raise AnalysisError("minimum/maximum: bad shapes")
        rows = self.zip_rows(xa, ya, xa, ya, sh, mm)
        if rows is not None and any(r is None for r in rows):
            rows = None
        return self.finish(Arr(dt, sh, rows, mm(xa.iv, ya.iv)), node)

    def intrinsic_sum(self, a: Any, axis: Any, node) -> Any:
        if isinstance(a, (list, tuple)):
            out: Any = 0
            for x in a:
                out = self.binop(out, ast.Add(), x, node)
            return out
        if not isinstance(a, Arr):
            return a
        rows = a.row_list()
        inner = shape_numel(a.shape[1:]) if a.shape else 1
        if axis is None:
            if rows is not None and inner is not None:
                lo = sum(r.lo for r in rows) * inner \
                    if inner >= 0 else 0
                hi = sum(r.hi for r in rows) * inner
                lo, hi = min(lo, hi), max(lo, hi)
                return self.finish(Arr(a.dtype, (), None, IV(lo, hi)),
                                   node)
            n = shape_numel(a.shape)
            niv = IV(n, n) if n is not None else IV(0, DEFAULT_DIM_HI)
            if a.shape and not isinstance(a.shape[0], int):
                niv = dim_iv(a.shape[0])
                for d in a.shape[1:]:
                    niv = iv_mul(niv, dim_iv(d))
            return self.finish(
                Arr(a.dtype, (), None, iv_mul(a.iv, niv)), node)
        if isinstance(axis, int) and axis < 0:
            axis += a.ndim
        if axis == 0:
            sh = a.shape[1:]
            if rows is not None:
                iv = IV(sum(r.lo for r in rows),
                        sum(r.hi for r in rows))
            else:
                iv = iv_mul(a.iv, dim_iv(a.shape[0]))
            return self.finish(Arr(a.dtype, sh, None, iv), node)
        if isinstance(axis, int) and 0 < axis < a.ndim:
            d = dim_iv(a.shape[axis])
            sh = a.shape[:axis] + a.shape[axis + 1:]
            iv = iv_mul(a.iv, d)
            out_rows = rows
            if rows is not None and a.shape[axis:axis + 1] \
                    and isinstance(a.shape[axis], int):
                k = a.shape[axis]
                out_rows = [IV(r.lo * k, r.hi * k) if r.lo >= 0
                            else iv_mul(r, IV(k, k)) for r in rows]
            return self.finish(Arr(a.dtype, sh, out_rows, iv), node)
        raise AnalysisError(f"sum axis={axis!r}")

    def intrinsic_dot(self, a: Any, b: Any, node) -> Arr:
        if not isinstance(a, Arr) or not isinstance(b, Arr):
            raise AnalysisError("dot of non-arrays")
        if a.ndim == 1 and b.ndim == 1:
            k = dim_iv(a.shape[0])
            sh: Tuple[Dim, ...] = ()
        elif a.ndim == 2 and b.ndim == 1:
            k = dim_iv(a.shape[1])
            sh = (a.shape[0],)
        elif a.ndim == 1 and b.ndim == 2:
            k = dim_iv(a.shape[0])
            sh = (b.shape[1],)
        else:
            k = dim_iv(a.shape[-1])
            sh = a.shape[:-1] + b.shape[1:]
        prod = iv_mul(a.iv, b.iv)
        return self.finish(
            Arr(promote(a.dtype, b.dtype), sh, None,
                iv_mul(prod, k)), node)

    def intrinsic_reshape(self, a: Any, shape: Tuple[Any, ...],
                          node) -> Arr:
        if not isinstance(a, Arr):
            a = self.cast(a, "int32", node)
        n = shape_numel(a.shape)
        shape = tuple(shape)
        if -1 in shape:
            known = 1
            ok = True
            for d in shape:
                if d == -1:
                    continue
                if not isinstance(d, int):
                    ok = False
                    break
                known *= d
            if ok and n is not None and known and n % known == 0:
                shape = tuple(n // known if d == -1 else d
                              for d in shape)
            else:
                shape = tuple(IV(0, INF) if d == -1 else d
                              for d in shape)
        rows = a.row_list()
        out_rows: Optional[List[IV]] = None
        if rows is not None and shape:
            n0 = shape[0]
            if isinstance(n0, int) and dim_eq(a.shape[0], n0):
                out_rows = rows
            elif isinstance(n0, int) and n0 and len(rows) % n0 == 0 \
                    and n0 <= ROWS_MAX:
                k = len(rows) // n0
                out_rows = []
                for i in range(n0):
                    h = rows[i * k]
                    for r in rows[i * k + 1:(i + 1) * k]:
                        h = h.join(r)
                    out_rows.append(h)
            elif isinstance(n0, int) and len(rows) and \
                    n0 % len(rows) == 0 and n0 <= ROWS_MAX:
                k = n0 // len(rows)
                out_rows = [r for r in rows for _ in range(k)]
        return Arr(a.dtype, shape, out_rows, a.iv)

    def intrinsic_transpose(self, a: Any, axes: Optional[tuple],
                            node) -> Arr:
        if not isinstance(a, Arr):
            raise AnalysisError("transpose of non-array")
        if axes is None:
            axes = tuple(reversed(range(a.ndim)))
        sh = tuple(a.shape[i] for i in axes)
        rows = a.rows if axes and axes[0] == 0 else None
        return Arr(a.dtype, sh, rows, a.iv)

    # -- lax intrinsics ----------------------------------------------------

    def lax_call(self, name: str, args: list, kwargs: dict,
                 node, frame: Frame) -> Any:
        if name == "scan":
            return self.lax_scan(args, kwargs, node, frame)
        if name == "fori_loop":
            return self.lax_fori(args, kwargs, node, frame)
        if name == "while_loop":
            return self.lax_while(args, kwargs, node, frame)
        if name == "cond":
            return self.lax_cond(args, kwargs, node, frame)
        if name == "select":
            return self.intrinsic_where(args[0], args[1], args[2],
                                        node)
        if name == "dynamic_index_in_dim":
            operand, index = args[0], args[1]
            axis = kwargs.get("axis",
                              args[2] if len(args) > 2 else 0)
            keepdims = kwargs.get(
                "keepdims", args[3] if len(args) > 3 else True)
            if axis != 0 or not isinstance(operand, Arr):
                raise AnalysisError("dynamic_index_in_dim axis != 0")
            sub = self.index_axis0(
                operand,
                index if isinstance(index, (int, IV, SymDim))
                else None, node)
            if keepdims is True:
                return Arr(sub.dtype, (1,) + tuple(sub.shape),
                           [sub.iv], sub.iv)
            return sub
        if name == "dynamic_slice":
            operand, starts, sizes = args[0], args[1], args[2]
            if not isinstance(operand, Arr):
                raise AnalysisError("dynamic_slice of non-array")
            return Arr(operand.dtype, tuple(sizes), None, operand.iv)
        if name == "dynamic_update_slice":
            operand, update = args[0], args[1]
            if not isinstance(operand, Arr):
                raise AnalysisError("dynamic_update_slice target")
            uiv = iv_of(update)
            return self.finish(
                Arr(operand.dtype, operand.shape, None,
                    operand.iv.join(uiv)), node)
        if name in ("bitcast_convert_type",):
            raise AnalysisError("bitcast reinterprets bits")
        raise AnalysisError(f"unmodeled lax.{name}")

    def lax_scan(self, args: list, kwargs: dict, node,
                 frame: Frame) -> Any:
        f = args[0] if args else kwargs.get("f")
        init = args[1] if len(args) > 1 else kwargs.get("init")
        xs = args[2] if len(args) > 2 else kwargs.get("xs")
        length = kwargs.get("length")
        if not isinstance(f, (Clo, Partial, Jitted)):
            raise AnalysisError("scan of non-closure")

        def leaf_elem(v: Any) -> Any:
            if isinstance(v, Arr):
                return self.index_axis0(v, None, node)
            if isinstance(v, (tuple, list)):
                return type(v)(leaf_elem(e) for e in v)
            if v is None:
                return None
            raise AnalysisError(
                f"scan xs of abstract structure ({type(v).__name__}"
                f" {str(v)[:40]})")

        def lead_dim(v: Any) -> Any:
            if isinstance(v, Arr):
                return v.shape[0] if v.shape else 1
            if isinstance(v, (tuple, list)):
                for e in v:
                    d = lead_dim(e)
                    if d is not None:
                        return d
            return None

        x_elem = leaf_elem(xs) if xs is not None else None
        n = length if length is not None else lead_dim(xs)
        if n is None:
            n = IV(0, DEFAULT_DIM_HI)
        carry = init
        y_out: Any = None
        for it in range(JOIN_CAP + WIDEN_EXTRA):
            out = self.apply(f, [carry, x_elem], {}, node, frame)
            if not (isinstance(out, tuple) and len(out) == 2):
                raise AnalysisError("scan body must return (carry, y)")
            new_carry, y = out
            y_out = y if y_out is None else vjoin(y_out, y)
            joined = vjoin(carry, new_carry)
            if veq(joined, carry):
                break
            carry = vwiden(carry, joined) if it >= JOIN_CAP else joined
        else:
            raise AnalysisError("scan carry did not converge")

        def stack_leaf(v: Any) -> Any:
            if isinstance(v, Arr):
                rows = None
                if isinstance(n, int) and n <= ROWS_MAX:
                    rows = [v.iv] * n
                return Arr(v.dtype, (n,) + tuple(v.shape), rows, v.iv)
            if isinstance(v, (tuple, list)):
                return type(v)(stack_leaf(e) for e in v)
            if v is None:
                return None
            if isinstance(v, (int, bool, IV, SymDim)):
                iv = iv_of(v)
                rows = [iv] * n if isinstance(n, int) \
                    and n <= ROWS_MAX else None
                return Arr("int32", (n,), rows, iv)
            raise AnalysisError("scan y of abstract structure")

        return (carry, stack_leaf(y_out))

    def lax_fori(self, args: list, kwargs: dict, node,
                 frame: Frame) -> Any:
        lo, hi, body, init = args[0], args[1], args[2], args[3]
        if not isinstance(body, (Clo, Partial, Jitted)):
            raise AnalysisError("fori_loop of non-closure")
        if isinstance(lo, bool):
            lo = int(lo)
        if isinstance(hi, bool):
            hi = int(hi)
        if isinstance(lo, int) and isinstance(hi, int) \
                and hi - lo <= UNROLL_MAX:
            val = init
            for i in range(lo, hi):
                val = self.apply(body, [i, val], {}, node, frame)
            return val
        ilo = iv_of(lo)
        ihi = iv_of(hi)
        i_iv = IV(ilo.lo, ihi.hi - 1)
        val = init
        for it in range(JOIN_CAP + WIDEN_EXTRA):
            new = self.apply(body, [i_iv, val], {}, node, frame)
            joined = vjoin(val, new)
            if veq(joined, val):
                break
            val = vwiden(val, joined) if it >= JOIN_CAP else joined
        else:
            raise AnalysisError("fori_loop did not converge")
        return val

    def lax_while(self, args: list, kwargs: dict, node,
                  frame: Frame) -> Any:
        cond_fn, body_fn, init = args[0], args[1], args[2]
        val = init
        for it in range(JOIN_CAP + WIDEN_EXTRA):
            t = self.truth(self.apply(cond_fn, [val], {}, node, frame))
            if t is False:
                return val
            new = self.apply(body_fn, [val], {}, node, frame)
            joined = vjoin(val, new)
            if veq(joined, val):
                break
            val = vwiden(val, joined) if it >= JOIN_CAP else joined
        else:
            raise AnalysisError("while_loop did not converge")
        # run cond once more for its own findings, then return the fix
        self.apply(cond_fn, [val], {}, node, frame)
        return val

    def lax_cond(self, args: list, kwargs: dict, node,
                 frame: Frame) -> Any:
        pred, tf, ff = args[0], args[1], args[2]
        operands = args[3:]
        t = self.truth(pred)
        if t is True:
            return self.apply(tf, list(operands), {}, node, frame)
        if t is False:
            return self.apply(ff, list(operands), {}, node, frame)
        a = self.apply(tf, list(operands), {}, node, frame)
        b = self.apply(ff, list(operands), {}, node, frame)
        return vjoin(a, b)

    # -- jax / pallas / functools intrinsics -------------------------------

    def intrinsic_call(self, b: Bound, args: list, kwargs: dict,
                       node, frame: Frame) -> Any:
        ns = b.recv
        if ns == "functools":
            if b.name == "partial":
                return Partial(args[0], tuple(args[1:]), dict(kwargs))
            # lru_cache()/cache/wraps: identity decorator for analysis
            if args and isinstance(args[0], (Clo, Partial, Jitted,
                                             Bound, RealFn)):
                return args[0]
            return Bound("intrinsic", "functools", "lru_cache")
        if ns == "jax" and b.name == "ShapeDtypeStruct":
            shape = args[0] if args else kwargs.get("shape")
            dt = args[1] if len(args) > 1 else kwargs.get("dtype")
            dt = dt.name if isinstance(dt, DtypeVal) else dt
            return SDS(tuple(shape), dt or "int32")
        if ns == "pl":
            if b.name == "BlockSpec":
                block = args[0] if args else kwargs.get("block_shape")
                imap = args[1] if len(args) > 1 \
                    else kwargs.get("index_map")
                return BlockSpec(
                    tuple(block) if block is not None else None, imap)
            if b.name == "program_id":
                ax = args[0] if args else kwargs.get("axis", 0)
                grid = self.a.grid
                if grid is None:
                    raise AnalysisError("program_id outside kernel")
                if not isinstance(ax, int) or ax >= len(grid):
                    raise AnalysisError("bad program_id axis")
                d = dim_iv(grid[ax])
                return IV(0, d.hi - 1)
            if b.name == "pallas_call":
                kern = args[0] if args else kwargs.pop("kernel", None)
                return Bound("pallascall", (kern, dict(kwargs)),
                             "pallas")
        if ns == "pltpu" and b.name == "VMEM":
            shape = args[0] if args else kwargs.get("shape")
            dt = args[1] if len(args) > 1 else kwargs.get("dtype")
            dt = dt.name if isinstance(dt, DtypeVal) else dt
            return VMEM(tuple(shape), dt or "int32")
        if ns == "tree":
            if b.name == "tree_map":
                return self.tree_map(args[0], args[1:], node, frame)
            raise AnalysisError(f"unmodeled tree_util.{b.name}")
        raise AnalysisError(f"unmodeled intrinsic {ns}.{b.name}")

    def tree_map(self, f: Any, trees: list, node, frame: Frame) -> Any:
        if not trees:
            raise AnalysisError("tree_map with no trees")

        def rec(parts):
            first = parts[0]
            if isinstance(first, (tuple, list)):
                return type(first)(
                    rec([p[i] for p in parts])
                    for i in range(len(first)))
            if isinstance(first, dict):
                return {k: rec([p[k] for p in parts]) for k in first}
            return self.apply(f, list(parts), {}, node, frame)
        return rec(trees)

    # -- pallas kernels ----------------------------------------------------

    def call_pallas(self, spec: tuple, args: list, node,
                    frame: Frame) -> Any:
        kern, kw = spec
        if not isinstance(kern, (Clo, Partial, Jitted)):
            raise AnalysisError("pallas kernel is not a closure")
        grid = kw.get("grid", ())
        if isinstance(grid, (int, IV, SymDim)):
            grid = (grid,)
        grid = tuple(grid)
        out_shape = kw.get("out_shape")
        in_specs = kw.get("in_specs")
        out_specs = kw.get("out_specs")
        scratch = kw.get("scratch_shapes", ()) or ()

        def block_of(spec_v: Any, full: Tuple[Dim, ...]) \
                -> Tuple[Dim, ...]:
            if isinstance(spec_v, BlockSpec) \
                    and spec_v.block_shape is not None:
                return tuple(d for d in spec_v.block_shape)
            return full

        in_refs = []
        specs_list = list(in_specs) if isinstance(
            in_specs, (list, tuple)) else [None] * len(args)
        if len(specs_list) < len(args):
            specs_list += [None] * (len(args) - len(specs_list))
        for v, sp in zip(args, specs_list):
            if isinstance(v, Arr):
                shape = block_of(sp, v.shape)
                r = Ref(v.dtype, tuple(shape))
                full_block = all(dim_eq(a_d, b_d) for a_d, b_d in
                                 zip(v.shape, shape)) \
                    and len(shape) == len(v.shape)
                rows = v.row_list() if full_block else None
                if rows is not None and shape \
                        and isinstance(shape[0], int) \
                        and len(rows) == shape[0]:
                    r.rows = list(rows)
                else:
                    r.rows = None
                    r.hull = v.iv
                r.written = True
                in_refs.append(r)
            elif isinstance(v, Opaque):
                r = Ref("int32", (IV(1, DEFAULT_DIM_HI),))
                r.rows = None
                r.hull = DT_IV("int32")
                r.written = True
                in_refs.append(r)
            else:
                # scalar-prefetch style arg passes through unchanged
                in_refs.append(v)

        outs = out_shape if isinstance(out_shape, (list, tuple)) \
            else [out_shape]
        osp = out_specs if isinstance(out_specs, (list, tuple)) \
            else [out_specs] * len(outs)
        out_refs = []
        for o, sp in zip(outs, osp):
            if not isinstance(o, SDS):
                raise AnalysisError("pallas out_shape must be SDS")
            out_refs.append(Ref(o.dtype, block_of(sp, o.shape)))
        scratch_refs = []
        for s in scratch:
            if isinstance(s, VMEM):
                scratch_refs.append(Ref(s.dtype, s.shape))
            else:
                raise AnalysisError("unmodeled scratch shape")

        prev = self.a.grid
        self.a.grid = grid
        try:
            self.apply(kern, in_refs + out_refs + scratch_refs, {},
                       node, frame)
        finally:
            self.a.grid = prev

        results = []
        for o, r in zip(outs, out_refs):
            v = r.value()
            iv = v.iv if v is not None else DT_IV(o.dtype)
            rows = None
            if v is not None and v.rows is not None and o.shape \
                    and isinstance(o.shape[0], int) \
                    and len(v.rows) == o.shape[0]:
                rows = v.rows
            results.append(Arr(o.dtype, tuple(o.shape), rows, iv))
        if isinstance(out_shape, (list, tuple)):
            return tuple(results)
        return results[0]

def _dotted_name(ctx: FileCtx, node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute via the file's import aliases."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    base = ctx.from_imports.get(node.id)
    if base is None:
        mod = ctx.module_aliases.get(node.id)
        base = mod if mod is not None else node.id
    parts.append(base)
    return ".".join(reversed(parts))


def _is_jit_name(dn: Optional[str]) -> bool:
    return dn is not None and (dn == "jit" or dn.endswith(".jit"))


def _static_names_of(call: ast.Call) -> Tuple[str, ...]:
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        out.append(e.value)
                return tuple(out)
    return ()


class Analysis:
    """One whole-tree interval analysis: module scopes, the abstract
    interpreter, entry discovery/seeding, findings, obligations."""

    def __init__(self, ctxs: Dict[str, FileCtx]):
        self.ctxs = ctxs
        self.modscopes: Dict[str, ModScope] = {}
        self._ctx_stack: List[FileCtx] = []
        self.findings: Dict[Tuple[str, int, str],
                            Tuple[str, FileCtx]] = {}
        self._captures: List[list] = []
        self.used_assumes: Set[Tuple[str, int]] = set()
        self.obligations: List[Dict[str, Any]] = []
        self.covered: Set[str] = set()
        self.entries: List[str] = []
        self.in_entry = False
        self.grid: Optional[Tuple[Any, ...]] = None
        self.pending: List[Tuple[Jitted, tuple, dict]] = []
        self._entry_keys: Set[Any] = set()
        self._factory_done: Set[Any] = set()
        self.interp = Interp(self)
        for path, ctx in sorted(ctxs.items()):
            self.modscopes[_posix_module(path)] = ModScope(self, ctx)

    # -- context & findings ------------------------------------------------

    def cur_ctx(self) -> Optional[FileCtx]:
        return self._ctx_stack[-1] if self._ctx_stack else None

    def push_ctx(self, ctx: FileCtx) -> None:
        self._ctx_stack.append(ctx)

    def pop_ctx(self) -> None:
        self._ctx_stack.pop()

    def add_finding(self, path: str, line: int, kind: str, msg: str,
                    ctx: FileCtx) -> None:
        # overwrite-dict keyed by site: fixpoint iterations report
        # monotonically growing bounds; the stabilized iteration's
        # message (written last) is the one that survives
        self.findings[(path, line, kind)] = (msg, ctx)
        for cap in self._captures:
            cap.append((path, line, kind, msg, ctx))

    def replay(self, rec) -> None:
        path, line, kind, msg, ctx = rec
        self.add_finding(path, line, kind, msg, ctx)

    def push_capture(self) -> list:
        cap: list = []
        self._captures.append(cap)
        return cap

    def pop_capture(self, cap: list) -> list:
        # pop by IDENTITY — list.remove() matches by equality and two
        # empty capture lists are equal, silently popping the wrong one
        for i in range(len(self._captures) - 1, -1, -1):
            if self._captures[i] is cap:
                del self._captures[i]
                break
        return cap

    def add_obligation(self, frame: Frame, spec: Assume,
                       stmt: ast.stmt, got: IV) -> None:
        self.obligations.append({
            "path": frame.ctx.path,
            "qual": frame.qual,
            "func": frame.qual.split(".")[-1],
            "var": spec.var,
            "lo": spec.lo,
            "hi": spec.hi,
            "line": spec.line,
            "computed": (got.lo, got.hi),
            "on_return": isinstance(stmt, ast.Return),
        })

    # -- entry discovery ---------------------------------------------------

    def register_entry(self, j: Jitted, node,
                       prefix: tuple = (),
                       prekw: Optional[dict] = None) -> None:
        clo = j.clo
        try:
            capsig = tuple(
                sorted((k, sig_of(v))
                       for sc in clo.scopes for k, v in sc.items()))
        except TypeError:
            capsig = None
        key = (clo.path, clo.qual, capsig)
        if key in self._entry_keys:
            return
        self._entry_keys.add(key)
        self.pending.append((j, tuple(prefix), dict(prekw or {})))

    def discover(self) -> None:
        for modname in sorted(self.modscopes):
            mod = self.modscopes[modname]
            ctx = mod.ctx
            for fnode in ctx.tree.body:
                if not isinstance(fnode, ast.FunctionDef):
                    continue
                static = self._decorator_static(ctx, fnode)
                if static is not None:
                    clo = mod.get(fnode.name)
                    if isinstance(clo, Clo):
                        self.register_entry(Jitted(clo, static), fnode)
                elif self._contains_jit_call(ctx, fnode):
                    self._seed_factory(mod, fnode)
            # module-level `verify = jax.jit(core, ...)` /
            # `tile = pl.pallas_call(...)` style assigns: force-evaluate
            # so make_jit/pallas registration fires
            for name, stmt in sorted(mod.assigns.items()):
                if any(isinstance(n, ast.Call)
                       and _is_jit_name(_dotted_name(ctx, n.func))
                       for n in ast.walk(stmt)):
                    mod.get(name)

    @staticmethod
    def _decorator_static(ctx: FileCtx, fnode: ast.FunctionDef) \
            -> Optional[Tuple[str, ...]]:
        """static_argnames if fnode is jit-decorated, else None."""
        for dec in fnode.decorator_list:
            if _is_jit_name(_dotted_name(ctx, dec)):
                return ()
            if isinstance(dec, ast.Call):
                dn = _dotted_name(ctx, dec.func)
                if _is_jit_name(dn):
                    return _static_names_of(dec)
                if dn is not None and dn.endswith("partial") \
                        and dec.args and _is_jit_name(
                            _dotted_name(ctx, dec.args[0])):
                    return _static_names_of(dec)
        return None

    @staticmethod
    def _contains_jit_call(ctx: FileCtx, fnode: ast.FunctionDef) -> bool:
        for n in ast.walk(fnode):
            if isinstance(n, ast.Call) \
                    and _is_jit_name(_dotted_name(ctx, n.func)):
                return True
        return False

    def _seed_factory(self, mod: ModScope, fnode: ast.FunctionDef) -> None:
        """A plain function whose body jits a closure (the lru_cached
        `_compiled(bucket, bits)` pattern): call it with params seeded
        from its def-site assume() pragmas, or from a call site whose
        arguments are module-level constants — interpreting the body
        registers the inner jit closure with its live captured env."""
        clo = mod.get(fnode.name)
        if not isinstance(clo, Clo):
            return
        seeds = self._factory_seed_args(mod, fnode)
        if seeds is None:
            self.add_finding(
                mod.path, fnode.lineno, "entry-precondition",
                f"factory {fnode.name}() jits a kernel but its "
                f"parameters cannot be seeded — add assume() pragmas "
                f"between def and body", mod.ctx)
            return
        try:
            fkey = (mod.path, fnode.name, tuple(
                sig_of(s) for s in seeds))
        except TypeError:
            fkey = (mod.path, fnode.name, None)
        if fkey in self._factory_done:
            return
        self._factory_done.add(fkey)
        try:
            self.interp.call_clo(clo, list(seeds), {}, None)
        except (AnalysisError, RecursionError) as e:
            self.add_finding(
                mod.path, fnode.lineno, "interval-crash",
                f"interval analyzer failed seeding factory "
                f"{fnode.name}: {e}", mod.ctx)

    def _entry_specs(self, ctx: FileCtx,
                     fnode: ast.FunctionDef) -> Dict[str, Assume]:
        body_start = fnode.body[0].lineno if fnode.body \
            else fnode.lineno + 1
        return {sp.var: sp for sp in
                ctx.assumes_between(fnode.lineno, body_start)}

    def _factory_seed_args(self, mod: ModScope,
                           fnode: ast.FunctionDef) -> Optional[list]:
        """Per-parameter seeding: def-site assume() pragma first, else
        the module-level constant the call sites pass (traced through
        intermediate host drivers — pow_is_one_batch hands HARD_BITS
        to _compiled through its own `bits` parameter)."""
        specs = self._entry_specs(mod.ctx, fnode)
        args = []
        for i, p in enumerate(fnode.args.posonlyargs
                              + fnode.args.args):
            sp = specs.get(p.arg)
            if sp is not None:
                self.used_assumes.add((mod.ctx.path, sp.line))
                args.append(IV(sp.lo, sp.hi)
                            if sp.lo != sp.hi else sp.lo)
                continue
            v = self._trace_const_arg(fnode.name, i, set())
            if v is None:
                return None
            args.append(v)
        return args

    @staticmethod
    def _concrete_host(v: Any) -> bool:
        if isinstance(v, (int, bool)):
            return True
        if isinstance(v, tuple):
            return all(isinstance(e, (int, bool)) for e in v)
        return False

    def _trace_const_arg(self, fname: str, argpos: int,
                         seen: Set[Tuple[str, int]]) -> Any:
        """Concrete host value flowing into parameter `argpos` of
        `fname` at some call site, following same-named parameters
        through intermediate functions up to the module constant."""
        if (fname, argpos) in seen or len(seen) > 8:
            return None
        seen.add((fname, argpos))
        for modname in sorted(self.modscopes):
            peer = self.modscopes[modname]
            tree = peer.ctx.tree
            for fdef in tree.body:
                if not isinstance(fdef, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                fparams = [q.arg for q in fdef.args.posonlyargs
                           + fdef.args.args]
                for call in ast.walk(fdef):
                    if not isinstance(call, ast.Call):
                        continue
                    cf = call.func
                    if not ((isinstance(cf, ast.Name)
                             and cf.id == fname)
                            or (isinstance(cf, ast.Attribute)
                                and cf.attr == fname)):
                        continue
                    if argpos >= len(call.args):
                        continue
                    a = call.args[argpos]
                    if isinstance(a, ast.Constant) \
                            and isinstance(a.value, (int, bool)):
                        return a.value
                    if not isinstance(a, ast.Name):
                        continue
                    v = peer.get(a.id)
                    if self._concrete_host(v):
                        return v
                    if a.id in fparams:
                        r = self._trace_const_arg(
                            fdef.name, fparams.index(a.id), seen)
                        if r is not None:
                            return r
        return None

    # -- entry runs --------------------------------------------------------

    def run(self) -> None:
        self.discover()
        while self.pending:
            j, prefix, prekw = self.pending.pop(0)
            self.run_entry(j, prefix, prekw)

    def _spec_value(self, spec: Assume, is_static: bool,
                    dims: Dict[str, SymDim]) -> Any:
        iv = IV(spec.lo, spec.hi)
        if spec.shape is None:
            if is_static:
                return iv if spec.lo != spec.hi else spec.lo
            return Arr(spec.dtype, (), None, iv)
        shape = tuple(
            dims.setdefault(d, SymDim(d)) if isinstance(d, str) else d
            for d in spec.shape)
        rows = None
        if shape and isinstance(shape[0], int) \
                and shape[0] <= ROWS_MAX:
            rows = [iv] * shape[0]
        return Arr(spec.dtype, shape, rows, iv)

    def run_entry(self, j: Jitted, prefix: tuple, prekw: dict) -> None:
        clo = j.clo
        ctx = clo.mod.ctx
        fnode = clo.node
        label = f"{clo.path}::{clo.qual}"
        self.entries.append(label)
        if isinstance(fnode, ast.Lambda):
            self.add_finding(clo.path, fnode.lineno,
                             "entry-precondition",
                             "jit of a lambda cannot carry assume() "
                             "preconditions — name the function",
                             ctx)
            return
        specs = self._entry_specs(ctx, fnode)
        dims: Dict[str, SymDim] = {}
        all_params = fnode.args.posonlyargs + fnode.args.args
        params = [p.arg for p in all_params]
        # an assume() on a name that is NOT a parameter bounds a shape
        # symbol instead: `assume(B, 1, 4096)` caps the block-count
        # axis every (N, B, 128) parameter shares
        for sp in specs.values():
            if sp.var not in params and sp.shape is None:
                dims[sp.var] = SymDim(sp.var, IV(sp.lo, sp.hi))
                self.used_assumes.add((ctx.path, sp.line))
        defaults: Dict[str, ast.expr] = {}
        for p, d in zip(all_params[len(all_params)
                                   - len(fnode.args.defaults):],
                        fnode.args.defaults):
            defaults[p.arg] = d
        args: List[Any] = list(prefix)
        for p in params[len(prefix):]:
            if p in prekw:
                args.append(prekw[p])
                continue
            sp = specs.get(p)
            if sp is None:
                if p in defaults:
                    # host-level default (interpret=False, zip215=True)
                    # is the value every kernel trace actually sees
                    dframe = Frame([{}], clo.mod, f"{clo.qual}:<default>")
                    try:
                        args.append(self.interp.eval(defaults[p],
                                                     dframe))
                    except AnalysisError as e:
                        args.append(Opaque(f"default of {p}: {e}"))
                    continue
                self.add_finding(
                    clo.path, fnode.lineno, "entry-precondition",
                    f"entry {clo.qual}() parameter `{p}` lacks an "
                    f"assume() precondition pragma — the int32 proof "
                    f"cannot start unseeded", ctx)
                args.append(Opaque(f"unseeded entry param {p}"))
                continue
            self.used_assumes.add((ctx.path, sp.line))
            args.append(self._spec_value(sp, p in j.static, dims))
        was = self.in_entry
        self.in_entry = True
        try:
            self.interp.call_clo(clo, args, {}, None)
        except (AnalysisError, RecursionError) as e:
            via = " > ".join(getattr(e, "stack", self.interp.stack)[-6:])
            self.add_finding(
                clo.path, fnode.lineno, "interval-crash",
                f"interval analyzer gave up in entry {clo.qual}: {e}"
                f" [in {via}]", ctx)
        finally:
            self.in_entry = was


def analyze_tree(root: str,
                 prefix: str = "cometbft_tpu/ops") -> Analysis:
    """Standalone API (tests, tools/interval_fuzz.py): analyze every
    module under `prefix` and return the finished Analysis."""
    ctxs: Dict[str, FileCtx] = {}
    base = os.path.join(root, prefix)
    for dirpath, _dirs, files in os.walk(base):
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, f), root)
            rel = rel.replace(os.sep, "/")
            ctxs[rel] = FileCtx(root, rel)
    a = Analysis(ctxs)
    a.run()
    return a


class KernelIntervalRule:
    """Interval abstract interpretation over ops/: prove every
    int32-typed value stays inside [-2**31, 2**31) on every path
    reachable from a jit/scan/pallas entry."""
    name = "kernel-interval"
    doc = ("int32 value whose computed interval escapes "
           "[-2**31, 2**31) on a reachable kernel path — or a hole in "
           "the proof (unbounded value, missing assume() "
           "precondition, analyzer bail-out). docs/STATICCHECK.md §v3")
    roots: Tuple[str, ...] = ("cometbft_tpu/ops",)
    exempt: frozenset = frozenset()
    tree_rule = True
    needs_project = True
    audits_assumes = True

    def __init__(self):
        self.used_assumes: Set[Tuple[str, int]] = set()
        self.obligations: List[Dict[str, Any]] = []
        self.covered: Set[str] = set()
        self.entries: List[str] = []

    def applies_to(self, path: str) -> bool:
        if path in self.exempt:
            return False
        return any(path == top or path.startswith(top + "/")
                   for top in self.roots)

    def check(self, ctx: FileCtx) -> Iterable[Finding]:
        return ()

    def finalize(self, root: str, project=None) -> Iterator[Finding]:
        if project is None:
            return
        ctxs = {p: c for p, c in project.ctxs.items()
                if self.applies_to(p)}
        analysis = Analysis(ctxs)
        analysis.run()
        self.used_assumes = analysis.used_assumes
        self.obligations = analysis.obligations
        self.covered = analysis.covered
        self.entries = analysis.entries
        for (path, line, kind) in sorted(analysis.findings):
            msg, ctx = analysis.findings[(path, line, kind)]
            src = ctx.lines[line - 1] \
                if 0 < line <= len(ctx.lines) else ""
            yield Finding(self.name, path, line, f"{kind}: {msg}", src)









