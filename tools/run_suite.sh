#!/bin/bash
# Full test suite in TWO pytest processes instead of one.
#
# Why: this jaxlib's XLA:CPU backend can SEGFAULT (stack-guard hit in
# libjax_common) in a process that has accumulated many kernel
# compilations — the same failure mode that already forces the
# mesh/pallas tests into fresh interpreters (tests/_mesh_harness.py,
# docs/PERF.md "known compile hazard"). A single `pytest tests/` run
# stacks every in-process compile from ~40 modules into one process
# and can cross the cliff mid-suite; splitting at the alphabetical
# midpoint keeps each process's compile count near round-4 levels.
#
# Usage: bash tools/run_suite.sh [extra pytest args]
set -u
cd "$(dirname "$0")/.."
ARGS=("$@")
FIRST=(tests/test_[a-o]*.py)
SECOND=(tests/test_[p-z]*.py)
rc=0
# project-invariant lint first: cheapest check, and a new finding (or
# a stale baseline entry) should fail the suite before any test burns
# compile time (docs/STATICCHECK.md; fix, pragma, or --fix-baseline).
# BUDGET: the whole-program engine (call graph + lock-order +
# verdict-taint + kernel-discipline + the v3 interval/lifecycle/
# contract rules) must stay under 90s for the full tree or it silently
# makes the suite unrunnable — a breach fails the suite; attribute the
# slow rule with `--format json` (rule_seconds). Measured ~30s with
# kernel-interval (the abstract interpreter) taking ~24s of it.
echo "=== staticcheck: project-invariant linter ===" >&2
sc_t0=$(date +%s)
python -m tools.staticcheck || rc=$?
sc_dt=$(( $(date +%s) - sc_t0 ))
if [ "$sc_dt" -gt 90 ]; then
    echo "staticcheck BUDGET BREACH: full-tree analysis took ${sc_dt}s" \
         "(> 90s) — bisect with: python -m tools.staticcheck" \
         "--format json (rule_seconds)" >&2
    rc=1
fi
# SARIF emitter smoke: the code-scanning output must stay parseable
# (cheap per-file rules only — the full tree already ran above)
python -m tools.staticcheck --rule wallclock --rule raw-env \
    --format sarif | python -c "
import json, sys
d = json.load(sys.stdin)
assert d['version'] == '2.1.0' and d['runs'][0]['tool']['driver'], d
" || rc=$?
# interval proof vs. concrete execution: every ops/ kernel fuzzed with
# inputs sampled inside its assume() intervals under the object-int
# shadow backend — a single int32 escape disproves the kernel-interval
# verdict and fails the suite (tools/interval_fuzz.py; full mode runs
# 3 seeds per kernel, this quick mode one)
echo "=== interval_fuzz: concrete no-overflow differential (quick) ===" >&2
python -m tools.interval_fuzz --quick || rc=$?
echo "=== suite 1/2: ${#FIRST[@]} modules (a-o) ===" >&2
python -m pytest "${FIRST[@]}" -q "${ARGS[@]+"${ARGS[@]}"}" || rc=$?
echo "=== suite 2/2: ${#SECOND[@]} modules (p-z) ===" >&2
python -m pytest "${SECOND[@]}" -q "${ARGS[@]+"${ARGS[@]}"}" || rc=$?
echo "=== simnet selftest (determinism + crash recovery + device health) ===" >&2
python tools/sim_run.py --selftest || rc=$?
# device health supervisor liveness/safety sweep (quick): the flap
# scenario must recover to device dispatch, the corrupt scenario must
# quarantine — across a seed range, not just the selftest's seed 1
echo "=== device-flap / device-corrupt quick sweeps ===" >&2
python tools/sim_run.py --scenario device-flap --seeds 0..4 --quick || rc=$?
python tools/sim_run.py --scenario device-corrupt --seeds 0..4 --quick || rc=$?
# per-shard mesh health (mesh/shard_health): a corrupt shard must
# quarantine + re-factor the mesh smaller, the sync must complete with
# zero corrupt verdicts surfaced, and the re-probe must grow it back —
# byte-identical per seed
echo "=== mesh-degrade quick sweep ===" >&2
python tools/sim_run.py --scenario mesh-degrade --seeds 0..4 --quick || rc=$?
# light-farm smoke: the scenario sweep pins determinism + the spec
# oracle; the bench A/B proves coalescing still beats N sequential
# clients (tiny config — the PERF.md datum is the N=32 run)
echo "=== light-farm quick sweep + farm A/B smoke ===" >&2
python tools/sim_run.py --scenario light-farm --seeds 0..4 --quick || rc=$?
python tools/bench_light.py --farm --clients 8 --blocks 12 \
    --validators 20 --json || rc=$?
# ingest front door: the flash-crowd sweep pins overload behavior
# (sheds, dup-filter hits, recheck-eviction release) byte-identical
# per seed; the bench A/B proves batched admission still amortizes the
# stub device round trip (tiny config — PERF.md has the full datum)
echo "=== flash-crowd quick sweep + ingest A/B smoke ===" >&2
python tools/sim_run.py --scenario flash-crowd --seeds 0..4 --quick || rc=$?
python tools/bench_ingest.py --clients 64 --rounds 2 --json || rc=$?
# aggsig: the bls-valset sweep pins the aggregate-commit engine run
# byte-identical per seed WITH sync-vs-aggregate verdict equivalence
# (clean / tampered / forged-bitmap / undercount); the bench smoke
# proves the O(1)-pairings-per-commit A/B still emits (tiny config —
# the PERF.md datum is the 200-validator run)
echo "=== bls-valset quick sweep + aggsig A/B smoke ===" >&2
python tools/sim_run.py --scenario bls-valset --seeds 0..2 --quick || rc=$?
BENCH_AGG_VALS=20 BENCH_AGG_BLOCKS=2 BENCH_AGG_SAMPLE=2 \
    python bench.py --aggsig || rc=$?
# sealsync: the seal-adoption sweep pins aggregate-seal catch-up byte-
# identical per seed — forged seal AND forged bitmap reject at the
# pivot pairing, adoption completes via the honest peer across an
# epoch boundary, and backfill re-pairs nothing (every adopted commit
# a SigCache hit); the bench smoke proves the seal-vs-blocksync A/B
# still emits (tiny config — the PERF.md datum is the 200-validator
# run)
echo "=== seal-adoption quick sweep + sealsync A/B smoke ===" >&2
python tools/sim_run.py --scenario seal-adoption --seeds 0..4 --quick || rc=$?
BENCH_SEAL_VALS=16 BENCH_SEAL_BLOCKS=6 \
    python bench.py --sealsync || rc=$?
# miller kernel smoke: the real fused Miller + final-exp scan against
# host math plus the canary-gated PairingChecker arc (slow-marked: one
# bucket-4 scan compile; suite 1/2's unfiltered run covers it too, but
# this keeps the kernel pinned when the caller filtered with -m)
echo "=== fused miller kernel smoke (slow; one scan compile) ===" >&2
python -m pytest tests/test_aggsig.py -q -m slow -k miller || rc=$?
# flight recorder (trace/): the viewer's invariant selftest (export /
# causal-chain / chrome conversion), then a trace-determinism sweep —
# the traced scenarios must emit byte-identical span streams per seed
# (docs/TRACE.md; the full contract suite is tests/test_trace.py in
# suite 2/2, these two re-pin the acceptance surface cheaply)
echo "=== trace_view selftest + trace-determinism sweep ===" >&2
python tools/trace_view.py --selftest || rc=$?
python -m pytest tests/test_trace.py -q \
    -k "deterministic or byte_identical" || rc=$?
# crash-consistent storage: the crash matrix tears a FileDB batch at
# seeded byte offsets (boundary + interior) and crashes at every
# registered storage fail point, asserting replay recovers the exact
# pre-batch state (full sweep = every offset; docs/STORAGE.md); the
# torn-storage sweep pins the same property end-to-end through a live
# node's save_block + reboot + recovery doctor, byte-identical per seed
echo "=== crash matrix (quick) + torn-storage quick sweep ===" >&2
python tools/crash_matrix.py --quick || rc=$?
python tools/sim_run.py --scenario torn-storage --seeds 0..4 --quick || rc=$?
# suite 2/2 already covers the slow-marked pipeline soak on a default
# (unfiltered) run; this explicit step guarantees the depth sweep even
# when the caller filtered the main suites (e.g. -m 'not slow'), so no
# extra ARGS are forwarded here.
echo "=== pipeline depth-sweep soak (K in {1,2,4,8}) ===" >&2
python -m pytest tests/test_pipeline.py -q -m slow || rc=$?
exit $rc
