"""Crash matrix — prove storage crash-consistency at every registered
fail point and every torn-write byte offset.

Two phases:

1. **Storage-level sweep** (in-process, exhaustive). A scripted batch
   workload runs against a real FileDB; an uninterrupted reference run
   records the state hash after EVERY batch. Then, for each batch and
   each tear offset (every byte offset of the batch's on-disk image in
   the full matrix; boundary + seeded offsets with --quick), the run is
   repeated with a `libs/faultio` plan that shears the write at that
   offset and crashes. The reopened DB must hash to the EXACT pre-batch
   state — a batch is all-or-nothing, never prefix-applied — and
   resuming the remaining batches must reach the byte-identical
   reference final state. The same phase drives the storage-side fail
   points directly: `db:pre-compact-replace` / `db:post-compact-replace`
   (both halves of the compact swap) and `wal:pre-rotate-rename` /
   `wal:post-rotate-rename` (both halves of the WAL rotation), asserting
   the reopened store/WAL lost nothing that was committed.

2. **Consensus-path sweep** (simnet). The fail-point registry table in
   docs/SIMNET.md is parsed, and every label not already pinned by
   phase 1 (and not on the printed skip list — subsystem labels covered
   by their own scenarios) gets a deterministic 4-node simulation with
   `crash_at_label(node 2, label)`: the node must crash at the label,
   reboot through replay + the recovery doctor, and reach the target
   height with the same app hash as its uninterrupted peers — the
   peers ARE the reference run. A registry label that never fires fails
   the matrix loudly, so new fail points cannot dodge coverage.

Usage:
  python tools/crash_matrix.py           # full matrix (every offset)
  python tools/crash_matrix.py --quick   # CI sweep (boundary + seeded
                                         # offsets, 1 seed per label)

Exit 0 on success; on failure prints a CRASH-MATRIX FAIL line naming
the (phase, label/offset) cell and exits 1.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import random
import re
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cometbft_tpu.consensus.wal import WAL, EndHeightMessage  # noqa: E402
from cometbft_tpu.db.kv import FileDB                          # noqa: E402
from cometbft_tpu.libs import fail as libfail                  # noqa: E402
from cometbft_tpu.libs import faultio                          # noqa: E402

# Labels pinned by the storage-level phase — no simnet run needed.
STORAGE_LABELS = {
    "db:pre-compact-replace", "db:post-compact-replace",
    "wal:pre-rotate-rename", "wal:post-rotate-rename",
    "faultio:torn-write",
}

# Labels whose crash semantics are proven by their OWN harnesses (each
# reason names the covering suite) — a plain 4-validator consensus run
# never crosses them, so a simnet sweep here would assert nothing.
SKIP_LABELS = {
    "farm:flush": "farm crash tests (tests/test_farm.py) + light-farm",
    "farm:commit-session": "farm crash tests + light-farm scenario",
    "ingest:flush": "admission crash tests + flash-crowd scenario",
    "trace:dump": "trace tests (dumping is never load-bearing)",
    "pipeline:dispatch": "pipelined blocksync crash tests + "
                         "blocksync-wedge scenario",
}

_failures = 0


def fail(msg: str) -> None:
    global _failures
    _failures += 1
    print(f"CRASH-MATRIX FAIL {msg}")


class MatrixCrash(Exception):
    """Raised by the fail hook at the label under test — the in-process
    stand-in for the env modes' os._exit(99)."""


def hook_for(label: str):
    def hook(lbl: str) -> None:
        if lbl == label:
            raise MatrixCrash(label)
    return hook


def db_hash(db) -> str:
    h = hashlib.sha256()
    for k, v in db.iterate():
        h.update(len(k).to_bytes(4, "big") + k)
        h.update(len(v).to_bytes(4, "big") + v)
    return h.hexdigest()


def make_ops(n_ops: int):
    """Deterministic batch workload shaped like store traffic: multi-
    record set batches with occasional deletes of live keys."""
    rng = random.Random(f"crash-matrix:{n_ops}")
    ops, live = [], []
    for _ in range(n_ops):
        sets, deletes = [], []
        for _ in range(rng.randrange(1, 5)):
            k = f"key/{rng.randrange(48)}".encode()
            v = bytes(rng.randrange(256)
                      for _ in range(rng.randrange(1, 72)))
            sets.append((k, v))
            live.append(k)
        if live and rng.random() < 0.35:
            deletes.append(rng.choice(live))
        ops.append((sets, deletes))
    return ops


def reference_run(path: str, ops):
    """Uninterrupted run: state hash + file size after every batch.
    prefix_hashes[i] == hash after the first i batches."""
    db = FileDB(path)
    hashes = [db_hash(db)]
    sizes = [os.path.getsize(path)]
    for sets, deletes in ops:
        db.write_batch(sets, deletes)
        hashes.append(db_hash(db))
        sizes.append(os.path.getsize(path))
    db.close()
    return hashes, sizes


def torn_cell(workdir: str, ops, hashes, i: int, seed: int,
              keep) -> None:
    """One matrix cell: tear batch i at `keep` bytes (None = seeded
    offset), crash, reopen, assert exact pre-batch state, resume,
    assert reference final state."""
    tag = f"torn op={i} seed={seed} keep={keep}"
    path = os.path.join(workdir, f"torn-{i}-{seed}-{keep}.db")
    plan = faultio.FaultPlan(seed=seed)
    plan.torn_write("db:log", nth=i + 1, keep=keep,
                    path_substr=os.path.basename(path))
    faultio.install(plan)
    crossed = []
    libfail.set_fail_hook(crossed.append)
    db = None
    try:
        db = FileDB(path)
        for j, (sets, deletes) in enumerate(ops):
            try:
                db.write_batch(sets, deletes)
            except faultio.InjectedCrash:
                if j != i:
                    fail(f"{tag}: tore batch {j}, expected {i}")
                break
        else:
            fail(f"{tag}: fault never fired")
            return
    finally:
        faultio.reset()
        libfail.clear_fail_hook()
        if db is not None:
            try:
                db.close()
            except Exception:  # noqa: BLE001 — handle state is torn
                pass
    if faultio.TORN_WRITE_LABEL not in crossed:
        fail(f"{tag}: {faultio.TORN_WRITE_LABEL} fail point not crossed")
    db2 = FileDB(path)
    got = db_hash(db2)
    if got != hashes[i]:
        which = ("prefix-applied batch" if got != hashes[i + 1]
                 else "torn batch survived whole")
        fail(f"{tag}: recovered state != pre-batch state ({which})")
        db2.close()
        return
    for sets, deletes in ops[i:]:
        db2.write_batch(sets, deletes)
    if db_hash(db2) != hashes[-1]:
        fail(f"{tag}: resumed run diverged from reference final state")
    db2.close()


def phase_storage_torn(workdir: str, quick: bool) -> int:
    n_ops = 6 if quick else 10
    ops = make_ops(n_ops)
    ref = os.path.join(workdir, "reference.db")
    hashes, sizes = reference_run(ref, ops)
    cells = 0
    for i in range(n_ops):
        op_len = sizes[i + 1] - sizes[i]
        if quick:
            rng = random.Random(f"crash-matrix:offsets:{i}")
            offsets = sorted({0, 1, op_len // 2, op_len - 1,
                              rng.randrange(op_len),
                              rng.randrange(op_len)})
        else:
            offsets = range(op_len)
        for keep in offsets:
            torn_cell(workdir, ops, hashes, i, seed=0, keep=keep)
            cells += 1
        # seeded-offset derivation path (keep=None): the tear offset is
        # a pure function of (seed, label, nth)
        for seed in range(2 if quick else 5):
            torn_cell(workdir, ops, hashes, i, seed=seed, keep=None)
            cells += 1
    return cells


def phase_storage_failpoints(workdir: str) -> int:
    ops = make_ops(8)
    cells = 0

    # --- compact swap: both halves ---------------------------------------
    for label in ("db:pre-compact-replace", "db:post-compact-replace"):
        path = os.path.join(workdir, f"compact-{label.split(':')[1]}.db")
        db = FileDB(path)
        for sets, deletes in ops:
            db.write_batch(sets, deletes)
        href = db_hash(db)
        libfail.set_fail_hook(hook_for(label))
        try:
            db.compact()
            fail(f"{label}: compact() never crossed the fail point")
        except MatrixCrash:
            pass
        finally:
            libfail.clear_fail_hook()
        pre = label == "db:pre-compact-replace"
        if os.path.exists(path + ".compact") != pre:
            fail(f"{label}: stale temp {'missing' if pre else 'present'} "
                 f"after crash")
        db2 = FileDB(path)
        if os.path.exists(path + ".compact"):
            fail(f"{label}: stale temp survived reopen")
        if db_hash(db2) != href:
            fail(f"{label}: reopened state != pre-compact state")
        db2.close()
        cells += 1

    # --- WAL rotation: both halves ---------------------------------------
    for label in ("wal:pre-rotate-rename", "wal:post-rotate-rename"):
        path = os.path.join(workdir, f"wal-{label.split(':')[1]}")
        wal = WAL(path, head_size_limit=256)
        libfail.set_fail_hook(hook_for(label))
        crashed_at = None
        try:
            for h in range(1, 200):
                wal.write_sync(EndHeightMessage(h))
        except MatrixCrash:
            crashed_at = h
        finally:
            libfail.clear_fail_hook()
        if crashed_at is None:
            fail(f"{label}: rotation never crossed the fail point")
            continue
        # everything synced BEFORE the crashed write must survive;
        # the in-flight message was never appended (rotation precedes
        # the append), so the group replays exactly 1..crashed_at-1
        wal2 = WAL(path, head_size_limit=256)
        heights = [m.height for m in wal2.iter_messages()]
        if heights != list(range(1, crashed_at)):
            fail(f"{label}: replay after crash lost committed records "
                 f"(got {len(heights)} of {crashed_at - 1})")
        for h in range(crashed_at, crashed_at + 6):
            wal2.write_sync(EndHeightMessage(h))
        wal2.close()
        wal3 = WAL(path, head_size_limit=256)
        heights = [m.height for m in wal3.iter_messages()]
        if heights != list(range(1, crashed_at + 6)):
            fail(f"{label}: resumed WAL is not contiguous")
        wal3.close()
        cells += 1
    return cells


def registry_labels() -> list:
    """Parse the fail-point registry table out of docs/SIMNET.md."""
    doc = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "SIMNET.md")
    with open(doc, encoding="utf-8") as f:
        text = f.read()
    section = text.split("### Fail-point registry", 1)[1]
    section = section.split("##", 1)[0]
    return re.findall(r"^\| `([^`]+)` \|", section, flags=re.M)


def phase_simnet(quick: bool) -> int:
    from cometbft_tpu.simnet.harness import Scenario, Simulation
    cells = 0
    for label in registry_labels():
        if label in STORAGE_LABELS:
            continue
        if label in SKIP_LABELS:
            print(f"  skip {label}: covered by {SKIP_LABELS[label]}")
            continue
        # k=1 for labels crossed every height (crash mid-chain, not at
        # height 1); k=0 for proposer-turn labels node 2 reaches once
        k = 1 if label.startswith(("finalize", "apply_block")) else 0

        def setup(sim, label=label, k=k):
            sim.crash_at_label(2, label, k=k, restart_after_ms=1800)
        sc = Scenario("crash-matrix", f"crash node 2 at {label}",
                      target_height=4, deadline_ms=120_000, setup=setup)
        for seed in range(1 if quick else 3):
            res = Simulation(sc, seed, quick=quick).run()
            tag = f"simnet {label} seed={seed}"
            if res.crashes < 1 or res.restarts < 1:
                fail(f"{tag}: label never crossed (crashes="
                     f"{res.crashes}) — cover it or add to SKIP_LABELS")
            elif not res.ok:
                fail(f"{tag}: {res.violations[0]}")
            elif res.errors:
                fail(f"{tag}: node error {res.errors[0]}")
            else:
                print(f"  ok {label} seed={seed} h={res.max_height} "
                      f"crashes={res.crashes} restarts={res.restarts}")
            cells += 1
    return cells


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="boundary+seeded offsets, 1 seed per label")
    args = ap.parse_args()
    workdir = tempfile.mkdtemp(prefix="crash-matrix-")
    try:
        print("phase 1a: torn-write offset sweep")
        torn = phase_storage_torn(workdir, args.quick)
        print(f"  {torn} cells")
        print("phase 1b: storage fail points (compact swap, WAL rotate)")
        fps = phase_storage_failpoints(workdir)
        print(f"  {fps} cells")
        print("phase 2: consensus-path fail points (simnet)")
        sims = phase_simnet(args.quick)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    if _failures:
        print(f"CRASH-MATRIX FAIL total={_failures}")
        return 1
    print(f"CRASH-MATRIX OK torn={torn} storage_failpoints={fps} "
          f"simnet={sims}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
