"""End-to-end blocksync benchmark at the QA valset scale
(BASELINE.json "blocksync catch-up" config; reference
internal/blocksync/reactor.go:540-544 logs blocks/s the same way).

Generates an N-block chain with a V-validator set (default 175 — the
QA-testnet valset, CometBFT-QA-v1.md), then times a fresh node
blocksyncing it through the real executor + TiledCommitVerifier,
reporting blocks/s and verified sigs/s. On a TPU backend the tile
flushes through the RLC device kernel; on CPU it takes the native
per-sig path (batch_size=0) unless --batch is forced.

Usage:
    python tools/bench_blocksync.py [--blocks 64] [--validators 175]
        [--tile 32] [--batch auto|0|N] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cometbft_tpu.libs.jax_cache import enable_compile_cache  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--blocks", type=int, default=64)
    ap.add_argument("--validators", type=int, default=175)
    ap.add_argument("--tile", type=int, default=32)
    ap.add_argument("--batch", default="auto",
                    help="auto: device tile on TPU, native on CPU; "
                         "0: native; N: force device batch N")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    enable_compile_cache()
    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.db.kv import MemDB
    from cometbft_tpu.engine.blocksync import BlocksyncReactor
    from cometbft_tpu.engine.chain_gen import (
        LocalChainSource, generate_chain)
    from cometbft_tpu.libs.jax_cache import is_device_platform
    from cometbft_tpu.state.execution import BlockExecutor
    from cometbft_tpu.state.state import State, StateStore
    from cometbft_tpu.store.blockstore import BlockStore

    if args.batch == "auto":
        # the device path blocks FOREVER on a wedged TPU tunnel, so the
        # choice is made by PROBING the backend in a throwaway
        # subprocess, pinning the cpu platform (and dropping the
        # device-assumption compile cache) when unavailable — the
        # shared bench-tool discipline (bench.resolve_backend_or_pin_cpu)
        from bench import resolve_backend_or_pin_cpu
        batch = 8192 if resolve_backend_or_pin_cpu() == "device" else 0
    else:
        batch = int(args.batch)
        if batch == 0 and is_device_platform():
            from bench import resolve_backend_or_pin_cpu
            resolve_backend_or_pin_cpu()

    t0 = time.monotonic()
    print(f"[bench_blocksync] generating {args.blocks} blocks x "
          f"{args.validators} validators...", file=sys.stderr, flush=True)
    chain = generate_chain(n_blocks=args.blocks,
                           n_validators=args.validators)
    gen_s = time.monotonic() - t0
    print(f"[bench_blocksync] chain in {gen_s:.1f}s; syncing "
          f"(batch={batch})...", file=sys.stderr, flush=True)

    app = KVStoreApplication()
    app.init_chain(chain.chain_id, 1, [], b"")
    db = MemDB()
    executor = BlockExecutor(app, state_store=StateStore(db),
                             block_store=BlockStore(db))
    state = State.from_genesis(chain.genesis)
    reactor = BlocksyncReactor(
        executor, BlockStore(db), LocalChainSource(chain),
        chain.chain_id, tile_size=args.tile, batch_size=batch)

    t1 = time.monotonic()
    state = reactor.sync(state)
    dt = time.monotonic() - t1
    assert state.last_block_height == args.blocks

    sigs = reactor.stats.sigs_verified
    rec = {
        "metric": "blocksync_throughput",
        "blocks_per_sec": round(args.blocks / dt, 2),
        "sigs_per_sec": round(sigs / dt, 1),
        "unit": "blocks/s",
        "blocks": args.blocks,
        "validators": args.validators,
        "tile": args.tile,
        "batch": batch,
        "sync_seconds": round(dt, 2),
    }
    if args.json:
        print(json.dumps(rec))
    else:
        print(f"blocksync: {rec['blocks_per_sec']} blocks/s, "
              f"{rec['sigs_per_sec']:,.0f} sigs/s "
              f"({args.blocks} blocks x {args.validators} validators, "
              f"tile {args.tile}, batch {batch}, {dt:.2f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
