"""A/B on the real chip: XLA point ops vs the pallas kernels, then the
full RLC verify both ways at batch 8192.

AB_SWEEP="256,512,1024" re-execs this script once per TILE value (the
pallas lane-tile is latched at module import, so each point needs a
fresh interpreter) timing ONLY the full pallas RLC — the TILE tuning
pass of VERDICT r5 item 3. AB_ONLY=pallas skips the per-stage A/B."""
import os, subprocess, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("AB_SWEEP"):
    tiles = [int(t) for t in os.environ["AB_SWEEP"].split(",")]
    print(f"TILE sweep: {tiles}", flush=True)
    for tile in tiles:
        env = dict(os.environ, COMETBFT_TPU_PALLAS_TILE=str(tile),
                   AB_ONLY="pallas")
        env.pop("AB_SWEEP")
        print(f"--- TILE={tile} ---", flush=True)
        try:
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, timeout=2400)
            rc = r.returncode
        except subprocess.TimeoutExpired:
            # one hung tile (wedged tunnel mid-run) must not abort the
            # remaining sweep points
            rc = "timeout"
        print(f"--- TILE={tile} rc={rc} ---", flush=True)
    sys.exit(0)
from cometbft_tpu.libs.jax_cache import enable_compile_cache
enable_compile_cache()
import numpy as np
import jax
import jax.numpy as jnp

N = int(os.environ.get("AB_N", "8192"))
print(f"device={jax.devices()[0].platform} N={N}", flush=True)

from cometbft_tpu.ops import edwards as ed
from cometbft_tpu.ops import pallas_verify as pv

rng = np.random.default_rng(0)
limbs = lambda *s: jnp.asarray(
    rng.integers(0, 1 << 16, size=(16, *s), dtype=np.int32))

def t(name, fn, *args, reps=5):
    t0 = time.perf_counter()
    out = fn(*args); jax.block_until_ready(out)
    print(f"{name:34s} compile+1st {time.perf_counter()-t0:7.1f}s",
          flush=True)
    best = 1e9
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args); jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{name:34s} {best*1e3:9.3f} ms", flush=True)
    return out

_only_pallas = os.environ.get("AB_ONLY") == "pallas"
print(f"pallas TILE={pv.TILE}", flush=True)

if not _only_pallas:
    pt = (limbs(N), limbs(N), limbs(N), limbs(N))
    packed = jnp.stack(pt)

    # 1) pt_add: XLA vs pallas
    t("pt_add XLA", jax.jit(ed.pt_add), pt, pt)
    t("pt_add PALLAS tiled", lambda p, q: pv.pt_add_tiled(p, q),
      packed, packed)

    # 2) window stage: XLA table+lookup+tree vs pallas fused
    tdig = jnp.asarray(rng.integers(0, 16, size=(64, N), dtype=np.int32))
    zdig = jnp.asarray(rng.integers(0, 16, size=(32, N), dtype=np.int32))

    @jax.jit
    def xla_stage(a, r, td, zd):
        wa = ed.pt_tree_sum(ed.lookup_windows(ed.window_table(a), td))
        wr = ed.pt_tree_sum(ed.lookup_windows(ed.window_table(r), zd))
        return wa[0] + wr[0]
    t("window stage XLA", xla_stage, pt, pt, tdig, zdig)

    def pallas_stage(a, r, td, zd):
        out = pv.rlc_window_sums(a, r, td, zd)
        folded = jnp.transpose(out, (2, 3, 1, 0, 4)).reshape(
            4, 16, 96, out.shape[0] * pv.TAIL)
        return ed.pt_tree_sum(tuple(folded[i] for i in range(4)))[0]
    t("window stage PALLAS", jax.jit(pallas_stage), packed, packed,
      tdig, zdig)

# 3) full RLC verify both ways on real signatures
from cometbft_tpu.ops.ed25519 import (
    make_rlc_coefficients, prepare_batch,
    verify_rlc_kernel, verify_rlc_kernel_pallas)
from cometbft_tpu.crypto import ref_ed25519 as ref

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey)
    from cryptography.hazmat.primitives import serialization
    keys = [Ed25519PrivateKey.generate() for _ in range(200)]
    raw = lambda k: k.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)
    msgs = [rng.integers(0, 256, 122, dtype=np.uint8).tobytes()
            for _ in range(N)]
    pubs = [raw(keys[i % 200]) for i in range(N)]
    sigs = [keys[i % 200].sign(m) for i, m in enumerate(msgs)]
except ImportError:
    seeds = [bytes([int(b) for b in rng.integers(0, 256, 32)])
             for _ in range(8)]
    msgs = [b"m" * 100] * N
    pubs = [ref.pubkey_from_seed(seeds[i % 8]) for i in range(N)]
    sigs = [ref.sign(seeds[i % 8], msgs[i]) for i in range(N)]

pub, sig, hb, hn, ok = prepare_batch(pubs, msgs, sigs, N, 128)
assert ok.all()
z = make_rlc_coefficients(N)
dev = jax.devices()[0]
pub, sig, hb, hn = (jax.device_put(x, dev) for x in (pub, sig, hb, hn))

def full(kern, name):
    bok, sok = t(f"RLC full {name}", lambda: kern(pub, sig, hb, hn, z))
    assert bool(bok) and np.asarray(sok).all(), name

full(verify_rlc_kernel_pallas, "PALLAS")
if not _only_pallas:
    full(verify_rlc_kernel, "XLA")
variants = [("PALLAS", verify_rlc_kernel_pallas)]
if not _only_pallas:
    variants.append(("XLA", verify_rlc_kernel))
for name, kern in variants:
    t0 = time.perf_counter()
    iters = 4
    for _ in range(iters):
        z2 = make_rlc_coefficients(N)
        bok, out = kern(pub, sig, hb, hn, z2)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    print(f"THROUGHPUT {name}: {N*iters/dt:,.0f} sigs/s "
          f"({dt/iters*1e3:.1f} ms/iter)", flush=True)
