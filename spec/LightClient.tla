---------------------------- MODULE LightClient ----------------------------
(***************************************************************************)
(* Light-client verification safety, written against                      *)
(* cometbft_tpu/light/verifier.py (reference artifact:                    *)
(* spec/light-client/verification/ in CometBFT).                          *)
(*                                                                        *)
(* The light client holds a TRUSTED header and accepts an untrusted       *)
(* header by one of two rules (verifier.py:67-130):                       *)
(*   adjacent:      the untrusted valset IS the trusted header's          *)
(*                  next-valset (hash-bound) and > 2/3 of it signed;      *)
(*   non-adjacent:  signers hold > 1/3 of the TRUSTED valset's power     *)
(*                  (verify_commit_light_trusting, validation.py:179)     *)
(*                  AND > 2/3 of the header's OWN claimed valset signed.  *)
(*                                                                        *)
(* Adversary: a fixed faulty set F signs anything; honest validators      *)
(* sign only the canonical header of each height.  Fault assumption:     *)
(* F holds strictly less than 1/3 of every canonical valset inside the   *)
(* trusting period (the premise of the skipping rule).                   *)
(*                                                                        *)
(* Safety: every header the client accepts is canonical.                 *)
(*                                                                        *)
(* Machine-checked by tools/check_light_spec.py — an explicit-state      *)
(* enumeration of EXACTLY this transition system (no TLC in the build    *)
(* image): all canonical chains over the valset family x all faulty      *)
(* sets satisfying the assumption x all reachable trusted states x all   *)
(* forged (claimed-valset, signer-subset) headers.  With                 *)
(* --n 5 --heights 4 --min-valset 2: 340,650 configs, no forgery         *)
(* accepted; --self-test drops the fault assumption and exhibits the     *)
(* classic claimed-valset forgery.                                       *)
(***************************************************************************)

EXTENDS Integers, FiniteSets

CONSTANTS
    Validators,     \* universe of validator identities (equal power)
    Faulty,         \* the adversary's validators
    Heights,        \* 1..H canonical chain heights
    Chain           \* [Heights -> SUBSET Validators]: canonical valsets

ASSUME Faulty \subseteq Validators
\* fault assumption: < 1/3 of every canonical valset is faulty
ASSUME \A h \in Heights :
    3 * Cardinality(Faulty \cap Chain[h]) < Cardinality(Chain[h])

(***************************************************************************)
(* The implementation's two threshold predicates (floor division          *)
(* matches validation.py:192 `needed = total * num // den` with the       *)
(* strict `tallied > needed` core).                                       *)
(***************************************************************************)
TrustingOK(S, T) ==
    3 * Cardinality(S \cap T) > Cardinality(T)

OwnCommitOK(S, W) ==
    /\ S \subseteq W
    /\ 3 * Cardinality(S) > 2 * Cardinality(W)

(***************************************************************************)
(* Headers presentable at height h: the canonical one (anyone in          *)
(* Chain[h] may appear as a signer) or a forgery (only Faulty sign).      *)
(* A forged ADJACENT header is hash-bound to the real next valset; a     *)
(* forged SKIPPING header claims any valset W.                           *)
(***************************************************************************)

VARIABLES trustedHeight, accepted   \* accepted: set of (height, canon?)

Init ==
    /\ trustedHeight = 1
    /\ accepted = {<<1, TRUE>>}

AcceptCanonical(h) ==
    /\ h \in Heights /\ h > trustedHeight
    /\ LET S == Chain[h] IN
       IF h = trustedHeight + 1
       THEN OwnCommitOK(S, Chain[h])
       ELSE /\ TrustingOK(S, Chain[trustedHeight])
            /\ OwnCommitOK(S, Chain[h])
    /\ trustedHeight' = h
    /\ accepted' = accepted \union {<<h, TRUE>>}

AcceptForgedAdjacent(h, S) ==
    /\ h = trustedHeight + 1 /\ h \in Heights
    /\ S \subseteq Faulty
    /\ OwnCommitOK(S, Chain[h])      \* hash-bound claimed set
    /\ accepted' = accepted \union {<<h, FALSE>>}
    /\ UNCHANGED trustedHeight

AcceptForgedSkipping(h, W, S) ==
    /\ h \in Heights /\ h > trustedHeight + 1
    /\ S \subseteq Faulty /\ W \subseteq Validators
    /\ TrustingOK(S, Chain[trustedHeight])
    /\ OwnCommitOK(S, W)
    /\ accepted' = accepted \union {<<h, FALSE>>}
    /\ UNCHANGED trustedHeight

Next ==
    \/ \E h \in Heights : AcceptCanonical(h)
    \/ \E h \in Heights, S \in SUBSET Faulty :
          AcceptForgedAdjacent(h, S)
    \/ \E h \in Heights, W \in SUBSET Validators,
         S \in SUBSET Faulty : AcceptForgedSkipping(h, W, S)

Spec == Init /\ [][Next]_<<trustedHeight, accepted>>

\* Safety: nothing non-canonical is ever accepted
Invariant == \A a \in accepted : a[2] = TRUE

=============================================================================
