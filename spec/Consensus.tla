------------------------------ MODULE Consensus ------------------------------
(***************************************************************************)
(* Formal specification of the consensus state machine implemented in     *)
(* cometbft_tpu/consensus/state.py — the Tendermint-family algorithm the  *)
(* reference documents in spec/consensus/ (consensus paper) and proves in *)
(* spec/ivy-proofs/.  This spec is written against THIS implementation:   *)
(* the state names below are the STEP_* constants, the actions are the    *)
(* _enter_* handlers, and the locking/validity rules are the POL rules    *)
(* the code enforces (state.py _enter_precommit / _do_prevote).           *)
(*                                                                        *)
(* Scope: single-height agreement over rounds, asynchronous network with  *)
(* message loss (the reactor's reconciliation makes loss benign), up to   *)
(* f Byzantine validators out of n = 3f+1.  Timeouts are modeled as       *)
(* nondeterministic scheduling (the Timeout* actions are always enabled   *)
(* once their step is reached) — the implementation's ticker only decides *)
(* WHEN, never WHETHER.                                                   *)
(*                                                                        *)
(* Properties at the bottom:                                              *)
(*   Agreement      — no two correct validators decide differently.      *)
(*   ValidityLock   — a correct validator only precommits a value it     *)
(*                    prevoted, and only re-locks with a newer POL.      *)
(*   DecisionPower  — every decision carries > 2/3 precommit power.      *)
(* Check with TLC on small instances (n=4, f=1, MaxRound=3).              *)
(***************************************************************************)

EXTENDS Integers, FiniteSets, TLC

CONSTANTS
    Validators,     \* the validator set (model power-1 each; the
                    \* implementation's weighted tally reduces to this
                    \* under equal powers — types/vote_set.py)
    Byzantine,      \* subset of Validators that may equivocate
    Values,         \* proposable block values
    MaxRound        \* bound for model checking

ASSUME Byzantine \subseteq Validators
ASSUME 3 * Cardinality(Byzantine) < Cardinality(Validators)

Correct == Validators \ Byzantine
Rounds  == 0..MaxRound
Nil     == CHOOSE v : v \notin Values

\* steps mirror consensus/state.py STEP_* constants
Steps == {"NewHeight", "Propose", "Prevote", "PrevoteWait",
          "Precommit", "PrecommitWait", "Commit"}

\* deterministic ROUND-ROBIN proposer rotation (types/validator.py
\* proposer priority reduces to round-robin under equal powers): a
\* fixed enumeration of the validator set, advanced one slot per round
N == Cardinality(Validators)
Order == CHOOSE seq \in [0..(N-1) -> Validators] :
             \A i, j \in 0..(N-1) : i # j => seq[i] # seq[j]
Proposer(r) == Order[r % N]

QuorumSize == (2 * Cardinality(Validators)) \div 3 + 1
Quorums == {Q \in SUBSET Validators : Cardinality(Q) >= QuorumSize}

VARIABLES
    step,        \* validator -> current step
    round,       \* validator -> current round
    lockedValue, \* validator -> Values ∪ {Nil}   (rs.locked_block)
    lockedRound, \* validator -> Rounds ∪ {-1}    (rs.locked_round)
    validValue,  \* validator -> Values ∪ {Nil}   (rs.valid_block)
    validRound,  \* validator -> Rounds ∪ {-1}    (rs.valid_round)
    decision,    \* validator -> Values ∪ {Nil}
    proposals,   \* round -> Values ∪ {Nil}: the proposer's broadcast
    prevotes,    \* [round, validator] -> Values ∪ {Nil} ∪ {"none"}
    precommits   \* [round, validator] -> Values ∪ {Nil} ∪ {"none"}

vars == <<step, round, lockedValue, lockedRound, validValue, validRound,
          decision, proposals, prevotes, precommits>>

Init ==
    /\ step        = [v \in Correct |-> "NewHeight"]
    /\ round       = [v \in Correct |-> 0]
    /\ lockedValue = [v \in Correct |-> Nil]
    /\ lockedRound = [v \in Correct |-> -1]
    /\ validValue  = [v \in Correct |-> Nil]
    /\ validRound  = [v \in Correct |-> -1]
    /\ decision    = [v \in Correct |-> Nil]
    /\ proposals   = [r \in Rounds |-> Nil]
    /\ prevotes    = [r \in Rounds |-> [v \in Correct |-> "none"]]
    /\ precommits  = [r \in Rounds |-> [v \in Correct |-> "none"]]

\* ---- vote bookkeeping (types/vote_set.py 2/3 accounting) -----------------
\*
\* WILDCARD BYZANTINE MODEL: faulty validators count toward EVERY
\* quorum for EVERY value simultaneously — the standard
\* over-approximation of equivocation (each Byzantine validator may
\* send any vote to any peer, so any quorum the adversary wants to
\* complete, it completes).  Strictly more adversarial than explicit
\* one-vote-per-round Byzantine actions, and faulty votes carry no
\* state.  Vote arrays are therefore indexed by CORRECT validators.

PrevotePower(r, x)   == {v \in Correct : prevotes[r][v] = x} \union Byzantine
PrecommitPower(r, x) == {v \in Correct : precommits[r][v] = x} \union Byzantine

HasPolka(r, x)  == \E Q \in Quorums : Q \subseteq PrevotePower(r, x)
HasCommit(r, x) == \E Q \in Quorums : Q \subseteq PrecommitPower(r, x)

\* any-2/3 prevotes arrived (prevote-wait trigger, state.go analog
\* _enter_prevote_wait)
AnyPolka(r) ==
    \E Q \in Quorums :
        \A v \in Q : v \in Byzantine \/ prevotes[r][v] # "none"

\* ---- actions: the _enter_* handlers --------------------------------------

\* _enter_new_round + _enter_propose: the proposer broadcasts either its
\* valid value (re-proposal with POL) or a fresh value
StartRound(v, r) ==
    /\ round[v] = r /\ step[v] \in {"NewHeight", "PrecommitWait"}
    /\ step' = [step EXCEPT ![v] = "Propose"]
    /\ IF v = Proposer(r) /\ proposals[r] = Nil
       THEN \E x \in Values :
              proposals' = [proposals EXCEPT ![r] =
                  IF validValue[v] # Nil THEN validValue[v] ELSE x]
       ELSE UNCHANGED proposals
    /\ UNCHANGED <<round, lockedValue, lockedRound, validValue,
                   validRound, decision, prevotes, precommits>>

\* _do_prevote: prevote the locked value if locked; else the proposal if
\* acceptable (PBTS/validation gates abstract to nondeterministic
\* acceptance); else nil.  (Byzantine prevotes need no action — the
\* wildcard quorum model counts them toward every value already.)
DoPrevote(v, r, x) ==
    /\ round[v] = r /\ step[v] = "Propose"
    /\ prevotes[r][v] = "none"
    /\ \/ /\ lockedValue[v] # Nil /\ x = lockedValue[v]
       \/ /\ lockedValue[v] = Nil
          /\ \/ x = proposals[r] /\ x # Nil
             \/ x = Nil          \* invalid/missing/untimely proposal
    /\ prevotes'  = [prevotes EXCEPT ![r][v] = x]
    /\ step'      = [step EXCEPT ![v] = "Prevote"]
    /\ UNCHANGED <<round, lockedValue, lockedRound, validValue,
                   validRound, decision, proposals, precommits>>

\* _enter_precommit on a polka for value x: lock and precommit
PrecommitValue(v, r, x) ==
    /\ round[v] = r /\ step[v] = "Prevote"
    /\ precommits[r][v] = "none"
    /\ x \in Values
    /\ HasPolka(r, x)
    /\ prevotes[r][v] = x  \* code path: own prevote in the polka set
    /\ lockedValue' = [lockedValue EXCEPT ![v] = x]
    /\ lockedRound' = [lockedRound EXCEPT ![v] = r]
    /\ validValue'  = [validValue EXCEPT ![v] = x]
    /\ validRound'  = [validRound EXCEPT ![v] = r]
    /\ precommits'  = [precommits EXCEPT ![r][v] = x]
    /\ step'        = [step EXCEPT ![v] = "Precommit"]
    /\ UNCHANGED <<round, decision, proposals, prevotes>>

\* _enter_precommit on a nil-polka: unlock, precommit nil
PrecommitNil(v, r) ==
    /\ round[v] = r /\ step[v] = "Prevote"
    /\ precommits[r][v] = "none"
    /\ HasPolka(r, Nil) \/ (AnyPolka(r) /\ ~\E x \in Values : HasPolka(r, x))
    /\ IF HasPolka(r, Nil)
       THEN /\ lockedValue' = [lockedValue EXCEPT ![v] = Nil]
            /\ lockedRound' = [lockedRound EXCEPT ![v] = -1]
       ELSE UNCHANGED <<lockedValue, lockedRound>>
    /\ precommits' = [precommits EXCEPT ![r][v] = Nil]
    /\ step'       = [step EXCEPT ![v] = "Precommit"]
    /\ UNCHANGED <<round, validValue, validRound, decision, proposals,
                   prevotes>>

\* a Byzantine proposer may broadcast any value (the wildcard vote
\* model covers Byzantine VOTES; the proposal channel still needs an
\* explicit adversarial action)
ByzantinePropose(r, x) ==
    /\ Proposer(r) \in Byzantine
    /\ proposals[r] = Nil
    /\ proposals' = [proposals EXCEPT ![r] = x]
    /\ UNCHANGED <<step, round, lockedValue, lockedRound, validValue,
                   validRound, decision, prevotes, precommits>>

\* finalize_commit: 2/3 precommits for x decide it (any validator that
\* observes the quorum, at any of its rounds — late deliveries included)
Decide(v, r, x) ==
    /\ decision[v] = Nil
    /\ x \in Values
    /\ HasCommit(r, x)
    /\ decision' = [decision EXCEPT ![v] = x]
    /\ step'     = [step EXCEPT ![v] = "Commit"]
    /\ UNCHANGED <<round, lockedValue, lockedRound, validValue,
                   validRound, proposals, prevotes, precommits>>

\* round advance (timeout precommit-wait / skip on 2/3 any): the ticker
\* abstracts to "may advance once precommit reached"
NextRound(v, r) ==
    /\ round[v] = r /\ r < MaxRound
    /\ step[v] \in {"Precommit", "PrecommitWait"}
    /\ decision[v] = Nil
    /\ round' = [round EXCEPT ![v] = r + 1]
    /\ step'  = [step EXCEPT ![v] = "NewHeight"]
    /\ UNCHANGED <<lockedValue, lockedRound, validValue, validRound,
                   decision, proposals, prevotes, precommits>>

Next ==
    \/ \E v \in Correct, r \in Rounds : StartRound(v, r)
    \/ \E v \in Correct, r \in Rounds, x \in Values \union {Nil} :
          DoPrevote(v, r, x)
    \/ \E v \in Correct, r \in Rounds, x \in Values :
          PrecommitValue(v, r, x)
    \/ \E v \in Correct, r \in Rounds : PrecommitNil(v, r)
    \/ \E r \in Rounds, x \in Values : ByzantinePropose(r, x)
    \/ \E v \in Correct, r \in Rounds, x \in Values : Decide(v, r, x)
    \/ \E v \in Correct, r \in Rounds : NextRound(v, r)

Spec == Init /\ [][Next]_vars

\* ---- properties -----------------------------------------------------------

\* Agreement: no two correct validators decide different values.  The
\* implementation counterpart: finalize_commit only fires on a 2/3
\* precommit quorum (vote_set.py two_thirds_majority), and quorum
\* intersection leaves a correct validator locked on the decided value.
Agreement ==
    \A u, v \in Correct :
        decision[u] # Nil /\ decision[v] # Nil => decision[u] = decision[v]

\* A correct validator's precommit for a value is backed by a polka in
\* the same round (state.py _enter_precommit requires
\* prevotes.two_thirds_majority()).
ValidityLock ==
    \A v \in Correct, r \in Rounds :
        precommits[r][v] \in Values => HasPolka(r, precommits[r][v])

\* Every decision is carried by >2/3 precommit power in some round.
DecisionPower ==
    \A v \in Correct :
        decision[v] # Nil =>
            \E r \in Rounds : HasCommit(r, decision[v])

\* TLC config suggestion:
\*   Validators = {v1, v2, v3, v4};  Byzantine = {v4}
\*   Values = {a, b};  MaxRound = 2;  SYMMETRY on Values
\*   INVARIANTS Agreement ValidityLock DecisionPower
\* No Java/TLC in the build environment: tools/check_spec.py is an
\* explicit-state checker of EXACTLY this transition system (same
\* actions and guards, same wildcard-Byzantine quorums, same
\* round-robin Proposer) — exhaustive at n=4/f=1/|Values|=2 through
\* MaxRound=3, run by tests/test_spec_check.py.
===============================================================================
