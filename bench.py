"""Headline benchmark: batched ed25519 signature verification throughput.

Measures the north-star metric (BASELINE.json): verified sigs/sec on one
chip, cross-block tiling — a (commits x validators) tile of real
signatures, matching blocksync catch-up with a 200-validator set
(reference internal/blocksync/reactor.go:483, baseline ~78k sigs/s CPU
batch-1024, docs/references/rfc/tendermint-core/rfc-018:187-189).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Env knobs: BENCH_BATCH (default 4096), BENCH_ITERS (default 4).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from cometbft_tpu.libs.jax_cache import enable_compile_cache  # noqa: E402

BASELINE_SIGS_PER_SEC = 78_000.0  # CPU curve25519-voi, 1024-sig batches


def _gen_signatures(n, n_validators=200, msg_len=122, seed=7):
    """n signatures from a 200-key validator set over vote-sized messages.

    Uses the fast C signer when available (signature generation is host
    tooling, not the measured path), falling back to the big-int oracle.
    """
    import numpy as np
    rng = np.random.default_rng(seed)
    msgs = [rng.integers(0, 256, size=msg_len, dtype=np.uint8).tobytes()
            for _ in range(n)]
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey)
        from cryptography.hazmat.primitives import serialization
        keys = [Ed25519PrivateKey.generate() for _ in range(n_validators)]
        raw = lambda k: k.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        pubs_by_val = [raw(k) for k in keys]
        pubs, sigs = [], []
        for i, m in enumerate(msgs):
            v = i % n_validators
            pubs.append(pubs_by_val[v])
            sigs.append(keys[v].sign(m))
    except ImportError:  # pragma: no cover
        from cometbft_tpu.crypto import ref_ed25519 as ref
        seeds = [bytes([int(b) for b in rng.integers(0, 256, 32)])
                 for _ in range(n_validators)]
        pubs_by_val = [ref.pubkey_from_seed(s) for s in seeds]
        pubs, sigs = [], []
        for i, m in enumerate(msgs):
            v = i % n_validators
            pubs.append(pubs_by_val[v])
            sigs.append(ref.sign(seeds[v], m))
    return pubs, msgs, sigs


def main():
    import numpy as np
    import jax
    enable_compile_cache()
    from cometbft_tpu.ops.ed25519 import (
        verify_rlc_kernel, prepare_batch, make_rlc_coefficients)

    batch = int(os.environ.get("BENCH_BATCH", "8192"))
    iters = int(os.environ.get("BENCH_ITERS", "4"))

    pubs, msgs, sigs = _gen_signatures(batch)
    pub, sig, hb, hn, ok_mask = prepare_batch(pubs, msgs, sigs, batch, 128)
    assert ok_mask.all()
    dev = jax.devices()[0]
    pub, sig, hb, hn = (jax.device_put(x, dev) for x in (pub, sig, hb, hn))

    # the production fast path: one random-linear-combination equation per
    # tile (fresh coefficients every flush, as the verifier requires)
    z = make_rlc_coefficients(batch)
    bok, sok = verify_rlc_kernel(pub, sig, hb, hn, z)  # compile + warm
    assert bool(bok) and np.asarray(sok).all(), "warmup verification failed"

    t0 = time.perf_counter()
    for _ in range(iters):
        z = make_rlc_coefficients(batch)
        bok, out = verify_rlc_kernel(pub, sig, hb, hn, z)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    assert bool(bok)

    sigs_per_sec = batch * iters / dt
    print(json.dumps({
        "metric": "ed25519_batch_verify_throughput",
        "value": round(sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(sigs_per_sec / BASELINE_SIGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
