"""Headline benchmark: batched ed25519 signature verification throughput.

Measures the north-star metric (BASELINE.json): verified sigs/sec on one
chip, cross-block tiling — a (commits x validators) tile of real
signatures, matching blocksync catch-up with a 200-validator set
(reference internal/blocksync/reactor.go:483, baseline ~78k sigs/s CPU
batch-1024, docs/references/rfc/tendermint-core/rfc-018:187-189).

Prints ONE JSON line on stdout: {"metric", "value", "unit",
"vs_baseline"}; all diagnostics/progress go to stderr. The TPU tunnel in
this environment is single-client and can wedge indefinitely at backend
init, so backend liveness is probed in a THROWAWAY SUBPROCESS with a
hard timeout first (retrying once); a wedged tunnel fails fast with a
diagnostic instead of hanging for 10 silent minutes.

Env knobs: BENCH_BATCH (default 8192), BENCH_ITERS (default 4),
BENCH_PROBE_TIMEOUT (s, default 75), BENCH_ALLOW_CPU=1 (measure on the
CPU backend instead of failing when no TPU — for local dev only; the
JSON then carries "backend": "cpu").
"""

import json
import os
import subprocess
import sys
import time

# XLA's HLO passes recurse deeply on the RLC kernel graph: at the
# default 8MB thread stack the batch-4096 compile OVERFLOWS (observed:
# SIGSEGV at the stack guard, dmesg "error 6" inside libjax_common).
# pthread stacks size themselves from RLIMIT_STACK at thread creation,
# so raise it before anything builds a compiler thread pool.
try:
    import resource
    _soft, _hard = resource.getrlimit(resource.RLIMIT_STACK)
    _want = 512 * 1024 * 1024
    if _hard != resource.RLIM_INFINITY:
        _want = min(_want, _hard)
    if _soft != resource.RLIM_INFINITY and _soft < _want:
        resource.setrlimit(resource.RLIMIT_STACK, (_want, _hard))
except (ImportError, ValueError, OSError):  # pragma: no cover
    pass

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from cometbft_tpu.libs.jax_cache import enable_compile_cache  # noqa: E402

BASELINE_SIGS_PER_SEC = 78_000.0  # CPU curve25519-voi, 1024-sig batches

_PROBE_CODE = """
import sys, os
sys.path.insert(0, {root!r})
from cometbft_tpu.libs.jax_cache import enable_compile_cache
enable_compile_cache()
import jax
ds = jax.devices()
print("PROBE", ds[0].platform, len(ds), flush=True)
"""


def _log(msg):
    print(f"[bench +{time.monotonic() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.monotonic()


def _probe_once(timeout: float):
    """One subprocess backend-init liveness check. Returns the device
    platform string ("axon"/"tpu"/"cpu"/...), or None if init hung or
    failed. The subprocess exits before we return, so the single-client
    tunnel is free for the real run."""
    code = _PROBE_CODE.format(root=os.path.dirname(os.path.abspath(__file__)))
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired:
        _log(f"backend init HUNG >{timeout:.0f}s — the TPU tunnel is "
             f"wedged (single-client; nothing in-repo can reset it)")
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PROBE "):
            _, platform, n = line.split()
            _log(f"backend alive: platform={platform} devices={n}")
            return platform
    _log(f"backend init FAILED rc={r.returncode}: "
         f"{(r.stderr or r.stdout).strip().splitlines()[-1:] or ['?']}")
    return None


def probe_backend():
    """Liveness-check backend init, riding the PR-3 DeviceSupervisor
    probe/backoff discipline instead of the old bespoke 2x75s
    probe-and-die (ROADMAP item 5): each failed probe reports a trip,
    retries wait out the supervisor's jittered exponential half-open
    windows, and BENCH_PROBE_BUDGET bounds the whole dance. Returns the
    platform string or None when the budget ran out — the caller then
    ALWAYS measures something (attributed CPU fallback), never dies
    numberless.

    Env knobs: BENCH_PROBE_TIMEOUT (s per attempt, default 75),
    BENCH_PROBE_BUDGET (s total, default 170), BENCH_PROBE_BACKOFF
    (s base window, default 2)."""
    from cometbft_tpu.device.health import DeviceSupervisor
    timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT", "75"))
    budget = float(os.environ.get("BENCH_PROBE_BUDGET", "170"))
    base = float(os.environ.get("BENCH_PROBE_BACKOFF", "2"))
    sup = DeviceSupervisor(backoff_base_s=base, backoff_cap_s=30.0,
                           probe_deadline_s=timeout, canary=False,
                           clock=time.monotonic, log=_log)
    deadline = time.monotonic() + budget
    attempt = 0
    while time.monotonic() < deadline:
        if not sup.allow_connect():
            time.sleep(min(0.25, max(0.0, deadline - time.monotonic())))
            continue
        attempt += 1
        _log(f"probing jax backend (attempt {attempt}, state "
             f"{sup.state_name()}, timeout {timeout:.0f}s, budget "
             f"{deadline - time.monotonic():.0f}s left)...")
        remaining = deadline - time.monotonic()
        platform = _probe_once(min(timeout, max(1.0, remaining)))
        if platform is not None:
            sup.report_success()
            return platform
        sup.report_trip(TimeoutError("backend init hung or failed"))
    _log(f"backend unavailable after {attempt} supervised attempt(s) "
         f"({budget:.0f}s budget)")
    return None


def resolve_backend_or_pin_cpu() -> str:
    """Shared bench-tool discipline (bench_blocksync, bench_light):
    probe the backend in a throwaway subprocess; if the device is
    unavailable (wedged tunnel / cpu-only), pin the cpu platform so no
    code path blocks on the tunnel, AND drop the persistent compile
    cache that enable_compile_cache admitted under the device
    assumption (XLA:CPU AOT reloads risk SIGILL on machine-feature
    mismatch). Returns "device" or "cpu"."""
    from cometbft_tpu.libs.jax_cache import (disable_persistent_cache,
                                             is_device_platform)
    platform = probe_backend()
    if platform not in (None, "cpu"):
        return "device"
    if is_device_platform():
        import jax
        jax.config.update("jax_platforms", "cpu")
    disable_persistent_cache()
    return "cpu"


def _gen_signatures(n, n_validators=200, msg_len=122, seed=7):
    """n signatures from a 200-key validator set over vote-sized messages.

    Uses the fast C signer when available (signature generation is host
    tooling, not the measured path), falling back to the big-int oracle.
    """
    import numpy as np
    rng = np.random.default_rng(seed)
    msgs = [rng.integers(0, 256, size=msg_len, dtype=np.uint8).tobytes()
            for _ in range(n)]
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey)
        from cryptography.hazmat.primitives import serialization
        keys = [Ed25519PrivateKey.generate() for _ in range(n_validators)]
        raw = lambda k: k.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        pubs_by_val = [raw(k) for k in keys]
        pubs, sigs = [], []
        for i, m in enumerate(msgs):
            v = i % n_validators
            pubs.append(pubs_by_val[v])
            sigs.append(keys[v].sign(m))
    except ImportError:  # pragma: no cover
        from cometbft_tpu.crypto import ref_ed25519 as ref
        seeds = [bytes([int(b) for b in rng.integers(0, 256, 32)])
                 for _ in range(n_validators)]
        pubs_by_val = [ref.pubkey_from_seed(s) for s in seeds]
        pubs, sigs = [], []
        for i, m in enumerate(msgs):
            v = i % n_validators
            pubs.append(pubs_by_val[v])
            sigs.append(ref.sign(seeds[v], m))
    return pubs, msgs, sigs


def measure(batch, iters):
    """Time the RLC kernel on the already-initialized default backend.

    BENCH_KERNEL=xla|pallas picks the point-stage implementation;
    default: pallas on TPU backends, xla elsewhere (the pallas mosaic
    kernels target the chip). Returns (sigs_per_sec, compile_secs)."""
    import numpy as np
    import jax
    from cometbft_tpu.ops import ed25519 as e5
    from cometbft_tpu.ops.ed25519 import (
        prepare_batch, make_rlc_coefficients)

    which = os.environ.get("BENCH_KERNEL") or \
        ("pallas" if e5.use_pallas_rlc() else "xla")
    kernel = (e5.verify_rlc_kernel_pallas if which == "pallas"
              else e5.verify_rlc_kernel)
    _log(f"kernel: {which}")

    _log(f"generating {batch} signatures (200-validator set)...")
    pubs, msgs, sigs = _gen_signatures(batch)
    _log("packing batch...")
    pub, sig, hb, hn, ok_mask = prepare_batch(pubs, msgs, sigs, batch, 128)
    assert ok_mask.all()
    dev = jax.devices()[0]
    pub, sig, hb, hn = (jax.device_put(x, dev) for x in (pub, sig, hb, hn))

    # the production fast path: one random-linear-combination equation per
    # tile (fresh coefficients every flush, as the verifier requires)
    _log("compiling + warming RLC kernel (first compile can take "
         "tens of seconds; persistent cache is on for TPU)...")
    tc = time.monotonic()
    z = make_rlc_coefficients(batch)
    bok, sok = kernel(pub, sig, hb, hn, z)  # compile + warm
    compile_secs = time.monotonic() - tc
    assert bool(bok) and np.asarray(sok).all(), "warmup verification failed"
    _log(f"warm in {compile_secs:.1f}s; timing {iters} iterations...")

    t0 = time.perf_counter()
    for i in range(iters):
        z = make_rlc_coefficients(batch)
        bok, out = kernel(pub, sig, hb, hn, z)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    assert bool(bok)
    _log(f"{iters} x {batch} sigs in {dt:.3f}s")
    return batch * iters / dt, compile_secs, which


def _measure_mode(batch: int, iters: int) -> int:
    """Child process: init backend, compile, measure, print ONE JSON
    line. Isolated so a compiler crash (XLA is known to SIGSEGV — stack
    overflow — building `verify_rlc_core` at large batch on some
    backends, see docs/PERF.md) kills only this process and the parent
    can retry a smaller batch against the now-warm compile cache."""
    enable_compile_cache()
    import jax
    dev = jax.devices()[0]
    _log(f"measure[{batch}]: devices: {jax.devices()}")
    from cometbft_tpu.libs.jax_cache import ledger
    sigs_per_sec, compile_secs, which = measure(batch, iters)
    warm_before = ledger().seen(f"rlc-{which}", batch)
    ledger().record(f"rlc-{which}", batch, compile_secs)
    rec = {
        "metric": "ed25519_batch_verify_throughput",
        "value": round(sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(sigs_per_sec / BASELINE_SIGS_PER_SEC, 3),
        "batch": batch,
        # which point-stage implementation produced the number — the
        # xla fallback must be distinguishable from a pallas result
        "kernel": which,
        # compile-cache attribution (ledger keyed kernel|bucket):
        # whether this (kernel, batch) was previously recorded warm,
        # and what the compile actually cost this run
        "compile_s": round(compile_secs, 2),
        "compile_cache": {"seen_before": warm_before,
                          **ledger().attribution()},
    }
    if dev.platform == "cpu":
        rec["backend"] = "cpu"
    print(json.dumps(rec), flush=True)
    return 0


def _pipeline_mode() -> int:
    """`bench.py --pipeline`: END-TO-END catch-up sigs/s (the actual
    north-star metric) over a generated chain, A/B sync-vs-pipelined.

    The device is a fixed-latency stub (pipeline/scheduler.
    FixedLatencyBackend) so the A/B runs on CPU even while the TPU
    tunnel is wedged: the stub models the RTT-bound tunnel and answers
    all-true `latency` seconds after each dispatch. The synchronous
    baseline is the pipeline_depth=1 degenerate case over the SAME stub,
    so both sides pay identical per-tile device latency and the delta is
    purely the overlap. Emits ONE JSON line with the kernel-bench schema
    (metric/value/unit/vs_baseline + diagnostics keys).

    Env knobs: BENCH_PIPE_BLOCKS (96), BENCH_PIPE_VALS (32),
    BENCH_PIPE_TILE (8), BENCH_PIPE_DEPTH (4),
    BENCH_PIPE_LATENCY (s, 0.15 — the measured r4 device time for a
    production 32-block x 200-validator tile: 6400 lanes at the
    chip-measured 42.7k sigs/s, docs/PERF.md; applied as a fixed
    per-dispatch cost since the single-client tunnel is RTT/queue
    dominated at smaller tiles).
    """
    n_blocks = int(os.environ.get("BENCH_PIPE_BLOCKS", "96"))
    n_vals = int(os.environ.get("BENCH_PIPE_VALS", "32"))
    tile = int(os.environ.get("BENCH_PIPE_TILE", "8"))
    depth = int(os.environ.get("BENCH_PIPE_DEPTH", "4"))
    latency = float(os.environ.get("BENCH_PIPE_LATENCY", "0.15"))

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.db.kv import MemDB
    from cometbft_tpu.engine.blocksync import BlocksyncReactor
    from cometbft_tpu.engine.chain_gen import (LocalChainSource,
                                               generate_chain)
    from cometbft_tpu.pipeline.scheduler import (FixedLatencyBackend,
                                                 PipelinedBlocksync)
    from cometbft_tpu.state.execution import BlockExecutor
    from cometbft_tpu.state.state import State, StateStore
    from cometbft_tpu.store.blockstore import BlockStore

    _log(f"generating {n_blocks}-block chain, {n_vals} validators...")
    chain = generate_chain(n_blocks=n_blocks, n_validators=n_vals,
                           txs_per_block=1)
    n_sigs = n_blocks * n_vals

    def run_depth(k: int) -> float:
        app = KVStoreApplication()
        app.init_chain(chain.chain_id, 1, [], b"")
        db = MemDB()
        store = BlockStore(db)
        executor = BlockExecutor(app, state_store=StateStore(db),
                                 block_store=store)
        state = State.from_genesis(chain.genesis)
        reactor = BlocksyncReactor(
            executor, store, LocalChainSource(chain), chain.chain_id,
            tile_size=tile, batch_size=0)
        pipe = PipelinedBlocksync(
            reactor, depth=k, backend=FixedLatencyBackend(latency))
        t0 = time.perf_counter()
        try:
            while state.last_block_height < n_blocks:
                state = pipe.run(state, n_blocks)
        finally:
            pipe.close()
        dt = time.perf_counter() - t0
        assert state.last_block_height == n_blocks
        assert reactor.stats.blocks_applied == n_blocks
        _log(f"depth={k}: {n_sigs} sigs in {dt:.3f}s "
             f"({n_sigs / dt:,.0f} sigs/s)")
        return n_sigs / dt

    sync_rate = run_depth(1)
    pipe_rate = run_depth(depth)
    rec = {
        "metric": "blocksync_catchup_throughput",
        "value": round(pipe_rate, 1),
        "unit": "sigs/s",
        "vs_baseline": round(pipe_rate / BASELINE_SIGS_PER_SEC, 3),
        "backend": "cpu-stub",
        "depth": depth,
        "tile_size": tile,
        "stub_latency_s": latency,
        "sync_sigs_per_sec": round(sync_rate, 1),
        "speedup_vs_sync": round(pipe_rate / sync_rate, 2),
        "blocks": n_blocks,
        "validators": n_vals,
    }
    print(json.dumps(rec), flush=True)
    return 0


def _aggsig_mode(miller_backend: str = "fast") -> int:
    """`bench.py --aggsig [--miller-backend oracle|fast|kernel]`:
    pick the Miller-loop implementation for the BLS legs, restore
    process state afterwards, and ALWAYS emit the one JSON line —
    a kernel failure degrades to the CPU path inside the
    supervisor-attached PairingChecker (probe/backoff discipline,
    device/health), and even a setup crash still prints an error
    record so sweep harnesses never lose the datapoint.

      oracle — the slow per-pair r-loop Miller product (pre-PR
               baseline, kept as the correctness oracle);
      fast   — the host optimal-ate loop (default production path);
      kernel — the fused ops/bls12 Miller + final-exp device call
               (COMETBFT_TPU_AGGSIG_KERNEL=1 semantics; on XLA:CPU
               this pays the multi-minute scan compile the ledger
               attributes under bls-miller@bucket|platform)."""
    import cometbft_tpu.crypto.bls12381 as bls_mod
    from cometbft_tpu.aggsig.verify import (ENV_KERNEL,
                                            reset_shared_finalexp)
    if miller_backend not in ("oracle", "fast", "kernel"):
        _log(f"unknown --miller-backend {miller_backend!r} "
             "(expected oracle|fast|kernel)")
        return 2
    restore = (bls_mod.miller_product, bls_mod.miller_loop)
    if miller_backend == "oracle":
        bls_mod.miller_product = bls_mod.miller_product_slow
        bls_mod.miller_loop = bls_mod.miller_loop_slow
    elif miller_backend == "kernel":
        os.environ[ENV_KERNEL] = "1"
    reset_shared_finalexp()     # re-decide the backend under the knob
    try:
        return _aggsig_bench(miller_backend)
    except Exception as exc:  # noqa: BLE001 — the JSON line must land
        print(json.dumps({"metric": "aggsig_catchup_commit_verify",
                          "miller_backend": miller_backend,
                          "error": f"{type(exc).__name__}: {exc}"}),
              flush=True)
        return 1
    finally:
        bls_mod.miller_product, bls_mod.miller_loop = restore
        if miller_backend == "kernel":
            os.environ.pop(ENV_KERNEL, None)
        reset_shared_finalexp()


def _aggsig_bench(miller_backend: str) -> int:
    """200-validator blocksync catch-up A/B —
    ed25519 batch verification vs the BLS aggregate-commit fast path
    (ROADMAP item 2, docs/AGGSIG.md).

    Three measured sides over same-shape generated chains:
      * ed25519: the existing native catch-up path (the production
        baseline these chains run today);
      * BLS aggregate: AggregatedCommit seals through the real
        blocksync marshal/settle route — per commit the pairing work
        is O(1) (two Miller loops + ONE final exponentiation when the
        quorum is co-timed), read off crypto/bls12381.OP_COUNTERS;
      * BLS per-signature: a measured sample of individual verifies,
        projected to the full set — the O(n) reference the aggregate
        replaces (2n Miller loops + n final exponentiations).

    One-time costs are attributed separately: proof-of-possession
    admission (amortized over each key's lifetime) and chain
    generation. Emits ONE JSON line (kernel-bench schema) including
    pairings-per-commit and the compile-cache ledger attribution.

    Env knobs: BENCH_AGG_VALS (200), BENCH_AGG_BLOCKS (4),
    BENCH_AGG_SAMPLE (4, per-sig sample size)."""
    n_vals = int(os.environ.get("BENCH_AGG_VALS", "200"))
    n_blocks = int(os.environ.get("BENCH_AGG_BLOCKS", "4"))
    sample = max(1, min(int(os.environ.get("BENCH_AGG_SAMPLE", "4")),
                        n_vals))

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.aggsig.aggregate import (register_pops_batch,
                                               reset_pop_registry)
    from cometbft_tpu.aggsig.verify import shared_pairing
    from cometbft_tpu.crypto.bls12381 import OP_COUNTERS
    from cometbft_tpu.db.kv import MemDB
    from cometbft_tpu.engine.blocksync import BlocksyncReactor
    from cometbft_tpu.engine.chain_gen import (LocalChainSource,
                                               generate_chain)
    from cometbft_tpu.libs.jax_cache import ledger
    from cometbft_tpu.pipeline.cache import reset_shared_cache
    from cometbft_tpu.state.execution import BlockExecutor
    from cometbft_tpu.state.state import State, StateStore
    from cometbft_tpu.store.blockstore import BlockStore
    from cometbft_tpu.types.agg_commit import AggregatedCommit

    pc = shared_pairing()
    if pc.backend == "kernel" and pc.supervisor is None:
        # probe/backoff supervision for the device path: a tripping or
        # corrupt kernel degrades every checker to CPU and the trip is
        # visible in the emitted record instead of killing the bench
        from cometbft_tpu.device.health import DeviceSupervisor
        sup = DeviceSupervisor()
        pc.supervisor = sup
        pc.finalexp.supervisor = sup
    _log(f"miller backend: {miller_backend} "
         f"(pairing checker backend: {pc.backend})")

    def catchup(chain) -> float:
        app = KVStoreApplication()
        app.init_chain(chain.chain_id, 1, [], b"")
        db = MemDB()
        store = BlockStore(db)
        executor = BlockExecutor(app, state_store=StateStore(db),
                                 block_store=store)
        state = State.from_genesis(chain.genesis)
        reactor = BlocksyncReactor(
            executor, store, LocalChainSource(chain), chain.chain_id,
            tile_size=8, batch_size=0)
        reset_shared_cache()
        t0 = time.perf_counter()
        state = reactor.sync(state)
        dt = time.perf_counter() - t0
        assert state.last_block_height == chain.max_height()
        return dt

    _log(f"generating {n_blocks}-block ed25519 chain, "
         f"{n_vals} validators...")
    ed_chain = generate_chain(n_blocks=n_blocks, n_validators=n_vals,
                              txs_per_block=1)
    ed_s = catchup(ed_chain)
    _log(f"ed25519 catch-up: {n_blocks * n_vals} sigs in {ed_s:.2f}s")

    _log(f"generating {n_blocks}-block BLS chain (aggregated seals)...")
    t0 = time.perf_counter()
    bls_chain = generate_chain(
        n_blocks=n_blocks, n_validators=n_vals, txs_per_block=1,
        key_type="bls12_381", aggregate=True)
    gen_s = time.perf_counter() - t0
    for c in bls_chain.seen_commits:
        assert isinstance(c, AggregatedCommit)

    # one-time PoP admission cost (batched RLC multi-pairing),
    # measured against a cleared registry
    reset_pop_registry()
    t0 = time.perf_counter()
    assert register_pops_batch(bls_chain.genesis.bls_pops)
    pop_s = time.perf_counter() - t0
    _log(f"PoP admission: {n_vals} keys in {pop_s:.2f}s "
         f"({pop_s / n_vals * 1000:.0f} ms/key, one-time)")

    c0 = dict(OP_COUNTERS)
    agg_s = catchup(bls_chain)
    millers = OP_COUNTERS["miller_loops"] - c0["miller_loops"]
    fexps = OP_COUNTERS["final_exps"] - c0["final_exps"]
    _log(f"BLS aggregate catch-up: {n_blocks} commits "
         f"({n_vals} signers each) in {agg_s:.2f}s — "
         f"{millers} Miller loops, {fexps} final exps total")

    # per-signature BLS reference, measured on a sample
    from cometbft_tpu.types.vote import Vote, PRECOMMIT_TYPE
    from cometbft_tpu.types.proto import Timestamp
    vals0 = bls_chain.valsets[0]
    t0 = time.perf_counter()
    checked = 0
    for i in range(sample):
        val = vals0.validators[i]
        key = bls_chain.keys[val.address]
        vote = Vote(type_=PRECOMMIT_TYPE, height=1, round=0,
                    block_id=bls_chain.block_ids[0],
                    timestamp=Timestamp(1_700_000_001, 1_000_000 + i),
                    validator_address=val.address, validator_index=i)
        sig = key.sign(vote.sign_bytes(bls_chain.chain_id))
        t_sig = time.perf_counter()
        assert val.pub_key.verify_signature(
            vote.sign_bytes(bls_chain.chain_id), sig)
        checked += 1
        del t_sig
    per_sig_s = (time.perf_counter() - t0) / checked
    projected_commit_s = per_sig_s * n_vals

    agg_commit_s = agg_s / n_blocks
    rec = {
        "metric": "aggsig_catchup_commit_verify",
        "value": round(agg_commit_s, 3),
        "unit": "s/commit",
        "vs_baseline": round(projected_commit_s / agg_commit_s, 1),
        "backend": pc.backend,
        "miller_backend": miller_backend,
        "kernel_quarantined": pc.quarantined,
        "validators": n_vals,
        "blocks": n_blocks,
        "pairings_per_commit": {
            "aggregate_miller_loops": round(millers / n_blocks, 2),
            "aggregate_final_exps": round(fexps / n_blocks, 2),
            "per_sig_miller_loops": 2 * n_vals,
            "per_sig_final_exps": n_vals,
        },
        "bls_aggregate_catchup_s": round(agg_s, 3),
        "bls_per_sig_s_measured": round(per_sig_s, 3),
        "bls_per_sig_commit_s_projected": round(projected_commit_s, 1),
        "speedup_vs_per_sig": round(projected_commit_s / agg_commit_s, 1),
        "ed25519_catchup_s": round(ed_s, 3),
        "ed25519_sigs_per_sec": round(n_blocks * n_vals / ed_s, 1),
        "pop_admission_s_total": round(pop_s, 2),
        "chain_gen_s": round(gen_s, 2),
        "compile_cache": ledger().attribution(),
    }
    print(json.dumps(rec), flush=True)
    return 0


def _sealsync_mode() -> int:
    """`bench.py --sealsync`: seal-adoption vs full-blocksync catch-up
    A/B (docs/SEALSYNC.md). ALWAYS emits the one JSON line — even a
    setup crash prints an error record so sweep harnesses never lose
    the datapoint."""
    try:
        return _sealsync_bench()
    except Exception as exc:  # noqa: BLE001 — the JSON line must land
        print(json.dumps({"metric": "sealsync_time_to_decided",
                          "error": f"{type(exc).__name__}: {exc}"}),
              flush=True)
        return 1


def _sealsync_bench() -> int:
    """Wide-valset catch-up A/B — aggregate-seal adoption vs full
    blocksync over the SAME generated BLS chain (ROADMAP item 2,
    docs/SEALSYNC.md).

    Side A (sealsync): SealAdopter walks the seal chain, pairs only
    the skip-schedule pivots, and installs every decided height as an
    adopted-seal record — time-to-decided, no block bodies. Then the
    body BACKFILL leg: a real BlocksyncReactor catch-up riding the
    adopter's SigCache, where every adopted commit must be a
    whole-aggregate cache hit (zero extra pairings).

    Side B (baseline): plain full blocksync from scratch — one
    aggregate pairing per commit plus body execution, the path a
    laggard pays today.

    Adoption runs FIRST so any one-time compile/warmup lands on side
    A's clock — the reported speedup is conservative. Emits ONE JSON
    line (kernel-bench schema) including per-side pairing-op deltas
    and the compile-cache ledger attribution.

    Env knobs: BENCH_SEAL_VALS (200), BENCH_SEAL_BLOCKS (8),
    BENCH_SEAL_SKIP (4, pivot cadence)."""
    n_vals = int(os.environ.get("BENCH_SEAL_VALS", "200"))
    n_blocks = int(os.environ.get("BENCH_SEAL_BLOCKS", "8"))
    max_skip = int(os.environ.get("BENCH_SEAL_SKIP", "4"))

    from cometbft_tpu.abci.kvstore import KVStoreApplication
    from cometbft_tpu.aggsig.aggregate import reset_pop_registry
    from cometbft_tpu.aggsig.verify import shared_pairing
    from cometbft_tpu.crypto.bls12381 import OP_COUNTERS
    from cometbft_tpu.db.kv import MemDB
    from cometbft_tpu.engine.blocksync import BlocksyncReactor
    from cometbft_tpu.engine.chain_gen import (ChainSealSource,
                                               LocalChainSource,
                                               generate_chain)
    from cometbft_tpu.libs.jax_cache import ledger
    from cometbft_tpu.libs.metrics import Registry
    from cometbft_tpu.libs.metrics_gen import SealsyncMetrics
    from cometbft_tpu.pipeline.cache import SigCache, reset_shared_cache
    from cometbft_tpu.sealsync import SealAdopter
    from cometbft_tpu.state.execution import BlockExecutor
    from cometbft_tpu.state.state import State, StateStore
    from cometbft_tpu.store.blockstore import BlockStore
    from cometbft_tpu.types.agg_commit import AggregatedCommit

    pc = shared_pairing()
    _log(f"pairing checker backend: {pc.backend}")

    _log(f"generating {n_blocks}-block BLS chain (aggregated seals), "
         f"{n_vals} validators...")
    t0 = time.perf_counter()
    chain = generate_chain(
        n_blocks=n_blocks, n_validators=n_vals, txs_per_block=1,
        key_type="bls12_381", aggregate=True)
    gen_s = time.perf_counter() - t0
    for c in chain.seen_commits:
        assert isinstance(c, AggregatedCommit)
    tip = chain.max_height()

    def catchup(store, cache) -> float:
        """Real blocksync catch-up into `store`; `cache` is the
        marshal-route SigCache (the adopter's on the backfill leg,
        None on the baseline)."""
        app = KVStoreApplication()
        app.init_chain(chain.chain_id, 1, [], b"")
        executor = BlockExecutor(app, state_store=StateStore(MemDB()),
                                 block_store=store)
        state = State.from_genesis(chain.genesis)
        reactor = BlocksyncReactor(
            executor, store, LocalChainSource(chain), chain.chain_id,
            tile_size=8, batch_size=0, cache=cache)
        t0 = time.perf_counter()
        state = reactor.sync(state)
        dt = time.perf_counter() - t0
        assert state.last_block_height == tip
        return dt

    # ---- side A: seal adoption (time-to-decided), then backfill ----
    reset_pop_registry()
    reset_shared_cache()
    a_state = State.from_genesis(chain.genesis)  # registers PoPs
    a_store = BlockStore(MemDB())
    a_cache = SigCache(65536)
    metrics = SealsyncMetrics(Registry())
    adopter = SealAdopter(
        chain.chain_id, a_store, ChainSealSource(chain),
        tile_size=8, max_skip=max_skip, cache=a_cache, shards=1,
        metrics=metrics)
    c0 = dict(OP_COUNTERS)
    t0 = time.perf_counter()
    adopted = adopter.adopt(a_state)
    adopt_s = time.perf_counter() - t0
    adopt_millers = OP_COUNTERS["miller_loops"] - c0["miller_loops"]
    adopt_fexps = OP_COUNTERS["final_exps"] - c0["final_exps"]
    assert adopted == tip and a_store.adopted_tip() == tip
    pivots = int(metrics.pivots_verified.value())
    skipped = int(metrics.pairings_skipped.value())
    _log(f"seal adoption: decided through h={adopted} in "
         f"{adopt_s:.2f}s — {pivots} pivot pairings, "
         f"{skipped} heights adopted without pairing")

    c0 = dict(OP_COUNTERS)
    backfill_s = catchup(a_store, a_cache)
    bf_millers = OP_COUNTERS["miller_loops"] - c0["miller_loops"]
    bf_fexps = OP_COUNTERS["final_exps"] - c0["final_exps"]
    _log(f"body backfill (adopter cache): {backfill_s:.2f}s — "
         f"{bf_millers} Miller loops, {bf_fexps} final exps "
         f"(adopted commits must be cache hits)")

    # ---- side B: full blocksync from scratch (the baseline) ----
    reset_pop_registry()
    reset_shared_cache()
    c0 = dict(OP_COUNTERS)
    blocksync_s = catchup(BlockStore(MemDB()), None)
    bs_millers = OP_COUNTERS["miller_loops"] - c0["miller_loops"]
    bs_fexps = OP_COUNTERS["final_exps"] - c0["final_exps"]
    _log(f"full blocksync: {blocksync_s:.2f}s — {bs_millers} Miller "
         f"loops, {bs_fexps} final exps")

    rec = {
        "metric": "sealsync_time_to_decided",
        "value": round(adopt_s, 3),
        "unit": "s",
        "vs_baseline": round(blocksync_s / adopt_s, 1),
        "backend": pc.backend,
        "validators": n_vals,
        "blocks": n_blocks,
        "max_skip": max_skip,
        "adopt_s": round(adopt_s, 3),
        "backfill_s": round(backfill_s, 3),
        "adopt_plus_backfill_s": round(adopt_s + backfill_s, 3),
        "blocksync_s": round(blocksync_s, 3),
        "speedup_decided": round(blocksync_s / adopt_s, 1),
        "speedup_full": round(blocksync_s / (adopt_s + backfill_s), 2),
        "pivot_pairings": pivots,
        "heights_adopted_without_pairing": skipped,
        "pairing_ops": {
            "adopt_miller_loops": adopt_millers,
            "adopt_final_exps": adopt_fexps,
            "backfill_miller_loops": bf_millers,
            "backfill_final_exps": bf_fexps,
            "blocksync_miller_loops": bs_millers,
            "blocksync_final_exps": bs_fexps,
        },
        "chain_gen_s": round(gen_s, 2),
        "compile_cache": ledger().attribution(),
    }
    print(json.dumps(rec), flush=True)
    return 0


def _measure_mesh_mode(n_devices: int, iters: int) -> int:
    """Child process: build the (commit, sig) topology over
    `n_devices`, warm the planned bucket (ledger-recorded under the
    mesh-shape kernel key), and time sharded dispatches through the
    real MeshExecutor. One JSON line on stdout. Isolated per device
    count: a mesh-compile crash kills only this child and the parent
    still emits the other counts."""
    enable_compile_cache()
    from collections import Counter
    from cometbft_tpu.libs.jax_cache import ledger
    from cometbft_tpu.mesh import MeshExecutor, MeshTopology
    from cometbft_tpu.mesh.planner import lanes_kernel_name

    from cometbft_tpu.device.health import CANARY_LANES
    width = int(os.environ.get("BENCH_MESH_WIDTH", "512"))
    topology = MeshTopology(n_devices=n_devices)
    view = topology.view()
    if view.n_shards != n_devices:
        raise SystemExit(f"only {view.n_shards} devices available, "
                         f"wanted {n_devices}")
    ex = MeshExecutor(topology, threaded=False)
    n_real = max(1, (width - CANARY_LANES) * view.n_shards)
    kernel = lanes_kernel_name(view.shape)
    bucket = width * view.n_shards
    warm_before = ledger().seen(kernel, bucket)
    _log(f"mesh[{n_devices}]: shape {view.shape[0]}x{view.shape[1]}, "
         f"bucket {bucket} ({width}/shard), warming...")
    t0 = time.monotonic()
    ex.warm([width], probe=False)  # a bench child never regrows
    compile_s = time.monotonic() - t0
    _log(f"mesh[{n_devices}]: warm in {compile_s:.1f}s; generating "
         f"{n_real} signatures...")
    pubs, msgs, sigs = _gen_signatures(n_real)
    # one untimed dispatch of the REAL batch: generic first-call
    # warm-up (device transfer paths, host marshalling caches) so the
    # timed loop measures steady state only
    t0 = time.monotonic()
    ex.verify(pubs, msgs, sigs)
    compile_s += time.monotonic() - t0
    t0 = time.perf_counter()
    fut = None
    for _ in range(iters):
        fut = ex.submit(pubs, msgs, sigs)
        out = fut.result()
    dt = time.perf_counter() - t0
    assert all(out), "bench lanes must all verify"
    per_shard = Counter(fut.shards)
    ex.close()
    rec = {
        "devices": n_devices,
        "shape": list(view.shape),
        "sigs_per_sec": round(n_real * iters / dt, 1),
        "bucket": bucket,
        "lanes_per_dispatch": n_real,
        "compile_s": round(compile_s, 2),
        "ledger_warm_before": warm_before,
        # per-shard result attribution: every lane's verdict names the
        # shard that produced it (device/protocol trailer semantics)
        "per_shard_lanes": {str(k): v
                            for k, v in sorted(per_shard.items())},
    }
    print(json.dumps(rec), flush=True)
    return 0


def _mesh_mode() -> int:
    """`bench.py --mesh`: per-device-count sigs/s through the sharded
    mesh executor (the ISSUE-12 acceptance bench). ALWAYS emits one
    JSON line: with no reachable device the measurement falls back to
    forced host-platform CPU devices (XLA_FLAGS
    --xla_force_host_platform_device_count), attributed via
    backend/fallback_reason/cpu_clamp — a wedged tunnel degrades the
    number, never the emission.

    Env knobs: BENCH_MESH_DEVICES ("1,2,4,8"), BENCH_MESH_WIDTH
    (per-shard lanes, default 512 device / 8 CPU-clamped),
    BENCH_ITERS, BENCH_MEASURE_TIMEOUT, BENCH_ALLOW_CPU."""
    iters = int(os.environ.get("BENCH_ITERS", "4"))
    counts = [int(c) for c in os.environ.get(
        "BENCH_MESH_DEVICES", "1,2,4,8").split(",") if c.strip()]
    allow_cpu = os.environ.get("BENCH_ALLOW_CPU") == "1"
    measure_timeout = float(os.environ.get("BENCH_MEASURE_TIMEOUT",
                                           "1500"))
    platform = probe_backend()
    fallback_reason = None
    if platform is None:
        fallback_reason = "device-unreachable (probe budget exhausted)"
    elif platform == "cpu" and not allow_cpu:
        fallback_reason = "cpu-backend-only"
    from cometbft_tpu.libs.jax_cache import ledger
    from cometbft_tpu.mesh.planner import lanes_kernel_name
    from cometbft_tpu.parallel.mesh import factor_mesh_shape
    want_width = int(os.environ.get("BENCH_MESH_WIDTH",
                                    "512" if not fallback_reason
                                    else "8"))
    results = {}
    best = 0.0
    for d in counts:
        child_env = dict(os.environ)
        cpu_clamp = None
        width = want_width
        if fallback_reason:
            # forced host devices stand in for the mesh; clamp the
            # per-shard width to the smallest bucket unless the ledger
            # shows this exact (mesh-shape, bucket) compiled cleanly
            # on cpu before (same lift rule as the kernel bench)
            child_env["JAX_PLATFORMS"] = "cpu"
            child_env["XLA_FLAGS"] = (
                child_env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={d}")
            shape = factor_mesh_shape(d)
            if want_width > 8 and ledger().seen(
                    lanes_kernel_name(shape), want_width * d,
                    platform="cpu"):
                cpu_clamp = "lifted-ledger-warm"
            else:
                cpu_clamp = "clamped-width-8"
                width = min(want_width, 8)
        child_env["BENCH_MESH_WIDTH"] = str(width)
        _log(f"measuring mesh over {d} device(s) in a subprocess "
             f"(timeout {measure_timeout:.0f}s)...")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--measure-mesh", str(d), str(iters)],
                env=child_env, capture_output=True, text=True,
                timeout=measure_timeout)
        except subprocess.TimeoutExpired:
            results[str(d)] = {"error": "timeout"}
            continue
        sys.stderr.write(r.stderr)
        line = next((ln for ln in r.stdout.splitlines()
                     if ln.startswith("{")), None)
        if r.returncode == 0 and line:
            rec = json.loads(line)
            if cpu_clamp:
                rec["cpu_clamp"] = cpu_clamp
            results[str(d)] = rec
            best = max(best, rec["sigs_per_sec"])
        else:
            if r.returncode < 0:
                ledger().record_crash(
                    lanes_kernel_name(factor_mesh_shape(d)), width * d,
                    f"signal {-r.returncode}",
                    platform="cpu" if fallback_reason else None)
            results[str(d)] = {
                "error": f"rc={r.returncode}",
                "detail": (r.stderr or "").strip().splitlines()[-1:]}
    rec = {
        "metric": "mesh_verify_throughput",
        "value": round(best, 1),
        "unit": "sigs/s",
        "vs_baseline": round(best / BASELINE_SIGS_PER_SEC, 3),
        "per_device_count": results,
        "iters": iters,
        "compile_cache": ledger().attribution(),
    }
    if fallback_reason:
        rec["backend"] = "cpu"
        rec["fallback_reason"] = fallback_reason
    print(json.dumps(rec), flush=True)
    return 0


def main():
    batch = int(os.environ.get("BENCH_BATCH", "8192"))
    iters = int(os.environ.get("BENCH_ITERS", "4"))
    allow_cpu = os.environ.get("BENCH_ALLOW_CPU") == "1"
    measure_timeout = float(os.environ.get("BENCH_MEASURE_TIMEOUT", "1500"))

    platform = probe_backend()
    # a bench round ALWAYS emits a number (ROADMAP item 5): when the
    # device is unreachable or only the CPU backend exists, measure the
    # attributed CPU fallback instead of dying numberless — the JSON
    # carries backend+fallback_reason so a CPU number can never be
    # mistaken for the TPU headline. BENCH_REQUIRE_TPU=1 restores the
    # old hard-fail for callers that must not spend CPU-compile time.
    fallback_reason = None
    if platform is None:
        fallback_reason = "device-unreachable (probe budget exhausted)"
    elif platform == "cpu" and not allow_cpu:
        fallback_reason = "cpu-backend-only"
    if fallback_reason and os.environ.get("BENCH_REQUIRE_TPU") == "1":
        print(f"bench: FATAL: {fallback_reason} and BENCH_REQUIRE_TPU=1; "
              f"see docs/PERF.md for the last recorded TPU measurement.",
              file=sys.stderr, flush=True)
        return 1
    child_env_extra = {}
    cpu_clamp = None
    if fallback_reason:
        _log(f"falling back to attributed CPU measurement "
             f"({fallback_reason})")
        # pin the cpu platform in every child so nothing touches the
        # (possibly wedged) tunnel mid-measurement
        child_env_extra["JAX_PLATFORMS"] = "cpu"
        platform = "cpu"
        # the XLA:CPU compile hazard (docs/PERF.md): batches >=256 can
        # crash the compiler outright and even 256 pays minutes —
        # clamp to the 64-lane CPU bucket the tree already uses,
        # UNLESS the compile ledger proves this (kernel, bucket)
        # already compiled CLEANLY on this platform/jax build: then
        # the measure child pays the known, recorded compile_s (still
        # bounded by BENCH_MEASURE_TIMEOUT) instead of being pinned to
        # tiny tiles forever (ROADMAP item-5 residual). A ledger miss
        # or a crash verdict keeps the old clamp.
        # the lookup must use the CHILD's platform key ("cpu"): the
        # parent may still be configured for the device platform, and
        # a device entry for the same (kernel, batch) must never lift
        # the CPU clamp
        from cometbft_tpu.libs.jax_cache import ledger as _lg
        if batch > 64 and _lg().seen(
                f"rlc-{os.environ.get('BENCH_KERNEL', 'xla')}", batch,
                platform="cpu"):
            cpu_clamp = "lifted-ledger-warm"
            _log(f"64-lane CPU clamp lifted: ledger shows a clean "
                 f"compile for batch={batch} on this platform")
        else:
            cpu_clamp = "clamped-64"
            batch = min(batch, 64)

    # measurement runs in a child per batch attempt: a compiler crash
    # falls back to the next smaller batch (the RLC equation amortizes
    # fully well before 1k lanes, so smaller tiles remain a fair
    # measurement), and a hang is bounded by the timeout
    attempts = []
    for b in (batch, batch // 4, 1024, 256, 64):
        if 1 <= b <= batch and b not in attempts:
            attempts.append(b)
    # kernel fallback: if the (default) pallas point-stage fails to
    # compile/run on this backend, retry the same batch with the pure
    # XLA kernel before shrinking the batch
    if os.environ.get("BENCH_KERNEL"):
        kernels = [os.environ["BENCH_KERNEL"]]
    elif platform == "cpu":
        kernels = ["xla"]
    else:
        kernels = ["pallas", "xla"]
    deadline = time.monotonic() + float(
        os.environ.get("BENCH_TOTAL_TIMEOUT", "4500"))
    from cometbft_tpu.libs.jax_cache import ledger
    for b in attempts:
        for which in kernels:
            if time.monotonic() > deadline:
                _log("total bench budget exhausted")
                return 1
            # key under the platform the measure CHILD runs on — in
            # fallback mode the parent is still device-configured
            child_platform = "cpu" if fallback_reason else None
            if ledger().known_crash(f"rlc-{which}", b,
                                    platform=child_platform):
                # the compile ledger remembers this (kernel, bucket)
                # killed the compiler on this platform/jax build —
                # skip straight to the next shape instead of paying
                # the crash again (ROADMAP item-5 residual)
                _log(f"skip batch={b} kernel={which}: ledger marks it "
                     f"compiler-fatal on this platform")
                continue
            _log(f"measuring batch={b} kernel={which} in a subprocess "
                 f"(timeout {measure_timeout:.0f}s)...")
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--measure", str(b), str(iters)],
                    env=dict(os.environ, BENCH_KERNEL=which,
                             **child_env_extra),
                    capture_output=True, text=True,
                    timeout=measure_timeout)
            except subprocess.TimeoutExpired:
                # a hung pallas compile must not kill the run — the
                # XLA kernel (or a smaller batch) may still produce
                # the number
                _log(f"measure[{b},{which}] timed out; trying the "
                     f"next kernel/batch")
                continue
            sys.stderr.write(r.stderr)
            line = next((ln for ln in r.stdout.splitlines()
                         if ln.startswith("{")), None)
            if r.returncode == 0 and line:
                if fallback_reason:
                    # attribute the fallback in the emitted record so
                    # a CPU number is never mistaken for the headline;
                    # cpu_clamp records whether the 64-lane clamp held
                    # or was lifted by a warm ledger bucket
                    rec = json.loads(line)
                    rec["backend"] = "cpu"
                    rec["fallback_reason"] = fallback_reason
                    rec["cpu_clamp"] = cpu_clamp
                    line = json.dumps(rec)
                print(line, flush=True)
                return 0
            if r.returncode < 0:
                # compiler crash (SIGSEGV et al): remember the bucket
                # so future rounds skip it without re-crashing
                ledger().record_crash(f"rlc-{which}", b,
                                      f"signal {-r.returncode}",
                                      platform=child_platform)
            _log(f"measure[{b},{which}] failed rc={r.returncode} "
                 f"(signal="
                 f"{-r.returncode if r.returncode < 0 else 'none'}); "
                 f"retrying")
    _log("all batch sizes failed")
    return 1


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--measure":
        sys.exit(_measure_mode(int(sys.argv[2]), int(sys.argv[3])))
    if len(sys.argv) > 1 and sys.argv[1] == "--measure-mesh":
        sys.exit(_measure_mesh_mode(int(sys.argv[2]), int(sys.argv[3])))
    if len(sys.argv) > 1 and sys.argv[1] == "--pipeline":
        sys.exit(_pipeline_mode())
    if len(sys.argv) > 1 and sys.argv[1] == "--aggsig":
        mb = "fast"
        if "--miller-backend" in sys.argv[2:]:
            i = sys.argv.index("--miller-backend")
            mb = sys.argv[i + 1] if i + 1 < len(sys.argv) else ""
        sys.exit(_aggsig_mode(mb))
    if len(sys.argv) > 1 and sys.argv[1] == "--sealsync":
        sys.exit(_sealsync_mode())
    if len(sys.argv) > 1 and sys.argv[1] == "--mesh":
        sys.exit(_mesh_mode())
    sys.exit(main())
