"""ASCII-armored, passphrase-encrypted private keys
(reference crypto/armor/armor.go, crypto/xsalsa20symmetric — the
`export/import` key codec; AEAD here is ChaCha20-Poly1305 with an
scrypt-style KDF replaced by PBKDF2-HMAC-SHA256, both stdlib-backed).

Format:
  -----BEGIN COMETBFT_TPU PRIVATE KEY-----
  kdf: pbkdf2-sha256
  salt: <hex>
  type: <key type>
  <base64 of nonce || AEAD ciphertext>
  -----END COMETBFT_TPU PRIVATE KEY-----
"""

from __future__ import annotations

import base64
import hashlib
import os
from typing import Tuple

try:
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305)
except ImportError:  # pragma: no cover — keep the module importable
    # without the cryptography wheel; armoring then raises at use
    ChaCha20Poly1305 = None

_HEADER = "-----BEGIN COMETBFT_TPU PRIVATE KEY-----"
_FOOTER = "-----END COMETBFT_TPU PRIVATE KEY-----"
_KDF_ROUNDS = 100_000


class ArmorError(Exception):
    pass


def _derive(passphrase: str, salt: bytes) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", passphrase.encode(), salt,
                               _KDF_ROUNDS, dklen=32)


def _require_aead():
    if ChaCha20Poly1305 is None:
        raise ArmorError("the 'cryptography' package is required for "
                         "key armoring; it is not installed")


def encrypt_armor_privkey(key_bytes: bytes, key_type: str,
                          passphrase: str) -> str:
    _require_aead()
    salt = os.urandom(16)
    nonce = os.urandom(12)
    aead = ChaCha20Poly1305(_derive(passphrase, salt))
    sealed = aead.encrypt(nonce, key_bytes, key_type.encode())
    body = base64.b64encode(nonce + sealed).decode()
    return "\n".join([
        _HEADER,
        "kdf: pbkdf2-sha256",
        f"salt: {salt.hex()}",
        f"type: {key_type}",
        "",
        body,
        _FOOTER,
    ])


def unarmor_decrypt_privkey(armored: str, passphrase: str
                            ) -> Tuple[bytes, str]:
    """-> (key bytes, key type). Raises ArmorError on bad format or
    wrong passphrase."""
    lines = [ln.strip() for ln in armored.strip().splitlines()]
    if not lines or lines[0] != _HEADER or lines[-1] != _FOOTER:
        raise ArmorError("missing armor header/footer")
    headers = {}
    body_lines = []
    for ln in lines[1:-1]:
        if ":" in ln and not body_lines and ln:
            k, _, v = ln.partition(":")
            headers[k.strip()] = v.strip()
        elif ln:
            body_lines.append(ln)
    if headers.get("kdf") != "pbkdf2-sha256":
        raise ArmorError(f"unsupported kdf {headers.get('kdf')!r}")
    try:
        salt = bytes.fromhex(headers["salt"])
        blob = base64.b64decode("".join(body_lines))
    except (KeyError, ValueError) as e:
        raise ArmorError(f"malformed armor: {e}") from e
    key_type = headers.get("type", "")
    _require_aead()
    aead = ChaCha20Poly1305(_derive(passphrase, salt))
    try:
        plain = aead.decrypt(blob[:12], blob[12:], key_type.encode())
    except Exception as e:
        raise ArmorError("wrong passphrase or corrupted armor") from e
    return plain, key_type
