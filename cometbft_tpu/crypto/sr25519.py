"""sr25519: Schnorr signatures over ristretto255 with Merlin transcripts
(reference crypto/sr25519/ — curve25519-voi's schnorrkel implementation;
batch verify at crypto/sr25519/batch.go:44-77, merlin transcripts :69).

Layered the way schnorrkel is:
- Keccak-f[1600] → STROBE-128 (AD / meta-AD / PRF ops) → Merlin
  transcript (append_message / challenge_bytes),
- ristretto255 group on top of the edwards25519 big-int oracle
  (ref_ed25519): canonical decode/encode, torsion-free by construction,
- Schnorr: sig = R(32) || s(32) with schnorrkel's high-bit marker on s;
  k = transcript challenge binding proto-name, context, message, A, R.

Structure follows the published schnorrkel/merlin/STROBE specs.
Cross-implementation vectors pinned in tests/test_curves.py:
- the merlin crate's transcript equivalence vector (byte-exact through
  Keccak-f[1600] → STROBE-128 → Merlin framing), and
- schnorrkel's MiniSecretKey Ed25519-expansion → public key vector
  (byte-exact ristretto255 encode + scalar mul + cofactor division),
which together cover every primitive a signature touches; sign/verify/
batch round-trips and tamper rejection are validated in-tree on top.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass
from typing import List, Optional, Tuple

from . import ref_ed25519 as ed

SR25519_KEY_TYPE = "sr25519"

SIGNING_CTX = b"substrate"

# --- Keccak-f[1600] ----------------------------------------------------------

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
_ROT = [[0, 36, 3, 41, 18], [1, 44, 10, 45, 2], [62, 6, 43, 15, 61],
        [28, 55, 25, 21, 56], [27, 20, 39, 8, 14]]
_MASK = (1 << 64) - 1


def _rol(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK


def keccak_f1600(state: bytearray) -> None:
    """In-place permutation over 200 bytes."""
    a = [[int.from_bytes(state[8 * (x + 5 * y):8 * (x + 5 * y) + 8],
                         "little") for y in range(5)] for x in range(5)]
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4]
             for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rol(a[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y]) & _MASK
                                     & b[(x + 2) % 5][y])
        # iota
        a[0][0] ^= rc
    for x in range(5):
        for y in range(5):
            state[8 * (x + 5 * y):8 * (x + 5 * y) + 8] = \
                a[x][y].to_bytes(8, "little")


# --- STROBE-128 (the subset merlin uses: AD, meta-AD, PRF) -------------------

_FLAG_I, _FLAG_A, _FLAG_C, _FLAG_T, _FLAG_M, _FLAG_K = (
    1, 2, 4, 8, 16, 32)
_STROBE_R = 166  # rate for sec=128 over keccak-f1600, minus 2 pad bytes


class Strobe128:
    def __init__(self, protocol: bytes):
        self.state = bytearray(200)
        seed = bytes([1, _STROBE_R + 2, 1, 0, 1, 96]) + b"STROBEv1.0.2"
        self.state[:len(seed)] = seed
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0
        self.cur_flags = 0
        self.meta_ad(protocol, False)

    def _run_f(self) -> None:
        self.state[self.pos] ^= self.pos_begin
        self.state[self.pos + 1] ^= 0x04
        self.state[_STROBE_R + 1] ^= 0x80
        keccak_f1600(self.state)
        self.pos = 0
        self.pos_begin = 0

    def _absorb(self, data: bytes) -> None:
        for byte in data:
            self.state[self.pos] ^= byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()

    def _squeeze(self, n: int) -> bytes:
        out = bytearray()
        for _ in range(n):
            out.append(self.state[self.pos])
            self.state[self.pos] = 0
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()
        return bytes(out)

    def _begin_op(self, flags: int, more: bool) -> None:
        if more:
            assert self.cur_flags == flags
            return
        assert not (flags & _FLAG_T), "transport ops unused"
        old_begin = self.pos_begin
        self.pos_begin = self.pos + 1
        self.cur_flags = flags
        self._absorb(bytes([old_begin, flags]))
        force_f = flags & (_FLAG_C | _FLAG_K)
        if force_f and self.pos != 0:
            self._run_f()

    def meta_ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_M | _FLAG_A, more)
        self._absorb(data)

    def ad(self, data: bytes, more: bool) -> None:
        self._begin_op(_FLAG_A, more)
        self._absorb(data)

    def prf(self, n: int, more: bool = False) -> bytes:
        self._begin_op(_FLAG_I | _FLAG_A | _FLAG_C, more)
        return self._squeeze(n)

    def key(self, data: bytes, more: bool = False) -> None:
        self._begin_op(_FLAG_A | _FLAG_C, more)
        # KEY overwrites (duplex with C): absorb-as-overwrite
        for byte in data:
            self.state[self.pos] = byte
            self.pos += 1
            if self.pos == _STROBE_R:
                self._run_f()


# --- Merlin transcript --------------------------------------------------------

class Transcript:
    def __init__(self, label: bytes):
        self.strobe = Strobe128(b"Merlin v1.0")
        self.append_message(b"dom-sep", label)

    def append_message(self, label: bytes, message: bytes) -> None:
        self.strobe.meta_ad(label
                            + len(message).to_bytes(4, "little"), False)
        self.strobe.ad(message, False)

    def append_u64(self, label: bytes, n: int) -> None:
        self.append_message(label, n.to_bytes(8, "little"))

    def challenge_bytes(self, label: bytes, n: int) -> bytes:
        self.strobe.meta_ad(label + n.to_bytes(4, "little"), False)
        return self.strobe.prf(n)

    def challenge_scalar(self, label: bytes) -> int:
        return int.from_bytes(self.challenge_bytes(label, 64),
                              "little") % ed.L

    def witness_bytes(self, label: bytes, nonce_seed: bytes,
                      n: int = 32) -> bytes:
        """Deterministic witness (schnorrkel witness_bytes with no
        external rng): fork the transcript, key in the nonce seed."""
        fork = Strobe128(b"Merlin v1.0")
        fork.state = bytearray(self.strobe.state)
        fork.pos = self.strobe.pos
        fork.pos_begin = self.strobe.pos_begin
        fork.cur_flags = self.strobe.cur_flags
        fork.meta_ad(label, False)
        fork.key(nonce_seed)
        return fork.prf(n)


# --- ristretto255 (over the edwards25519 oracle) ------------------------------

_D = ed.D
_P = ed.P
_SQRT_M1 = ed.SQRT_M1
_INVSQRT_A_MINUS_D = pow(
    (-1 - _D) % _P, (_P - 3) // 4, _P)  # placeholder; computed below


def _sqrt_ratio(u: int, v: int) -> Tuple[bool, int]:
    """sqrt(u/v) per ristretto: returns (was_square, root)."""
    v3 = v * v % _P * v % _P
    v7 = v3 * v3 % _P * v % _P
    r = u * v3 % _P * pow(u * v7 % _P, (_P - 5) // 8, _P) % _P
    check = v * r % _P * r % _P
    if check == u % _P:
        return True, min(r, _P - r)
    if check == (-u) % _P:
        r = r * _SQRT_M1 % _P
        return True, min(r, _P - r)
    if check == (-u * _SQRT_M1) % _P:
        r = r * _SQRT_M1 % _P
        return False, min(r, _P - r)
    return False, min(r, _P - r)


def ristretto_decode(b: bytes) -> Optional[tuple]:
    """32 bytes -> internal extended edwards point, or None."""
    if len(b) != 32:
        return None
    s = int.from_bytes(b, "little")
    if s >= _P or (s & 1):  # canonical and non-negative
        return None
    ss = s * s % _P
    u1 = (1 - ss) % _P
    u2 = (1 + ss) % _P
    u2_sqr = u2 * u2 % _P
    v = (-(_D * u1 % _P * u1) - u2_sqr) % _P
    ok, invsqrt = _sqrt_ratio(1, v * u2_sqr % _P)
    if not ok:
        return None
    den_x = invsqrt * u2 % _P
    den_y = invsqrt * den_x % _P * v % _P
    x = (s + s) % _P * den_x % _P
    if x % 2 == 1:
        x = _P - x
    y = u1 * den_y % _P
    t = x * y % _P
    # spec: reject when t is negative or y is zero — without the t check
    # two distinct byte strings decode to the same element (canonical
    # encoding is ristretto's whole point)
    if y == 0 or t % 2 == 1:
        return None
    return (x, y, 1, t)


def ristretto_encode(pt: tuple) -> bytes:
    """internal extended point -> canonical 32 bytes."""
    x0, y0, z0, t0 = pt
    u1 = (z0 + y0) * (z0 - y0) % _P
    u2 = x0 * y0 % _P
    _, invsqrt = _sqrt_ratio(1, u1 * u2 % _P * u2 % _P)
    den1 = invsqrt * u1 % _P
    den2 = invsqrt * u2 % _P
    z_inv = den1 * den2 % _P * t0 % _P
    ix = x0 * _SQRT_M1 % _P
    iy = y0 * _SQRT_M1 % _P
    enchanted = den1 * _INVSQRT_A_MINUS_D % _P
    rotate = (t0 * z_inv % _P) % 2 == 1
    if rotate:
        x, y = iy, ix
        den_inv = enchanted
    else:
        x, y = x0, y0
        den_inv = den2
    if (x * z_inv % _P) % 2 == 1:
        y = (-y) % _P
    s = (z0 - y) * den_inv % _P
    if s % 2 == 1:
        s = (-s) % _P
    return s.to_bytes(32, "little")


def _compute_invsqrt_a_minus_d() -> int:
    a_minus_d = (-1 - _D) % _P
    ok, r = _sqrt_ratio(1, a_minus_d)
    assert ok
    return r


_INVSQRT_A_MINUS_D = _compute_invsqrt_a_minus_d()


# --- Schnorr (schnorrkel layout) ---------------------------------------------

def _signing_transcript(context: bytes, msg: bytes, pub: bytes,
                        r_enc: Optional[bytes]) -> Transcript:
    t = Transcript(b"SigningContext")
    t.append_message(b"", context)
    t.append_message(b"sign-bytes", msg)
    t.append_message(b"proto-name", b"Schnorr-sig")
    t.append_message(b"sign:pk", pub)
    if r_enc is not None:
        t.append_message(b"sign:R", r_enc)
    return t


@dataclass(frozen=True)
class Sr25519PubKey:
    raw: bytes  # ristretto255 compressed

    def __post_init__(self):
        if len(self.raw) != 32:
            raise ValueError("sr25519 pubkey must be 32 bytes")

    def address(self) -> bytes:
        return hashlib.sha256(self.raw).digest()[:20]

    def bytes_(self) -> bytes:
        return self.raw

    def type_(self) -> str:
        return SR25519_KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes,
                         context: bytes = SIGNING_CTX) -> bool:
        if len(sig) != 64:
            return False
        if not (sig[63] & 0x80):
            return False  # schnorrkel marker bit required
        s_bytes = sig[32:63] + bytes([sig[63] & 0x7F])
        s = int.from_bytes(s_bytes, "little")
        if s >= ed.L:
            return False
        r_enc = sig[:32]
        r_pt = ristretto_decode(r_enc)
        a_pt = ristretto_decode(self.raw)
        if r_pt is None or a_pt is None:
            return False
        t = _signing_transcript(context, msg, self.raw, r_enc)
        k = t.challenge_scalar(b"sign:c")
        # [s]B == R + [k]A  (torsion-free in ristretto: exact equation)
        sb = ed.pt_mul(s, ed.BASE)
        rhs = ed.pt_add(r_pt, ed.pt_mul(k, a_pt))
        return ristretto_encode(sb) == ristretto_encode(rhs)


@dataclass(frozen=True)
class Sr25519PrivKey:
    key: bytes        # 32-byte scalar seed
    nonce: bytes      # 32-byte nonce seed

    @classmethod
    def generate(cls, rng=None) -> "Sr25519PrivKey":
        import secrets
        if rng is None:
            return cls(secrets.token_bytes(32), secrets.token_bytes(32))
        return cls(bytes(rng.randrange(256) for _ in range(32)),
                   bytes(rng.randrange(256) for _ in range(32)))

    @classmethod
    def from_mini_secret(cls, seed: bytes) -> "Sr25519PrivKey":
        """schnorrkel MiniSecretKey ExpandMode::Ed25519 (the substrate
        default): scalar = ed25519-clamp(sha512(seed)[:32]) divided by
        the cofactor, nonce = sha512(seed)[32:]. Pinned against the
        public wasm-crypto derivation vector in tests/test_curves.py."""
        if len(seed) != 32:
            raise ValueError("mini secret must be 32 bytes")
        h = hashlib.sha512(seed).digest()
        key = bytearray(h[:32])
        key[0] &= 248
        key[31] &= 63
        key[31] |= 64
        scalar = int.from_bytes(bytes(key), "little") >> 3
        return cls(scalar.to_bytes(32, "little"), h[32:64])

    def _scalar(self) -> int:
        return int.from_bytes(self.key, "little") % ed.L

    def pub_key(self) -> Sr25519PubKey:
        return Sr25519PubKey(
            ristretto_encode(ed.pt_mul(self._scalar(), ed.BASE)))

    def bytes_(self) -> bytes:
        return self.key + self.nonce

    def type_(self) -> str:
        return SR25519_KEY_TYPE

    def sign(self, msg: bytes, context: bytes = SIGNING_CTX) -> bytes:
        d = self._scalar()
        pub = self.pub_key().raw
        t = _signing_transcript(context, msg, pub, None)
        r = int.from_bytes(
            t.witness_bytes(b"signing", self.nonce, 64), "little") % ed.L
        r_enc = ristretto_encode(ed.pt_mul(r, ed.BASE))
        t.append_message(b"sign:R", r_enc)
        k = t.challenge_scalar(b"sign:c")
        s = (k * d + r) % ed.L
        s_bytes = bytearray(s.to_bytes(32, "little"))
        s_bytes[31] |= 0x80  # schnorrkel format marker
        return r_enc + bytes(s_bytes)


class Sr25519BatchVerifier:
    """Batch verifier (reference crypto/sr25519/batch.go:44-77).

    Random-linear-combination over the Schnorr equations:
      Σ z_i·s_i · B  ==  Σ z_i·R_i + Σ (z_i·k_i)·A_i
    computed on the host oracle (sr25519 is not the consensus hot path;
    volume rides the ed25519 TPU kernel)."""

    def __init__(self):
        self._items: List[Tuple[Sr25519PubKey, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._items)

    def add(self, pk, msg: bytes, sig: bytes) -> None:
        if pk.type_() != SR25519_KEY_TYPE:
            raise TypeError(f"sr25519 batch got {pk.type_()} key")
        self._items.append((pk, msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        import secrets
        if not self._items:
            return False, []
        lhs_scalar = 0
        rhs = None
        parsed = []
        for pk, msg, sig in self._items:
            if len(sig) != 64 or not (sig[63] & 0x80):
                parsed.append(None)
                continue
            s = int.from_bytes(sig[32:63] + bytes([sig[63] & 0x7F]),
                               "little")
            r_pt = ristretto_decode(sig[:32])
            a_pt = ristretto_decode(pk.raw)
            if s >= ed.L or r_pt is None or a_pt is None:
                parsed.append(None)
                continue
            t = _signing_transcript(SIGNING_CTX, msg, pk.raw, sig[:32])
            k = t.challenge_scalar(b"sign:c")
            parsed.append((s, r_pt, a_pt, k))
        if any(p is None for p in parsed):
            oks = [self._items[i][0].verify_signature(
                self._items[i][1], self._items[i][2])
                if parsed[i] is not None else False
                for i in range(len(self._items))]
            return all(oks), oks
        for s, r_pt, a_pt, k in parsed:
            z = int.from_bytes(secrets.token_bytes(16), "little")
            lhs_scalar = (lhs_scalar + z * s) % ed.L
            term = ed.pt_add(r_pt, ed.pt_mul(k, a_pt))
            zterm = ed.pt_mul(z, term)
            rhs = zterm if rhs is None else ed.pt_add(rhs, zterm)
        lhs = ed.pt_mul(lhs_scalar, ed.BASE)
        if ristretto_encode(lhs) == ristretto_encode(rhs):
            return True, [True] * len(self._items)
        oks = [pk.verify_signature(msg, sig)
               for pk, msg, sig in self._items]
        return all(oks), oks
