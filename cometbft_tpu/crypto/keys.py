"""Key interfaces and the ed25519 implementation.

Mirrors the reference plugin surface (crypto/crypto.go:22-54: PubKey,
PrivKey, BatchVerifier) so every call site — vote verification, commit
batch verification, light client — goes through the same seam the
reference uses, with the TPU kernel slotted in behind it
(crypto/batch/batch.go:11-35 is re-created in `batch.py`).

Single-signature verification uses ZIP-215 semantics, identical to the
batch path (reference crypto/ed25519/ed25519.go:181-188) — verdict parity
between single and batch verification is what makes batch-failure
attribution sound.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Protocol, Sequence, Tuple, runtime_checkable

from . import ref_ed25519 as ref

ADDRESS_SIZE = 20  # reference crypto/tmhash/hash.go:78 (sha256, truncated)

ED25519_KEY_TYPE = "ed25519"

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PublicKey as _CEd25519PublicKey)
    from cryptography.exceptions import InvalidSignature as _CInvalidSig

    def _native_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
        if len(sig) != 64 or len(pub) != 32:
            return False
        try:
            _CEd25519PublicKey.from_public_bytes(pub).verify(sig, msg)
            return True
        except (_CInvalidSig, ValueError):
            return False
except ImportError:  # pragma: no cover
    def _native_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
        return False


def address_from_pubkey_bytes(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()[:ADDRESS_SIZE]


@runtime_checkable
class PubKey(Protocol):
    def address(self) -> bytes: ...
    def bytes_(self) -> bytes: ...
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...
    def type_(self) -> str: ...


@runtime_checkable
class PrivKey(Protocol):
    def sign(self, msg: bytes) -> bytes: ...
    def pub_key(self) -> PubKey: ...
    def bytes_(self) -> bytes: ...
    def type_(self) -> str: ...


class BatchVerifier(Protocol):
    """reference crypto/crypto.go:46-54."""

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None: ...
    def verify(self) -> Tuple[bool, List[bool]]: ...


@dataclass(frozen=True)
class Ed25519PubKey:
    raw: bytes

    def __post_init__(self):
        if len(self.raw) != 32:
            raise ValueError(f"ed25519 pubkey must be 32B, got {len(self.raw)}")

    def address(self) -> bytes:
        return address_from_pubkey_bytes(self.raw)

    def bytes_(self) -> bytes:
        return self.raw

    def type_(self) -> str:
        return ED25519_KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """Single-signature ZIP-215 verify — the consensus addVote hot
        path (reference types/vote.go:235, crypto/ed25519/ed25519.go:181).

        Fast path: the native C verifier (~50µs). It implements strict
        cofactorless RFC 8032, which ACCEPTS a strict subset of ZIP-215:
        an accept is always ZIP-215-valid (the cofactorless equation
        implies the cofactored one; s<L and point validity are enforced),
        but a reject may still be ZIP-215-valid (non-canonical encodings,
        small-order/mixed-order components), so rejects re-check against
        the full ZIP-215 oracle. Honest traffic never hits the slow path.
        """
        fast = _native_verify(self.raw, msg, sig)
        if fast:
            return True
        return ref.verify(self.raw, msg, sig, zip215=True)


@dataclass(frozen=True)
class Ed25519PrivKey:
    seed: bytes

    def __post_init__(self):
        if len(self.seed) != 32:
            raise ValueError("ed25519 seed must be 32B")

    @classmethod
    def generate(cls, rng=None) -> "Ed25519PrivKey":
        import secrets
        return cls(secrets.token_bytes(32) if rng is None
                   else bytes(rng.randrange(256) for _ in range(32)))

    def sign(self, msg: bytes) -> bytes:
        # fast native signer when available; identical RFC 8032 output
        try:
            from cryptography.hazmat.primitives.asymmetric.ed25519 import (
                Ed25519PrivateKey)
            return Ed25519PrivateKey.from_private_bytes(self.seed).sign(msg)
        except ImportError:  # pragma: no cover
            return ref.sign(self.seed, msg)

    def pub_key(self) -> Ed25519PubKey:
        return Ed25519PubKey(ref.pubkey_from_seed(self.seed))

    def bytes_(self) -> bytes:
        return self.seed

    def type_(self) -> str:
        return ED25519_KEY_TYPE


class Ed25519BatchVerifier:
    """Accumulate-and-flush batch verifier backed by the TPU kernel
    (replaces curve25519-voi's CPU batch, reference
    crypto/ed25519/ed25519.go:208-241).

    Unlike the reference — whose batch returns one bool plus a per-sig
    attribution vector only on failure — the lane-parallel kernel always
    produces per-signature verdicts, so `verify()` is exact attribution
    with no fallback re-verification pass (types/validation.go:306-315).
    """

    def __init__(self, batch_size: Optional[int] = None):
        self._pubs: List[bytes] = []
        self._msgs: List[bytes] = []
        self._sigs: List[bytes] = []
        self._batch_size = batch_size

    def __len__(self) -> int:
        return len(self._pubs)

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        if pk.type_() != ED25519_KEY_TYPE:
            raise TypeError(f"ed25519 batch verifier got {pk.type_()} key")
        self._pubs.append(pk.bytes_())
        self._msgs.append(msg)
        self._sigs.append(sig)

    def verify(self) -> Tuple[bool, List[bool]]:
        if not self._pubs:
            return False, []
        n = len(self._pubs)
        eff = self._batch_size or 1 << (n - 1).bit_length()
        from ..libs.jax_cache import is_device_platform, ledger
        if not is_device_platform() and eff > 64 \
                and not ledger().warm_in_process("ed25519-rlc", eff):
            # CPU backend: jitting the RLC kernel at batch >= 256
            # takes minutes and can crash the XLA:CPU compiler
            # (docs/PERF.md); a >64-lane flush on a CPU node runs the
            # native per-sig verify instead — the same clamp blocksync
            # applies (engine/blocksync.py:79-89). The clamp LIFTS
            # when this process already compiled the bucket (node
            # prewarm, or an earlier flush through this verifier): the
            # warm jit cache makes the wide kernel the cheaper path
            # (ROADMAP item-5 residual). Process-local warmth only —
            # XLA:CPU executables are never persisted, so another
            # process's ledger entry predicts a full recompile, not a
            # reload (libs/jax_cache.warm_in_process).
            oks = [Ed25519PubKey(p).verify_signature(m, s)
                   for p, m, s in zip(self._pubs, self._msgs,
                                      self._sigs)]
            return all(oks), oks
        from ..ops.ed25519 import verify_batch
        with ledger().compile_guard("ed25519-rlc", eff):
            out = verify_batch(self._pubs, self._msgs, self._sigs,
                               batch_size=self._batch_size)
        oks = [bool(v) for v in out]
        return all(oks), oks


def privkey_from_type_bytes(key_type: str, raw: bytes) -> PrivKey:
    """Private-key factory by wire type string — the decode side of
    FilePV state files, which persist (type, raw) so a BLS validator
    key round-trips as BLS instead of being re-typed ed25519."""
    if key_type == ED25519_KEY_TYPE:
        return Ed25519PrivKey(raw)
    if key_type == "bls12_381":
        from .bls12381 import Bls12381PrivKey
        return Bls12381PrivKey(raw)
    raise ValueError(f"unsupported privval key type {key_type!r}")


def pubkey_from_type_bytes(key_type: str, raw: bytes) -> PubKey:
    """Key factory by wire type string (reference
    crypto/encoding/codec.go:119 PubKeyFromTypeAndBytes)."""
    if key_type == ED25519_KEY_TYPE:
        return Ed25519PubKey(raw)
    if key_type == "secp256k1":
        from .secp256k1 import Secp256k1PubKey
        return Secp256k1PubKey(raw)
    if key_type == "sr25519":
        from .sr25519 import Sr25519PubKey
        return Sr25519PubKey(raw)
    if key_type == "bls12_381":
        # pure-Python curve (reference gates this type behind a blst
        # build tag, crypto/bls12381/key_bls12381.go:1)
        from .bls12381 import Bls12381PubKey
        return Bls12381PubKey(raw)
    raise ValueError(f"unknown key type {key_type!r}")
