"""Pure-Python BLS12-381 key type (reference
crypto/bls12381/key_bls12381.go + const.go — there the curve rides the
cosmos/crypto cgo wrapper over blst behind a build tag; this
environment has no blst, so the curve is implemented from scratch:
VERDICT r4 missing #9, the last unimplemented component rows).

Scope and compatibility:

- Key/signature SHAPES and semantics match the reference exactly:
  32-byte secret scalars, 48-byte compressed G1 public keys, 96-byte
  compressed G2 signatures (ZCash serialization: compression/infinity/
  sign bits in the top three bits of the first byte), address =
  sha256(pubkey)[:20] (tmhash.SumTruncated), and messages longer than
  32 bytes are sha256-hashed before signing
  (key_bls12381.go:84-97,122-144 Sign/VerifySignature).
- The PAIRING is the real thing: optimal-ate-style Miller loop over
  the Fq12 tower with a full final exponentiation — verification is
  e(g1, sig) == e(pk, H(m)) with subgroup checks on deserialization.
- Two DOCUMENTED interop deviations (both local to the sign path;
  verification of our own signatures is self-consistent):
  1. HASH-TO-CURVE: expand_message_xmd (RFC 9380 §5.3.1, SHA-256)
     feeding a deterministic try-and-increment map onto the twist,
     then cofactor clearing — NOT the IETF SSWU suite. The SSWU
     3-isogeny constant tables cannot be transcribed here with
     confidence and no blst/py_ecc exists in the image to validate
     them against; a sound, deterministic, constant-documented map
     keeps the scheme secure (hash outputs are indistinguishable from
     random curve points) at the cost of signature interop with
     Ethereum-suite signers. Swapping `hash_to_g2` for SSWU restores
     byte interop without touching anything else.
  2. SHORT-MESSAGE PADDING: messages of at most 32 bytes are
     zero-padded to exactly 32 before hashing to the curve
     (`_fixed_msg`). The reference passes short messages to blst as
     raw bytes, unpadded — so even with SSWU in place, signatures
     over messages shorter than 32 bytes would differ from the
     reference's, and messages differing only in trailing zero bytes
     within the 32-byte window sign identically here. Consensus
     messages are always longer than 32 bytes (sha256-hashed on both
     sides), so the divergence is confined to short ad-hoc payloads.

Everything derivable is DERIVED from the curve parameter x (checked at
import): r = x^4 - x^2 + 1, p = (x-1)^2/3·r + x, G1 cofactor
(x-1)^2/3, and the twist cofactor from the sextic-twist order
p^2 + 1 - (t2 - 3f2)/2 (t2 = t^2-2p, 3f2^2 = 4p^2-t2^2) — pinned by
tests multiplying random curve points to infinity.

Performance: a verify costs two pairings ≈ seconds in pure Python.
This key type exists for validator-key compatibility coverage, not the
hot path (the reference gates it behind a build tag for the same
reason); consensus ed25519 remains the TPU-accelerated path.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Optional, Tuple

from ..libs.env import env_int

# --- parameters (identities asserted below) -----------------------------------

X_PARAM = -0xD201000000010000
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

assert R == X_PARAM**4 - X_PARAM**2 + 1
assert P == (X_PARAM - 1) ** 2 // 3 * R + X_PARAM

H1 = (X_PARAM - 1) ** 2 // 3                  # G1 cofactor
_T = X_PARAM + 1                              # trace of Frobenius
_T2 = _T * _T - 2 * P
_F2 = __import__("math").isqrt((4 * P * P - _T2 * _T2) // 3)
assert 3 * _F2 * _F2 == 4 * P * P - _T2 * _T2
_N2 = P * P + 1 - (_T2 - 3 * _F2) // 2        # sextic M-twist order
assert _N2 % R == 0
H2 = _N2 // R                                 # twist cofactor

KEY_TYPE = "bls12_381"                        # const.go KeyType
PRIV_KEY_SIZE = 32
PUB_KEY_SIZE = 48
SIGNATURE_LENGTH = 96
MAX_MSG_LEN = 32

# --- Fq and Fq2 ---------------------------------------------------------------

def _inv(a: int) -> int:
    return pow(a, P - 2, P)


F2 = Tuple[int, int]                          # a0 + a1*u, u^2 = -1


def f2(a0: int, a1: int = 0) -> F2:
    return (a0 % P, a1 % P)


F2_ZERO, F2_ONE = (0, 0), (1, 0)
XI = (1, 1)                                   # Fq6 non-residue 1+u


def f2_add(a: F2, b: F2) -> F2:
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a: F2, b: F2) -> F2:
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a: F2) -> F2:
    return ((-a[0]) % P, (-a[1]) % P)


def f2_mul(a: F2, b: F2) -> F2:
    return ((a[0] * b[0] - a[1] * b[1]) % P,
            (a[0] * b[1] + a[1] * b[0]) % P)


def f2_sq(a: F2) -> F2:
    return f2_mul(a, a)


def f2_inv(a: F2) -> F2:
    d = _inv(a[0] * a[0] + a[1] * a[1])
    return (a[0] * d % P, (-a[1]) * d % P)


def f2_pow(a: F2, e: int) -> F2:
    out = F2_ONE
    while e:
        if e & 1:
            out = f2_mul(out, a)
        a = f2_sq(a)
        e >>= 1
    return out


def fq_sqrt(a: int) -> Optional[int]:
    """p ≡ 3 (mod 4): sqrt = a^((p+1)/4), checked."""
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a % P else None


def f2_sqrt(a: F2) -> Optional[F2]:
    """Complex method for p ≡ 3 (mod 4); returns None for non-squares."""
    a0, a1 = a
    if a1 == 0:
        s = fq_sqrt(a0)
        if s is not None:
            return (s, 0)
        s = fq_sqrt(-a0 % P)
        return None if s is None else (0, s)
    alpha = fq_sqrt((a0 * a0 + a1 * a1) % P)
    if alpha is None:
        return None
    delta = (a0 + alpha) * _inv(2) % P
    x0 = fq_sqrt(delta)
    if x0 is None:
        delta = (a0 - alpha) * _inv(2) % P
        x0 = fq_sqrt(delta)
        if x0 is None:
            return None
    x1 = a1 * _inv(2 * x0) % P
    cand = (x0, x1)
    return cand if f2_sq(cand) == a else None


# --- Fq12 tower: Fq12 = Fq2[v]/(v^3 - ξ) [w]/(w^2 - v) ------------------------
# Represented flat: 6 Fq2 coefficients of w^0..w^5 (w^6 = ξ).

F12 = Tuple[F2, F2, F2, F2, F2, F2]
F12_ONE: F12 = (F2_ONE, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO)


def f12_mul(a: F12, b: F12) -> F12:
    acc = [F2_ZERO] * 11
    for i in range(6):
        ai = a[i]
        if ai == F2_ZERO:
            continue
        for j in range(6):
            if b[j] == F2_ZERO:
                continue
            acc[i + j] = f2_add(acc[i + j], f2_mul(ai, b[j]))
    for k in range(10, 5, -1):                # w^6 = ξ
        if acc[k] != F2_ZERO:
            acc[k - 6] = f2_add(acc[k - 6], f2_mul(acc[k], XI))
    return tuple(acc[:6])


def f12_sq(a: F12) -> F12:
    return f12_mul(a, a)


def f12_pow(a: F12, e: int) -> F12:
    out = F12_ONE
    while e:
        if e & 1:
            out = f12_mul(out, a)
        a = f12_sq(a)
        e >>= 1
    return out


# Fq6 helpers for inversion only: Fq6 = Fq2[v]/(v^3 - ξ), and the flat
# w-representation splits as a = A(v) + B(v)·w with w^2 = v, i.e.
# A = (a0, a2, a4), B = (a1, a3, a5) in v-coefficients.

def _f6_mul(a, b):
    c = [F2_ZERO] * 5
    for i in range(3):
        for j in range(3):
            c[i + j] = f2_add(c[i + j], f2_mul(a[i], b[j]))
    return (f2_add(c[0], f2_mul(c[3], XI)),
            f2_add(c[1], f2_mul(c[4], XI)),
            c[2])


def _f6_inv(a):
    """Standard Fq6 inversion: inv = (A, B, C)/F with
    A = c0^2 - ξ c1 c2, B = ξ c2^2 - c0 c1, C = c1^2 - c0 c2,
    F = c0 A + ξ c1 C + ξ c2 B."""
    c0, c1, c2 = a
    A = f2_sub(f2_sq(c0), f2_mul(XI, f2_mul(c1, c2)))
    B = f2_sub(f2_mul(XI, f2_sq(c2)), f2_mul(c0, c1))
    C = f2_sub(f2_sq(c1), f2_mul(c0, c2))
    F = f2_add(f2_mul(c0, A),
               f2_mul(XI, f2_add(f2_mul(c1, C), f2_mul(c2, B))))
    fi = f2_inv(F)
    return (f2_mul(A, fi), f2_mul(B, fi), f2_mul(C, fi))


def _f6_mul_v(a):
    """Multiply by v (v^3 = ξ): (c0, c1, c2) -> (ξ c2, c0, c1)."""
    return (f2_mul(a[2], XI), a[0], a[1])


def f12_inv(a: F12) -> F12:
    """Tower inversion: a = A + B·w, w^2 = v, so
    a^-1 = (A - B·w) / (A^2 - B^2·v)."""
    A = (a[0], a[2], a[4])
    B = (a[1], a[3], a[5])
    den = tuple(f2_sub(x, y) for x, y in
                zip(_f6_mul(A, A), _f6_mul_v(_f6_mul(B, B))))
    di = _f6_inv(den)
    iA = _f6_mul(A, di)
    iB = _f6_mul(tuple(f2_neg(x) for x in B), di)
    return (iA[0], iB[0], iA[1], iB[1], iA[2], iB[2])


# --- curve points (Jacobian over generic field ops) ---------------------------
# G1: y^2 = x^3 + 4 over Fq; G2: y^2 = x^3 + 4(1+u) over Fq2 (M-twist).

B1 = 4
B2 = (4, 4)

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)


class _Curve:
    """Affine short-Weierstrass group law parameterized by the field."""

    def __init__(self, add, sub, mul, sq, inv, neg, b, zero, one,
                 two, three):
        self.add, self.sub, self.mul = add, sub, mul
        self.sq, self.inv, self.neg = sq, inv, neg
        self.b, self.zero, self.one = b, zero, one
        self.two, self.three = two, three

    def on_curve(self, pt) -> bool:
        if pt is None:
            return True
        x, y = pt
        return self.sq(y) == self.add(self.mul(self.sq(x), x), self.b)

    def pt_add(self, p, q):
        if p is None:
            return q
        if q is None:
            return p
        if p[0] == q[0]:
            if p[1] != q[1] or p[1] == self.zero:
                return None
            num = self.mul(self.three, self.sq(p[0]))
            den = self.mul(self.two, p[1])
        else:
            num = self.sub(q[1], p[1])
            den = self.sub(q[0], p[0])
        lam = self.mul(num, self.inv(den))
        x3 = self.sub(self.sub(self.sq(lam), p[0]), q[0])
        return (x3, self.sub(self.mul(lam, self.sub(p[0], x3)), p[1]))

    def pt_neg(self, p):
        return None if p is None else (p[0], self.neg(p[1]))

    def pt_mul_affine(self, k, p):
        """Affine double-and-add — one field inversion PER BIT. Kept as
        the oracle `pt_mul` (Jacobian) is pinned against."""
        acc = None
        while k:
            if k & 1:
                acc = self.pt_add(acc, p)
            p = self.pt_add(p, p)
            k >>= 1
        return acc

    # --- Jacobian scalar multiplication ----------------------------------
    # (X, Y, Z) with x = X/Z^2, y = Y/Z^3. One field inversion for the
    # whole multiplication instead of one per bit: on Fq2 that turns a
    # ~600us-per-bit affine ladder into ~20us-per-bit, which is what
    # makes BLS signing / cofactor clearing / subgroup checks usable in
    # a consensus loop. Equality with pt_mul_affine is property-pinned
    # (tests/test_aggsig.py) for random scalars including group-order
    # multiples (-> infinity).

    def _jac_double(self, P3):
        X1, Y1, Z1 = P3
        mul, sq, add, sub = self.mul, self.sq, self.add, self.sub
        if Y1 == self.zero:
            return None
        A = sq(X1)
        B = sq(Y1)
        C = sq(B)
        D = sub(sub(sq(add(X1, B)), A), C)
        D = add(D, D)
        E = add(add(A, A), A)
        X3 = sub(sq(E), add(D, D))
        C8 = add(C, C)
        C8 = add(C8, C8)
        C8 = add(C8, C8)
        Y3 = sub(mul(E, sub(D, X3)), C8)
        Z3 = mul(add(Y1, Y1), Z1)
        return (X3, Y3, Z3)

    def _jac_add_affine(self, P3, q):
        """Mixed addition: Jacobian accumulator + affine q (q != inf)."""
        mul, sq, sub = self.mul, self.sq, self.sub
        X1, Y1, Z1 = P3
        x2, y2 = q
        Z1Z1 = sq(Z1)
        U2 = mul(x2, Z1Z1)
        S2 = mul(mul(y2, Z1), Z1Z1)
        H = sub(U2, X1)
        R = sub(S2, Y1)
        if H == self.zero:
            if R == self.zero:
                return self._jac_double(P3)
            return None
        HH = sq(H)
        H3 = mul(H, HH)
        V = mul(X1, HH)
        X3 = sub(sub(sq(R), H3), V)
        X3 = sub(X3, V)
        Y3 = sub(mul(R, sub(V, X3)), mul(Y1, H3))
        Z3 = mul(Z1, H)
        return (X3, Y3, Z3)

    def pt_mul(self, k, p):
        if p is None or k == 0:
            return None
        acc = None
        for bit in bin(k)[2:]:
            if acc is not None:
                acc = self._jac_double(acc)
            if bit == "1":
                if acc is None:
                    acc = (p[0], p[1], self.one)
                else:
                    acc = self._jac_add_affine(acc, p)
        if acc is None:
            return None
        X, Y, Z = acc
        zi = self.inv(Z)
        zi2 = self.sq(zi)
        return (self.mul(X, zi2), self.mul(self.mul(Y, zi2), zi))


_fq = _Curve(lambda a, b: (a + b) % P, lambda a, b: (a - b) % P,
             lambda a, b: a * b % P, lambda a: a * a % P, _inv,
             lambda a: -a % P, B1, 0, 1, 2, 3)
_fq2 = _Curve(f2_add, f2_sub, f2_mul, f2_sq, f2_inv, f2_neg, B2,
              F2_ZERO, F2_ONE, (2, 0), (3, 0))
_fq12_two = (F2_ZERO,) * 6
_fq12 = _Curve(
    lambda a, b: tuple(f2_add(x, y) for x, y in zip(a, b)),
    lambda a, b: tuple(f2_sub(x, y) for x, y in zip(a, b)),
    f12_mul, f12_sq, f12_inv,
    lambda a: tuple(f2_neg(x) for x in a),
    ((4, 0), F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO),
    (F2_ZERO,) * 6, F12_ONE,
    ((2, 0), F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO),
    ((3, 0), F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO))


_XI_INV = f2_inv(XI)


def _untwist(q):
    """E'(Fq2) -> E(Fq12): (x', y') -> (x'/w^2, y'/w^3).

    The M-twist satisfies y'^2 = x'^3 + 4ξ; dividing through by w^6 = ξ
    gives (y'/w^3)^2 = (x'/w^2)^3 + 4, i.e. the mapped point lies on
    E(Fq12): y^2 = x^3 + 4. With w^-2 = w^4·ξ^-1 and w^-3 = w^3·ξ^-1,
    the images are single-coefficient Fq12 elements (pinned on-curve by
    tests/test_bls12381.py)."""
    x, y = q
    ex = [F2_ZERO] * 6
    ex[4] = f2_mul(x, _XI_INV)
    ey = [F2_ZERO] * 6
    ey[3] = f2_mul(y, _XI_INV)
    return (tuple(ex), tuple(ey))


def _embed_g1(p):
    x, y = p
    ex = (f2(x), F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO)
    ey = (f2(y), F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO, F2_ZERO)
    return (ex, ey)


# --- pairing ------------------------------------------------------------------

def _line(f_add, f_sub, f_mul, f_sq, f_inv, a, b, px, py):
    """Evaluate the line through a,b (or tangent at a when a==b) at
    (px, py); returns (line_value, a+b). Generic over the field."""
    if a[0] == b[0] and a[1] == b[1]:
        num = f_mul(_fq12.three, f_sq(a[0]))
        den = f_mul(_fq12.two, a[1])
    elif a[0] == b[0]:
        # vertical line x - a.x
        return f_sub(px, a[0]), None
    else:
        num = f_sub(b[1], a[1])
        den = f_sub(b[0], a[0])
    lam = f_mul(num, f_inv(den))
    val = f_sub(f_sub(py, a[1]), f_mul(lam, f_sub(px, a[0])))
    x3 = f_sub(f_sub(f_sq(lam), a[0]), b[0])
    y3 = f_sub(f_mul(lam, f_sub(a[0], x3)), a[1])
    return val, (x3, y3)


# Pairing-op tally for perf attribution (bench.py --aggsig reads the
# deltas): miller_loops is the O(n)-vs-O(1) evidence for aggregate
# commits, final_exps the shared-exponentiation evidence. Counts only —
# never logged from deterministic paths.
OP_COUNTERS = {"miller_loops": 0, "final_exps": 0}


def miller_loop_slow(p_g1, q_g2) -> F12:
    """Miller loop f_{r,Q}(P) over Fq12 with both points embedded.
    Textbook double-and-add over the full group order r — simple,
    slow, and unambiguous (no twist/frobenius shortcuts to get wrong).
    Retained as the oracle the optimal-ate fast path (`miller_loop`)
    is pinned against: both are nondegenerate bilinear pairings after
    final exponentiation, so their `multi_pairing_is_one` verdicts
    are identical (they differ by a fixed exponent coprime to r)."""
    if p_g1 is None or q_g2 is None:
        return F12_ONE
    OP_COUNTERS["miller_loops"] += 1
    px, py = _embed_g1(p_g1)
    q = _untwist(q_g2)
    f = F12_ONE
    t = q
    c = _fq12
    for bit in bin(R)[3:]:
        val, t = _line(c.add, c.sub, c.mul, c.sq, c.inv, t, t, px, py)
        f = f12_mul(f12_sq(f), val)
        if bit == "1":
            val, t = _line(c.add, c.sub, c.mul, c.sq, c.inv, t, q,
                           px, py)
            f = f12_mul(f, val)
    return f


# --- optimal-ate Miller loop (the fast path) ----------------------------------
# The ate pairing loops over the BLS parameter x (64 bits, 6 set bits)
# instead of the 255-bit group order r, with the twist point kept in
# Jacobian coordinates on E'(Fq2) so no step inverts anything — the
# slow oracle's per-bit Fq12 inversion is what made it the host floor.
# x is negative: f_{x,Q} = conj(f_{|x|,Q}) up to factors the final
# exponentiation kills (conj(f)^E = f^{-E} EXACTLY, because
# (conj(f)·f)^E = f^{(p^6+1)·E} and r | p^6+1).

X_ABS = -X_PARAM
_X_BITS = bin(X_ABS)[2:]
MILLER_STEPS = len(_X_BITS) - 1               # 63 doubling steps
MILLER_ADD_STEPS = _X_BITS[1:].count("1")     # 5 addition steps


def f12_conj(a: F12) -> F12:
    """a ↦ a^(p^6): Frobenius^6 is the identity on the Fq2
    coefficients and w^(p^6) = w·ξ^((p^6-1)/6) = -w, so conjugation
    negates the odd-w coefficients (pinned against f12_frobenius
    applied six times by tests)."""
    return (a[0], f2_neg(a[1]), a[2], f2_neg(a[3]), a[4], f2_neg(a[5]))


def f12_mul_sparse035(a: F12, c0: F2, c3: F2, c5: F2) -> F12:
    """Multiply by a line value c0 + c3·w^3 + c5·w^5 — the sparse
    shape every evaluated optimal-ate line takes after untwisting
    (18 Fq2 products instead of f12_mul's 36; dense-vs-sparse
    equivalence is test-pinned)."""
    acc = [F2_ZERO] * 11
    for i in range(6):
        ai = a[i]
        if ai == F2_ZERO:
            continue
        acc[i] = f2_add(acc[i], f2_mul(ai, c0))
        acc[i + 3] = f2_add(acc[i + 3], f2_mul(ai, c3))
        acc[i + 5] = f2_add(acc[i + 5], f2_mul(ai, c5))
    for k in range(10, 5, -1):
        if acc[k] != F2_ZERO:
            acc[k - 6] = f2_add(acc[k - 6], f2_mul(acc[k], XI))
    return tuple(acc[:6])


def _f2_scale(a: F2, s: int) -> F2:
    return (a[0] * s % P, a[1] * s % P)


def prepare_pair_lines(p_g1, q_g2):
    """Evaluated line coefficients for f_{|x|,Q}(P): one entry per
    doubling step, ((c0, c3, c5) doubling line, addition line or None).

    Derivation: the untwist sends (x', y') on the M-twist to
    (x'/w^2, y'/w^3) on E(Fq12), so a twist-side chord/tangent of
    slope λ' evaluates at embedded P = (px, py) to
    py + (λ'x' − y')·ξ^{-1}·w^3 − λ'·px·ξ^{-1}·w^5; scaling by ξ and
    by the Jacobian denominators (Z3·Z1Z1 for the tangent, Z3 for the
    chord) clears every inversion. All scalings are Fq2* factors,
    which the final exponentiation kills ((p^2-1) | (p^12-1)/r).
    Shared by the host fast path and the ops/bls12 kernel marshal."""
    px, py = p_g1
    xq, yq = q_g2
    X, Y, Z = xq, yq, F2_ONE
    out = []
    for bit in _X_BITS[1:]:
        # tangent at T=(X,Y,Z), line scaled by Z3·Z1Z1 (dbl-2009-l)
        A = f2_sq(X)
        B = f2_sq(Y)
        Z1Z1 = f2_sq(Z)
        C = f2_sq(B)
        D = f2_sub(f2_sub(f2_sq(f2_add(X, B)), A), C)
        D = f2_add(D, D)                          # 4·X·Y^2
        E = f2_add(f2_add(A, A), A)               # 3·X^2
        Z3 = f2_mul(f2_add(Y, Y), Z)
        dbl = (_f2_scale(f2_mul(XI, f2_mul(Z3, Z1Z1)), py),
               f2_sub(f2_mul(E, X), f2_add(B, B)),
               _f2_scale(f2_neg(f2_mul(E, Z1Z1)), px))
        X3 = f2_sub(f2_sq(E), f2_add(D, D))
        C8 = f2_add(C, C)
        C8 = f2_add(C8, C8)
        C8 = f2_add(C8, C8)
        Y3 = f2_sub(f2_mul(E, f2_sub(D, X3)), C8)
        X, Y, Z = X3, Y3, Z3
        add = None
        if bit == "1":
            # chord through T and affine Q, anchored at Q, scaled Z3
            Z1Z1 = f2_sq(Z)
            U2 = f2_mul(xq, Z1Z1)
            S2 = f2_mul(yq, f2_mul(Z, Z1Z1))
            H = f2_sub(U2, X)
            Rr = f2_sub(S2, Y)
            Z3 = f2_mul(Z, H)
            add = (_f2_scale(f2_mul(XI, Z3), py),
                   f2_sub(f2_mul(Rr, xq), f2_mul(yq, Z3)),
                   _f2_scale(f2_neg(Rr), px))
            HH = f2_sq(H)
            H3 = f2_mul(H, HH)
            V = f2_mul(X, HH)
            X3 = f2_sub(f2_sub(f2_sq(Rr), H3), f2_add(V, V))
            Y3 = f2_sub(f2_mul(Rr, f2_sub(V, X3)), f2_mul(Y, H3))
            X, Y, Z = X3, Y3, Z3
        out.append((dbl, add))
    return out


def miller_loop(p_g1, q_g2) -> F12:
    """Optimal-ate Miller loop f_{x,Q}(P): 63 inversion-free Jacobian
    doubling steps + 5 additions over |x| = 0xd201000000010000, sparse
    line multiplications, final conjugation for the negative x.
    Final-exponentiation-equal to the slow |x|-loop over the generic
    embedded machinery, and verdict-equivalent to the r-loop oracle
    (`miller_loop_slow`) — both pinned by tests."""
    if p_g1 is None or q_g2 is None:
        return F12_ONE
    return miller_product([(p_g1, q_g2)])


_FINAL_EXP = (P**12 - 1) // R


def pairing(p_g1, q_g2) -> F12:
    """e(P, Q) = miller(P, Q)^((p^12-1)/r). Full-exponent final
    exponentiation: ~4300 Fq12 squarings, correct by construction."""
    return f12_pow(miller_loop(p_g1, q_g2), _FINAL_EXP)


# --- fast final exponentiation + multi-pairing --------------------------------
# (p^12-1)/r = (p^6-1) · (p^2+1) · (p^4-p^2+1)/r: the first two factors
# (the "easy part") are one inversion plus Frobenius maps, leaving a
# ~1270-bit pow instead of the monolithic ~4310-bit one — ~3.4x fewer
# Fq12 operations. final_exponentiation == f12_pow(·, _FINAL_EXP) is
# property-pinned by tests/test_aggsig.py on real Miller outputs.

assert (P - 1) % 6 == 0
_FROB_GAMMA = tuple(f2_pow(XI, i * (P - 1) // 6) for i in range(6))

_HARD_EXP = (P**4 - P**2 + 1) // R
assert _HARD_EXP * R == P**4 - P**2 + 1
assert (P**6 - 1) * (P**2 + 1) * _HARD_EXP == _FINAL_EXP


def f2_conj(a: F2) -> F2:
    """Frobenius on Fq2 (p-th power) is conjugation: a0 + a1·u with
    u^2 = -1 maps to a0 - a1·u."""
    return (a[0], (-a[1]) % P)


def f12_frobenius(a: F12) -> F12:
    """a ↦ a^p on the flat w-basis: coefficient-wise Fq2 conjugation,
    then w^i picks up ξ^{i(p-1)/6} (w^p = w·(w^6)^{(p-1)/6} = w·ξ^{(p-1)/6}).
    Pinned against f12_pow(a, P) by tests."""
    return tuple(f2_mul(f2_conj(c), _FROB_GAMMA[i])
                 for i, c in enumerate(a))


def final_exp_easy(f: F12) -> F12:
    """The (p^6-1)(p^2+1) "easy part": one inversion plus Frobenius
    maps. Split out so the batched kernel (ops/bls12) can take over at
    the hard part — the fixed-exponent pow that is pure mul/square and
    therefore lane-parallel."""
    m = f
    for _ in range(6):                       # f^(p^6)
        m = f12_frobenius(m)
    m = f12_mul(m, f12_inv(f))               # f^(p^6-1)
    return f12_mul(f12_frobenius(f12_frobenius(m)), m)   # ^(p^2+1)


def final_exponentiation(f: F12) -> F12:
    """f^((p^12-1)/r) via the easy/hard split above."""
    OP_COUNTERS["final_exps"] += 1
    return f12_pow(final_exp_easy(f), _HARD_EXP)


def miller_product_slow(pairs) -> F12:
    """Product of slow-oracle (r-loop) Miller loops over (P_g1, Q_g2)
    pairs. Retained as the oracle bench.py --miller-backend=oracle and
    the fast-vs-slow verdict tests run against."""
    out = F12_ONE
    for p_g1, q_g2 in pairs:
        out = f12_mul(out, miller_loop_slow(p_g1, q_g2))
    return out


def miller_product(pairs) -> F12:
    """Product of optimal-ate Miller loops over (P_g1, Q_g2) pairs —
    the shared part of a multi-pairing check (one final exponentiation
    serves all of them) — with the per-step Fq12 squaring SHARED
    across pairs: one f12_sq per parameter bit regardless of pair
    count, which the per-pair slow oracle cannot express."""
    live = [(p, q) for p, q in pairs if p is not None and q is not None]
    if not live:
        return F12_ONE
    OP_COUNTERS["miller_loops"] += len(live)
    prepared = [prepare_pair_lines(p, q) for p, q in live]
    f = F12_ONE
    for step in range(MILLER_STEPS):
        f = f12_sq(f)
        for lines in prepared:
            dbl, add = lines[step]
            f = f12_mul_sparse035(f, *dbl)
            if add is not None:
                f = f12_mul_sparse035(f, *add)
    return f12_conj(f)


def multi_pairing_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 with ONE shared final exponentiation —
    the aggregate-verification primitive. A two-pairing equality
    e(a,b) == e(c,d) is multi_pairing_is_one([(-a, b), (c, d)])."""
    return final_exponentiation(miller_product(pairs)) == F12_ONE


G1_NEG = (G1_GEN[0], P - G1_GEN[1])


# --- serialization (ZCash format, as blst/cosmos-crypto emit) -----------------

def g1_compress(p) -> bytes:
    if p is None:
        return bytes([0xC0]) + bytes(47)
    x, y = p
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= 0x80
    if y > (P - 1) // 2:
        out[0] |= 0x20
    return bytes(out)


def g1_decompress(b: bytes):
    if len(b) != 48 or not b[0] & 0x80:
        raise ValueError("bad G1 encoding")
    if b[0] & 0x40:
        if any(b[1:]) or b[0] != 0xC0:
            raise ValueError("bad G1 infinity")
        return None
    sign = bool(b[0] & 0x20)
    x = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = fq_sqrt((x * x % P * x + B1) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if (y > (P - 1) // 2) != sign:
        y = P - y
    pt = (x, y)
    if _fq.pt_mul(R, pt) is not None:
        raise ValueError("G1 point not in subgroup")
    return pt


def _g2_y_is_larger(y: F2) -> bool:
    """Lexicographic on (c1, c0) against the negation."""
    y0, y1 = y
    n0, n1 = (-y0) % P, (-y1) % P
    return (y1, y0) > (n1, n0)


def g2_compress(q) -> bytes:
    if q is None:
        return bytes([0xC0]) + bytes(95)
    (x0, x1), y = q
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= 0x80
    if _g2_y_is_larger(y):
        out[0] |= 0x20
    return bytes(out)


def g2_decompress(b: bytes):
    if len(b) != 96 or not b[0] & 0x80:
        raise ValueError("bad G2 encoding")
    if b[0] & 0x40:
        if any(b[1:]) or b[0] != 0xC0:
            raise ValueError("bad G2 infinity")
        return None
    sign = bool(b[0] & 0x20)
    x1 = int.from_bytes(bytes([b[0] & 0x1F]) + b[1:48], "big")
    x0 = int.from_bytes(b[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(f2_sq(x), x), B2))
    if y is None:
        raise ValueError("G2 x not on curve")
    if _g2_y_is_larger(y) != sign:
        y = f2_neg(y)
    pt = (x, y)
    if _fq2.pt_mul(R, pt) is not None:
        raise ValueError("G2 point not in subgroup")
    return pt


# --- hash to G2 (documented non-IETF map; module docstring) -------------------

def expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256 (this part IS the standard)."""
    if len(dst) > 255:
        raise ValueError("DST too long")
    b_in_bytes, r_in_bytes = 32, 64
    ell = (length + b_in_bytes - 1) // b_in_bytes
    if ell > 255:
        raise ValueError("length too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(r_in_bytes)
    l_i_b = length.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bs = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        prev = bytes(a ^ c for a, c in zip(b0, bs[-1]))
        bs.append(hashlib.sha256(prev + bytes([i]) + dst_prime).digest())
    return b"".join(bs)[:length]


DST = b"COMETBFT_TPU_BLS_SIG_BLS12381G2_XMD:SHA-256_TAI_RO_"


def hash_to_g2(msg: bytes):
    """Deterministic hash onto the r-torsion of the twist: xmd-expand
    to an Fq2 x-candidate + sign bit, increment a counter until x lands
    on the curve, clear the cofactor. Not the IETF SSWU suite (see
    module docstring); constant-time properties are NOT claimed (the
    verify path hashes public data only)."""
    for ctr in range(256):
        uni = expand_message_xmd(msg + bytes([ctr]), DST, 129)
        x0 = int.from_bytes(uni[:64], "big") % P
        x1 = int.from_bytes(uni[64:128], "big") % P
        x = (x0, x1)
        y = f2_sqrt(f2_add(f2_mul(f2_sq(x), x), B2))
        if y is None:
            continue
        if uni[128] & 1:
            y = f2_neg(y)
        pt = _fq2.pt_mul(H2, (x, y))
        if pt is not None:
            return pt
    raise ValueError("hash_to_g2 failed (probability ~2^-256)")


# Explicit LRU with a hard cap instead of functools.lru_cache: the
# memo is keyed by raw sign-bytes, so on a long chain it grows with
# distinct (height, round) forever — the cap bounds it and the
# eviction counter makes the pressure observable (mirrors the
# SigCache's hits/misses/evictions discipline). Cap is env-tunable
# because a blocksync verifier re-touches at most a few tiles' worth
# of messages at once.
H2C_CACHE_CAP = env_int("COMETBFT_TPU_H2C_CACHE_CAP", 1024, minimum=2)
H2G2_COUNTERS = {"hits": 0, "misses": 0, "evictions": 0}
_H2C_LOCK = threading.Lock()
_H2C_CACHE: "collections.OrderedDict[bytes, object]" = \
    collections.OrderedDict()


def hash_to_g2_cached(msg: bytes):
    """Memoized hash_to_g2 over the (immutable) message bytes. The
    same consensus sign-bytes are hashed by the signer, by every
    verifier in the process (simnet runs all nodes in-process), and by
    the aggregate-commit verifier's message grouping — a pure function
    of msg, so the memo cannot change any verdict. Bounded LRU
    (H2C_CACHE_CAP entries, evictions counted in H2G2_COUNTERS)."""
    with _H2C_LOCK:
        pt = _H2C_CACHE.get(msg)
        if pt is not None:
            _H2C_CACHE.move_to_end(msg)
            H2G2_COUNTERS["hits"] += 1
            return pt
    pt = hash_to_g2(msg)        # outside the lock: the map is pure
    with _H2C_LOCK:
        H2G2_COUNTERS["misses"] += 1
        _H2C_CACHE[msg] = pt
        _H2C_CACHE.move_to_end(msg)
        while len(_H2C_CACHE) > H2C_CACHE_CAP:
            _H2C_CACHE.popitem(last=False)
            H2G2_COUNTERS["evictions"] += 1
    return pt


def reset_hash_to_g2_cache() -> None:
    """Test hook: drop memoized points and zero the counters."""
    with _H2C_LOCK:
        _H2C_CACHE.clear()
        for k in H2G2_COUNTERS:
            H2G2_COUNTERS[k] = 0


# --- the key type (reference key_bls12381.go surface) -------------------------

def _fixed_msg(msg: bytes) -> bytes:
    """>32 bytes -> sha256 (key_bls12381.go:90-97/133-136); at most 32
    bytes -> zero-padded to exactly 32. The padding is interop
    deviation #2 (module docstring): the reference hands short
    messages to blst raw, unpadded — Go's `[32]byte(msg)` conversion
    would PANIC for len < 32, so there is no padded-array semantics to
    match there. Padding makes trailing-zero variants within the
    32-byte window sign identically, which is acceptable only because
    consensus sign-bytes are always longer than 32 bytes."""
    if len(msg) > MAX_MSG_LEN:
        return hashlib.sha256(msg).digest()
    return msg.ljust(MAX_MSG_LEN, b"\x00")


class Bls12381PrivKey:
    def __init__(self, raw: bytes):
        if len(raw) != PRIV_KEY_SIZE:
            raise ValueError("bls12_381 private key must be 32 bytes")
        self._sk = int.from_bytes(raw, "big")
        # STRICT range check, matching blst's SecretKeyFromBytes
        # (key_bls12381.go:44): scalars outside [1, r-1] are rejected,
        # never silently reduced — the same key file must be accepted
        # or rejected identically by both implementations
        if not 1 <= self._sk < R:
            raise ValueError("bls12_381 private key out of range")
        self._raw = raw

    @classmethod
    def generate(cls, seed: Optional[bytes] = None) -> "Bls12381PrivKey":
        import secrets
        if seed is not None:
            sk = int.from_bytes(
                hashlib.sha256(b"bls-keygen" + seed).digest(), "big") % R
            if sk == 0:  # pragma: no cover — 2^-255
                sk = 1
        else:
            sk = secrets.randbelow(R - 1) + 1
        return cls(sk.to_bytes(32, "big"))

    def sign(self, msg: bytes) -> bytes:
        h = hash_to_g2_cached(_fixed_msg(msg))
        return g2_compress(_fq2.pt_mul(self._sk, h))

    def pub_key(self) -> "Bls12381PubKey":
        return Bls12381PubKey(
            g1_compress(_fq.pt_mul(self._sk, G1_GEN)))

    def bytes_(self) -> bytes:
        return self._raw

    def type_(self) -> str:
        return KEY_TYPE


class Bls12381PubKey:
    def __init__(self, raw: bytes):
        if len(raw) != PUB_KEY_SIZE:
            raise ValueError("bls12_381 public key must be 48 bytes")
        self._raw = raw
        self._pt = g1_decompress(raw)  # validates curve + subgroup
        if self._pt is None:
            raise ValueError("bls12_381 public key is infinity")

    @property
    def point(self):
        """The decompressed (subgroup-checked) G1 point — consumed by
        aggsig's pubkey grouping so aggregation never re-decompresses."""
        return self._pt

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_LENGTH:
            return False
        try:
            s = g2_decompress(sig)
        except ValueError:
            return False
        if s is None:
            return False
        h = hash_to_g2_cached(_fixed_msg(msg))
        # e(g1, s) == e(pk, h)  ⟺  e(-g1, s)·e(pk, h) == 1: two Miller
        # loops sharing one final exponentiation (same verdict as the
        # two-pairing equality, pinned by tests)
        return multi_pairing_is_one([(G1_NEG, s), (self._pt, h)])

    def address(self) -> bytes:
        return hashlib.sha256(self._raw).digest()[:20]

    def bytes_(self) -> bytes:
        return self._raw

    def type_(self) -> str:
        return KEY_TYPE
