"""secp256k1 ECDSA keys and signatures
(reference crypto/secp256k1/secp256k1.go — btcec-backed there; pure
Python here: ECDSA is a consensus-edge key type for app/account keys,
not the validator hot path, so host arithmetic is the right cost tier).

Semantics matched to the reference:
- pubkey: 33-byte compressed SEC1 encoding
- address: RIPEMD160(SHA256(pubkey)) (secp256k1.go:41-47, bitcoin style)
- signature: 64-byte r || s with the low-s rule enforced on both sign
  and verify (malleability, secp256k1.go Sign/VerifySignature)
- nonce: RFC 6979 deterministic (SHA-256)
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass

# curve parameters (SEC2)
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
B = 7

SECP256K1_KEY_TYPE = "secp256k1"


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


def _pt_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _pt_mul(k: int, pt):
    acc = None
    while k:
        if k & 1:
            acc = _pt_add(acc, pt)
        pt = _pt_add(pt, pt)
        k >>= 1
    return acc


def _compress(pt) -> bytes:
    x, y = pt
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def _decompress(b: bytes):
    if len(b) != 33 or b[0] not in (2, 3):
        return None
    x = int.from_bytes(b[1:], "big")
    if x >= P:
        return None
    y2 = (pow(x, 3, P) + B) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        return None
    if (y & 1) != (b[0] & 1):
        y = P - y
    return x, y


def _rfc6979_k(privkey: int, msg_hash: bytes) -> int:
    """Deterministic nonce (RFC 6979, SHA-256)."""
    x = privkey.to_bytes(32, "big")
    h1 = msg_hash
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def address_from_pubkey(pub: bytes) -> bytes:
    """RIPEMD160(SHA256(pubkey)) (reference secp256k1.go:41-47)."""
    return hashlib.new("ripemd160",
                       hashlib.sha256(pub).digest()).digest()


@dataclass(frozen=True)
class Secp256k1PubKey:
    raw: bytes  # 33-byte compressed

    def __post_init__(self):
        if len(self.raw) != 33:
            raise ValueError("secp256k1 pubkey must be 33 bytes")

    def address(self) -> bytes:
        return address_from_pubkey(self.raw)

    def bytes_(self) -> bytes:
        return self.raw

    def type_(self) -> str:
        return SECP256K1_KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        """64-byte r||s, low-s enforced (secp256k1.go VerifySignature
        rejects high-s)."""
        if len(sig) != 64:
            return False
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (1 <= r < N and 1 <= s < N):
            return False
        if s > N // 2:
            return False  # malleable high-s rejected
        pt = _decompress(self.raw)
        if pt is None:
            return False
        e = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
        w = _inv(s, N)
        u1, u2 = e * w % N, r * w % N
        R = _pt_add(_pt_mul(u1, (GX, GY)), _pt_mul(u2, pt))
        if R is None:
            return False
        return R[0] % N == r


@dataclass(frozen=True)
class Secp256k1PrivKey:
    secret: bytes  # 32 bytes

    def __post_init__(self):
        d = int.from_bytes(self.secret, "big")
        if len(self.secret) != 32 or not (1 <= d < N):
            raise ValueError("invalid secp256k1 secret")

    @classmethod
    def generate(cls, rng=None) -> "Secp256k1PrivKey":
        import secrets
        while True:
            raw = (secrets.token_bytes(32) if rng is None else
                   bytes(rng.randrange(256) for _ in range(32)))
            d = int.from_bytes(raw, "big")
            if 1 <= d < N:
                return cls(raw)

    def pub_key(self) -> Secp256k1PubKey:
        d = int.from_bytes(self.secret, "big")
        return Secp256k1PubKey(_compress(_pt_mul(d, (GX, GY))))

    def bytes_(self) -> bytes:
        return self.secret

    def type_(self) -> str:
        return SECP256K1_KEY_TYPE

    def sign(self, msg: bytes) -> bytes:
        """Deterministic ECDSA over sha256(msg), low-s normalized."""
        d = int.from_bytes(self.secret, "big")
        h = hashlib.sha256(msg).digest()
        e = int.from_bytes(h, "big") % N
        while True:
            k = _rfc6979_k(d, h)
            R = _pt_mul(k, (GX, GY))
            r = R[0] % N
            if r == 0:
                h = hashlib.sha256(h).digest()
                continue
            s = _inv(k, N) * (e + r * d) % N
            if s == 0:
                h = hashlib.sha256(h).digest()
                continue
            if s > N // 2:
                s = N - s
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")
