"""Pure-Python ed25519 reference implementation (big-int, host-side).

This is the trusted oracle for the TPU kernels in `cometbft_tpu.ops`: it
generates the fixed-base tables, provides host-side signing, and backs the
test suite. It mirrors the semantics of the reference engine's ed25519
provider (reference: crypto/ed25519/ed25519.go:40-42,181-188 — ZIP-215
verification via curve25519-voi), including the cofactored verification
equation [8][s]B = [8]R + [8][k]A and ZIP-215's permissive point decoding
(non-canonical y accepted, small-order points accepted, s strictly < L).

Not constant-time; never use for production secret keys. Signing here exists
for tests, tooling, and validator-file workflows (reference: privval/file.go)
— the hot path (verification) runs on TPU.
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

# --- curve constants ---------------------------------------------------------

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# base point: y = 4/5 (mod p), x recovered with even sign
B_Y = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """x from y per RFC 8032 §5.1.3; None if no square root exists."""
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate root of u/v via the (p-5)/8 trick
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P), (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if vxx == u:
        pass
    elif vxx == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if x % 2 != sign:
        x = (-x) % P
    return x


B_X = _recover_x(B_Y, 0)
assert B_X is not None

# --- group ops in extended coordinates (X:Y:Z:T), a=-1 twisted Edwards -------

Point = Tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)
BASE: Point = (B_X, B_Y, 1, (B_X * B_Y) % P)

_D2 = (2 * D) % P


def pt_add(p: Point, q: Point) -> Point:
    """Complete unified addition (add-2008-hwcd-3)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % P
    b = ((y1 + x1) * (y2 + x2)) % P
    c = (t1 * _D2 * t2) % P
    d = (2 * z1 * z2) % P
    e = b - a
    f = d - c
    g = d + c
    h = b + a
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def pt_double(p: Point) -> Point:
    """dbl-2008-hwcd."""
    x1, y1, z1, _ = p
    a = (x1 * x1) % P
    b = (y1 * y1) % P
    c = (2 * z1 * z1) % P
    h = (a + b) % P
    e = (h - (x1 + y1) * (x1 + y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def pt_neg(p: Point) -> Point:
    x, y, z, t = p
    return ((-x) % P, y, z, (-t) % P)


def pt_mul(s: int, p: Point) -> Point:
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = pt_add(q, p)
        p = pt_double(p)
        s >>= 1
    return q


def pt_eq(p: Point, q: Point) -> bool:
    """Projective equality: X1*Z2 == X2*Z1 and Y1*Z2 == Y2*Z1."""
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def pt_is_identity(p: Point) -> bool:
    x, y, z, _ = p
    return x % P == 0 and (y - z) % P == 0


def pt_compress(p: Point) -> bytes:
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    x, y = (x * zi) % P, (y * zi) % P
    return ((y | ((x & 1) << 255))).to_bytes(32, "little")


def pt_decompress(s: bytes, zip215: bool = True) -> Point | None:
    """Decode a 32-byte point.

    zip215=True (the verification default, matching the reference's
    curve25519-voi config at crypto/ed25519/ed25519.go:181-188): the y
    coordinate is NOT required to be canonical (values >= p are reduced),
    and x == 0 with sign bit 1 is accepted.
    """
    if len(s) != 32:
        return None
    val = int.from_bytes(s, "little")
    sign = (val >> 255) & 1
    y = val & ((1 << 255) - 1)
    if not zip215 and y >= P:
        return None
    y %= P
    x = _recover_x(y, sign)
    if x is None:
        return None
    if not zip215 and x == 0 and sign == 1:
        return None
    return (x, y, 1, (x * y) % P)


# --- scalars -----------------------------------------------------------------

def sc_reduce(b: bytes) -> int:
    return int.from_bytes(b, "little") % L


def clamp(h: bytes) -> int:
    a = bytearray(h[:32])
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


# --- RFC 8032 sign / verify --------------------------------------------------

def pubkey_from_seed(seed: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = clamp(h)
    return pt_compress(pt_mul(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = clamp(h)
    prefix = h[32:]
    pub = pt_compress(pt_mul(a, BASE))
    r = sc_reduce(hashlib.sha512(prefix + msg).digest())
    rb = pt_compress(pt_mul(r, BASE))
    k = sc_reduce(hashlib.sha512(rb + pub + msg).digest())
    s = (r + k * a) % L
    return rb + s.to_bytes(32, "little")


def verify(pub: bytes, msg: bytes, sig: bytes, zip215: bool = True) -> bool:
    """Cofactored ZIP-215 verification: [8][s]B == [8]R + [8][k]A.

    Mirrors reference crypto/ed25519/ed25519.go:181-188 (VerifyOptionsZIP_215).
    k is hashed over the ORIGINAL encodings of R and A, not re-canonicalized.
    """
    if len(sig) != 64 or len(pub) != 32:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:  # s must always be canonical (malleability check)
        return False
    a_pt = pt_decompress(pub, zip215=zip215)
    r_pt = pt_decompress(sig[:32], zip215=zip215)
    if a_pt is None or r_pt is None:
        return False
    k = sc_reduce(hashlib.sha512(sig[:32] + pub + msg).digest())
    # [s]B - R - [k]A, then multiply by cofactor 8
    acc = pt_add(pt_mul(s, BASE), pt_neg(pt_add(r_pt, pt_mul(k, a_pt))))
    for _ in range(3):
        acc = pt_double(acc)
    return pt_is_identity(acc)


# --- fixed-base window table (consumed by ops/edwards.py) --------------------

def base_table_int(windows: int = 64, wbits: int = 4) -> List[List[Point]]:
    """table[i][j] = [j * 2**(wbits*i)]B in extended coords (Z not normalized).

    Built iteratively (row i+1 = each entry of row i doubled wbits times) so
    import-time cost stays low; entries keep projective Z to avoid inversions.
    """
    row: List[Point] = [IDENTITY, BASE]
    for j in range(2, 2**wbits):
        row.append(pt_add(row[j - 1], BASE))
    table = [row]
    for _ in range(windows - 1):
        prev = table[-1]
        nxt = []
        for pt in prev:
            q = pt
            for _ in range(wbits):
                q = pt_double(q)
            nxt.append(q)
        table.append(nxt)
    return table
