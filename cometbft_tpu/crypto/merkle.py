"""RFC-6962 Merkle tree over SHA-256 (reference crypto/merkle/tree.go,
hash.go: leaf prefix 0x00, inner prefix 0x01, empty hash = sha256("")).

Host-side hashlib implementation — header/validator-set hashing is a
control-plane operation over dozens of items; the TPU data plane is for
signatures. Proofs follow crypto/merkle/proof.go semantics.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Sequence

_LEAF = b"\x00"
_INNER = b"\x01"


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(_LEAF + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(_INNER + left + right)


def empty_hash() -> bytes:
    return _sha256(b"")


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n."""
    assert n > 1
    k = 1 << (n.bit_length() - 1)
    return k >> 1 if k == n else k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]),
                      hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    """Merkle inclusion proof (reference crypto/merkle/proof.go:28-48)."""
    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes]

    def compute_root(self) -> bytes:
        """Raises ValueError on malformed index/total/aunt shapes."""
        return _compute_from_aunts(self.index, self.total, self.leaf_hash,
                                   self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        """False (never raises) on attacker-controlled malformed proofs —
        this sits on the gossip ingest path (PartSet.add_part)."""
        if self.leaf_hash != leaf_hash(leaf):
            return False
        try:
            return self.compute_root() == root
        except ValueError:
            return False


def _compute_from_aunts(index: int, total: int, lh: bytes,
                        aunts: List[bytes]) -> bytes:
    if not (0 <= index < total):
        raise ValueError(f"proof index {index} out of range for {total}")
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single-leaf tree")
        return lh
    if not aunts:
        raise ValueError("missing aunts")
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, lh, aunts[:-1])
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, lh, aunts[:-1])
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]
                            ) -> tuple[bytes, List[Proof]]:
    """Root hash + one inclusion proof per item
    (reference crypto/merkle/proof.go ProofsFromByteSlices)."""
    n = len(items)
    leaves = [leaf_hash(it) for it in items]

    def build(lo: int, hi: int) -> tuple[bytes, dict]:
        if hi - lo == 1:
            return leaves[lo], {lo: []}
        k = _split_point(hi - lo)
        lroot, lp = build(lo, lo + k)
        rroot, rp = build(lo + k, hi)
        proofs = {}
        for i, aunts in lp.items():
            proofs[i] = aunts + [rroot]
        for i, aunts in rp.items():
            proofs[i] = aunts + [lroot]
        return inner_hash(lroot, rroot), proofs

    if n == 0:
        return empty_hash(), []
    root, pmap = build(0, n)
    return root, [Proof(n, i, leaves[i], pmap[i]) for i in range(n)]
