"""RFC-6962 Merkle tree over SHA-256 (reference crypto/merkle/tree.go,
hash.go: leaf prefix 0x00, inner prefix 0x01, empty hash = sha256("")).

Host-side hashlib implementation — header/validator-set hashing is a
control-plane operation over dozens of items; the TPU data plane is for
signatures. Proofs follow crypto/merkle/proof.go semantics.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Sequence

_LEAF = b"\x00"
_INNER = b"\x01"


def _sha256(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(_LEAF + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(_INNER + left + right)


def empty_hash() -> bytes:
    return _sha256(b"")


def _split_point(n: int) -> int:
    """Largest power of two strictly less than n."""
    assert n > 1
    k = 1 << (n.bit_length() - 1)
    return k >> 1 if k == n else k


def hash_from_byte_slices(items: Sequence[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = _split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]),
                      hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    """Merkle inclusion proof (reference crypto/merkle/proof.go:28-48)."""
    total: int
    index: int
    leaf_hash: bytes
    aunts: List[bytes]

    def compute_root(self) -> bytes:
        """Raises ValueError on malformed index/total/aunt shapes."""
        return _compute_from_aunts(self.index, self.total, self.leaf_hash,
                                   self.aunts)

    def verify(self, root: bytes, leaf: bytes) -> bool:
        """False (never raises) on attacker-controlled malformed proofs —
        this sits on the gossip ingest path (PartSet.add_part)."""
        if self.leaf_hash != leaf_hash(leaf):
            return False
        try:
            return self.compute_root() == root
        except ValueError:
            return False


@dataclass
class AbsenceProof:
    """Proof that no leaf exists between two ADJACENT tree positions:
    inclusion proofs for the left neighbor and (unless the left neighbor
    is the last leaf) the right neighbor, carried with their raw leaf
    bytes so the verifier can check the neighbors bracket the missing
    item under the application's leaf ordering.

    The reference verifies absence through its ProofRuntime op set
    (light/rpc/client.go:149,182 VerifyAbsence over iavl range proofs);
    this is the same guarantee re-based on the RFC-6962 tree: adjacency
    of indices in a sorted-leaf tree means nothing lies between."""
    left: Proof
    left_leaf: bytes
    right: Optional[Proof]
    right_leaf: Optional[bytes]

    def verify_adjacent(self, root: bytes) -> bool:
        """Structural check only: both neighbors are in the tree under
        `root` and are index-adjacent (or left is the final leaf). The
        caller must separately check the leaf CONTENTS bracket the
        missing key — ordering is an application-level contract."""
        if not self.left.verify(root, self.left_leaf):
            return False
        if self.right is None:
            return self.right_leaf is None and \
                self.left.index == self.left.total - 1
        if self.right_leaf is None:
            return False
        if not self.right.verify(root, self.right_leaf):
            return False
        return (self.right.total == self.left.total
                and self.right.index == self.left.index + 1)


def _compute_from_aunts(index: int, total: int, lh: bytes,
                        aunts: List[bytes]) -> bytes:
    if not (0 <= index < total):
        raise ValueError(f"proof index {index} out of range for {total}")
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single-leaf tree")
        return lh
    if not aunts:
        raise ValueError("missing aunts")
    k = _split_point(total)
    if index < k:
        left = _compute_from_aunts(index, k, lh, aunts[:-1])
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, lh, aunts[:-1])
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: Sequence[bytes]
                            ) -> tuple[bytes, List[Proof]]:
    """Root hash + one inclusion proof per item
    (reference crypto/merkle/proof.go ProofsFromByteSlices)."""
    n = len(items)
    leaves = [leaf_hash(it) for it in items]

    def build(lo: int, hi: int) -> tuple[bytes, dict]:
        if hi - lo == 1:
            return leaves[lo], {lo: []}
        k = _split_point(hi - lo)
        lroot, lp = build(lo, lo + k)
        rroot, rp = build(lo + k, hi)
        proofs = {}
        for i, aunts in lp.items():
            proofs[i] = aunts + [rroot]
        for i, aunts in rp.items():
            proofs[i] = aunts + [lroot]
        return inner_hash(lroot, rroot), proofs

    if n == 0:
        return empty_hash(), []
    root, pmap = build(0, n)
    return root, [Proof(n, i, leaves[i], pmap[i]) for i in range(n)]
