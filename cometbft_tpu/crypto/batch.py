"""Batch-verifier dispatch by key type — the plugin seam where the TPU
data plane slots into every verification call site (reference
crypto/batch/batch.go:11-35)."""

from __future__ import annotations

from typing import List, Optional, Tuple

from .keys import BatchVerifier, Ed25519BatchVerifier, PubKey, ED25519_KEY_TYPE


def create_batch_verifier(pk: PubKey) -> Tuple[Optional[BatchVerifier], bool]:
    """(verifier, supported) for the given key type
    (reference crypto/batch/batch.go:11-21).

    With COMETBFT_TPU_DEVICE_SERVER=host:port set, ed25519 batches are
    shipped to the host's TPU-owner device server instead of verifying
    in-process — every node process on the machine then shares one
    compiled kernel and one accumulate-and-flush tile stream."""
    if pk.type_() == ED25519_KEY_TYPE:
        from ..device.client import RemoteBatchVerifier, shared_client
        client = shared_client()
        if client is not None:
            return RemoteBatchVerifier(client), True
        return Ed25519BatchVerifier(), True
    if pk.type_() == "sr25519":
        from .sr25519 import Sr25519BatchVerifier
        return Sr25519BatchVerifier(), True
    if pk.type_() == "bls12_381":
        # one multi-pairing (random-linear-combination) over the whole
        # batch with a single shared final exponentiation, per-sig
        # fallback for attribution — so MixedBatchVerifier handles
        # mixed-curve vote sets instead of silently going per-sig
        from ..aggsig.aggregate import BlsBatchVerifier
        return BlsBatchVerifier(), True
    return None, False


def supports_batch_verifier(pk: PubKey) -> bool:
    """reference crypto/batch/batch.go:25-35 (secp256k1 has no batch
    form, exactly like the reference — callers fall back to per-sig)."""
    return pk is not None and pk.type_() in (ED25519_KEY_TYPE, "sr25519",
                                             "bls12_381")


class MixedBatchVerifier:
    """The BASELINE mixed-curve config: one verifier accepting
    ed25519 + sr25519 + secp256k1 keys, dispatching each signature to
    its curve's verifier (batched where the curve supports it, per-sig
    fallback where it doesn't), with per-signature attribution in the
    original order."""

    def __init__(self):
        self._order: List[Tuple[str, int]] = []   # (kind, idx in bucket)
        self._buckets = {}
        self._singles: List[Tuple[PubKey, bytes, bytes]] = []

    def __len__(self) -> int:
        return len(self._order)

    def add(self, pk: PubKey, msg: bytes, sig: bytes) -> None:
        kind = pk.type_()
        bucket = self._buckets.get(kind)
        if bucket is None and supports_batch_verifier(pk):
            bucket, _ = create_batch_verifier(pk)
            self._buckets[kind] = bucket
        if bucket is not None:
            self._order.append((kind, len(bucket)))
            bucket.add(pk, msg, sig)
        else:
            self._order.append(("single", len(self._singles)))
            self._singles.append((pk, msg, sig))

    def verify(self) -> Tuple[bool, List[bool]]:
        if not self._order:
            # match the single-curve verifiers (and the reference):
            # an empty batch is a failure, not vacuous success
            return False, []
        results = {}
        for kind, bucket in self._buckets.items():
            _, oks = bucket.verify()
            results[kind] = oks
        single_oks = [pk.verify_signature(msg, sig)
                      for pk, msg, sig in self._singles]
        out = []
        for kind, idx in self._order:
            out.append(single_oks[idx] if kind == "single"
                       else results[kind][idx])
        return all(out), out
