"""Batch-verifier dispatch by key type — the plugin seam where the TPU
data plane slots into every verification call site (reference
crypto/batch/batch.go:11-35)."""

from __future__ import annotations

from typing import Optional, Tuple

from .keys import BatchVerifier, Ed25519BatchVerifier, PubKey, ED25519_KEY_TYPE


def create_batch_verifier(pk: PubKey) -> Tuple[Optional[BatchVerifier], bool]:
    """(verifier, supported) for the given key type
    (reference crypto/batch/batch.go:11-21)."""
    if pk.type_() == ED25519_KEY_TYPE:
        return Ed25519BatchVerifier(), True
    return None, False


def supports_batch_verifier(pk: PubKey) -> bool:
    """reference crypto/batch/batch.go:25-35."""
    return pk is not None and pk.type_() == ED25519_KEY_TYPE
