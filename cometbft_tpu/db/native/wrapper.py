"""ctypes bindings for the C++ append-log KV backend (kvlog.cc).

The shared library is built on first use with g++ (cached beside the
source, rebuilt when the source is newer). File format is identical to
db.kv.FileDB, so the two backends can open each other's files.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, List, Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "kvlog.cc")
_SO = os.path.join(_DIR, "kvlog.so")

_lib = None
_lib_lock = threading.Lock()


def _build() -> None:
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _SO, _SRC],
        check=True, capture_output=True)


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.nkv_open.restype = ctypes.c_void_p
        lib.nkv_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.nkv_close.argtypes = [ctypes.c_void_p]
        lib.nkv_set.restype = ctypes.c_int
        lib.nkv_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_size_t, ctypes.c_char_p,
                                ctypes.c_size_t]
        lib.nkv_del.restype = ctypes.c_int
        lib.nkv_del.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_size_t]
        lib.nkv_get.restype = ctypes.c_int64
        lib.nkv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_size_t,
                                ctypes.POINTER(ctypes.POINTER(
                                    ctypes.c_uint8))]
        lib.nkv_free.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        lib.nkv_size.restype = ctypes.c_int64
        lib.nkv_size.argtypes = [ctypes.c_void_p]
        lib.nkv_iter.restype = ctypes.c_void_p
        lib.nkv_iter.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_size_t, ctypes.c_char_p,
                                 ctypes.c_size_t]
        lib.nkv_iter_next.restype = ctypes.c_int
        lib.nkv_iter_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.nkv_iter_close.argtypes = [ctypes.c_void_p]
        lib.nkv_compact.restype = ctypes.c_int
        lib.nkv_compact.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


class NativeDB:
    """KVStore over the C++ backend (same seam as MemDB/FileDB)."""

    def __init__(self, path: str, fsync: bool = False):
        self._lib = _load()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._h = self._lib.nkv_open(path.encode(), 1 if fsync else 0)
        if not self._h:
            raise OSError(f"nkv_open failed for {path}")

    def get(self, key: bytes) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.nkv_get(self._h, key, len(key), ctypes.byref(out))
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.nkv_free(out)

    def set(self, key: bytes, value: bytes) -> None:
        if self._lib.nkv_set(self._h, key, len(key), value,
                             len(value)) != 0:
            raise OSError("nkv_set failed")

    def delete(self, key: bytes) -> None:
        if self._lib.nkv_del(self._h, key, len(key)) != 0:
            raise OSError("nkv_del failed")

    def iterate(self, start: bytes = b"",
                end: Optional[bytes] = None
                ) -> Iterator[Tuple[bytes, bytes]]:
        it = self._lib.nkv_iter(self._h, start, len(start),
                                end or b"", len(end or b""))
        try:
            k = ctypes.POINTER(ctypes.c_uint8)()
            v = ctypes.POINTER(ctypes.c_uint8)()
            klen = ctypes.c_size_t()
            vlen = ctypes.c_size_t()
            while self._lib.nkv_iter_next(
                    it, ctypes.byref(k), ctypes.byref(klen),
                    ctypes.byref(v), ctypes.byref(vlen)):
                yield (ctypes.string_at(k, klen.value),
                       ctypes.string_at(v, vlen.value))
        finally:
            self._lib.nkv_iter_close(it)

    def write_batch(self, sets: List[Tuple[bytes, bytes]],
                    deletes: List[bytes] = ()) -> None:
        for k, v in sets:
            self.set(k, v)
        for k in deletes:
            self.delete(k)

    def compact(self) -> None:
        if self._lib.nkv_compact(self._h) != 0:
            raise OSError("nkv_compact failed")

    def __len__(self) -> int:
        return int(self._lib.nkv_size(self._h))

    def close(self) -> None:
        if self._h:
            self._lib.nkv_close(self._h)
            self._h = None
