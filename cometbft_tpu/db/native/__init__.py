from .wrapper import NativeDB, native_available

__all__ = ["NativeDB", "native_available"]
