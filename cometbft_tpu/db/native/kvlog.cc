// Native append-log KV store — the C++ storage backend behind the
// KVStore seam (the role cometbft-db's LevelDB/RocksDB backends play for
// the reference engine, node/node.go:284; record format shared with the
// pure-Python FileDB in ../kv.py so files are interchangeable).
//
// v1 record (written here): u8 op(0|1) | u32le klen | u32le vlen |
//   key | value — self-committing.
// v2 record (written by FileDB, docs/STORAGE.md): u8 op(2|3|4) |
//   u32le klen | u32le vlen | u32le crc | key | value, crc over the
//   v1-shaped header + key + value. Ops 2/3 buffer until a commit
//   marker (op 4, value = u32le record count) lands; an uncommitted,
//   torn, or CRC-bad tail truncates back to the last commit boundary,
//   mirroring FileDB's all-or-nothing batch replay. This backend keeps
//   WRITING v1 (each record its own commit point — FileDB replays the
//   mixed log fine) but must READ v2 so the two stay interchangeable.
// Open replays the log into an ordered in-memory index (std::map) and
// truncates a torn tail (crash mid-append). compact() rewrites live
// records through a temp file + atomic rename.
//
// C ABI for ctypes; all returned buffers are malloc'd and freed with
// nkv_free. Thread safety: a single mutex per handle (callers are the
// Python engine's storage paths, already coarse-grained).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint8_t REC_SET = 0;
constexpr uint8_t REC_DEL = 1;
constexpr uint8_t REC_SET2 = 2;
constexpr uint8_t REC_DEL2 = 3;
constexpr uint8_t REC_COMMIT = 4;

// zlib-compatible CRC-32 (polynomial 0xEDB88320), table built once —
// matches Python's zlib.crc32 so FileDB-written records verify here
const uint32_t* crc_table() {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  return table;
}

uint32_t crc32_update(uint32_t crc, const void* buf, size_t n) {
  const uint8_t* p = (const uint8_t*)buf;
  const uint32_t* t = crc_table();
  crc ^= 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) crc = t[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

struct Handle {
  std::map<std::string, std::string> index;
  std::string path;
  FILE* f = nullptr;
  bool fsync_each = false;
  std::mutex mu;
};

struct Iter {
  std::vector<std::pair<std::string, std::string>> snapshot;
  size_t pos = 0;
};

bool read_exact(FILE* f, void* buf, size_t n) {
  return fread(buf, 1, n, f) == n;
}

uint32_t rd32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

void wr32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xff; p[1] = (v >> 8) & 0xff;
  p[2] = (v >> 16) & 0xff; p[3] = (v >> 24) & 0xff;
}

// replay; returns byte offset of the last COMMITTED byte (end of the
// last complete v1 record or v2 commit marker — buffered v2 records
// without their marker are a crashed batch, discarded wholesale)
long replay(Handle* h, FILE* f) {
  long good = 0, pos = 0;
  uint8_t hdr[13];
  std::string key, val;
  std::vector<std::pair<uint8_t, std::pair<std::string, std::string>>>
      pending;
  for (;;) {
    if (!read_exact(f, hdr, 1)) break;
    uint8_t op = hdr[0];
    if (op == REC_SET || op == REC_DEL) {
      if (!read_exact(f, hdr + 1, 8)) break;
      uint32_t klen = rd32(hdr + 1), vlen = rd32(hdr + 5);
      key.resize(klen);
      val.resize(vlen);
      if (klen && !read_exact(f, &key[0], klen)) break;
      if (vlen && !read_exact(f, &val[0], vlen)) break;
      if (!pending.empty()) break;  // v1 inside an open v2 batch: corrupt
      if (op == REC_SET) {
        h->index[key] = val;
      } else {
        h->index.erase(key);
      }
      pos += 9 + (long)klen + (long)vlen;
      good = pos;
    } else if (op == REC_SET2 || op == REC_DEL2 || op == REC_COMMIT) {
      if (!read_exact(f, hdr + 1, 12)) break;
      uint32_t klen = rd32(hdr + 1), vlen = rd32(hdr + 5);
      uint32_t crc = rd32(hdr + 9);
      key.resize(klen);
      val.resize(vlen);
      if (klen && !read_exact(f, &key[0], klen)) break;
      if (vlen && !read_exact(f, &val[0], vlen)) break;
      // crc covers the v1-shaped header (op|klen|vlen) + key + value
      uint32_t got = crc32_update(0, hdr, 1);
      got = crc32_update(got, hdr + 1, 8);
      got = crc32_update(got, key.data(), klen);
      got = crc32_update(got, val.data(), vlen);
      if (got != crc) break;
      pos += 13 + (long)klen + (long)vlen;
      if (op == REC_COMMIT) {
        if (klen != 0 || vlen != 4 ||
            rd32((const uint8_t*)val.data()) != pending.size())
          break;
        for (const auto& p : pending) {
          if (p.first == REC_SET2) {
            h->index[p.second.first] = p.second.second;
          } else {
            h->index.erase(p.second.first);
          }
        }
        pending.clear();
        good = pos;
      } else {
        pending.push_back({op, {key, val}});
      }
    } else {
      break;  // unknown op: corrupt tail
    }
  }
  return good;
}

int append(Handle* h, uint8_t op, const uint8_t* k, size_t klen,
           const uint8_t* v, size_t vlen) {
  if (h->f == nullptr) return -1;  // e.g. reopen failed after compact
  uint8_t hdr[9];
  hdr[0] = op;
  wr32(hdr + 1, (uint32_t)klen);
  wr32(hdr + 5, (uint32_t)vlen);
  if (fwrite(hdr, 1, 9, h->f) != 9) return -1;
  if (klen && fwrite(k, 1, klen, h->f) != klen) return -1;
  if (vlen && fwrite(v, 1, vlen, h->f) != vlen) return -1;
  if (fflush(h->f) != 0) return -1;
  if (h->fsync_each && fsync(fileno(h->f)) != 0) return -1;
  return 0;
}

}  // namespace

extern "C" {

void* nkv_open(const char* path, int fsync_each) {
  auto* h = new Handle();
  h->path = path;
  h->fsync_each = fsync_each != 0;
  // crash hygiene (parity with FileDB): a crash before compact()'s
  // rename leaves a stale temp beside the log — always stale state
  remove((h->path + ".compact").c_str());
  FILE* existing = fopen(path, "rb");
  if (existing != nullptr) {
    long good = replay(h, existing);
    fseek(existing, 0, SEEK_END);
    long size = ftell(existing);
    fclose(existing);
    if (good != size) {
      if (truncate(path, good) != 0) {
        delete h;
        return nullptr;
      }
    }
  }
  h->f = fopen(path, "ab");
  if (h->f == nullptr) {
    delete h;
    return nullptr;
  }
  return h;
}

void nkv_close(void* hp) {
  auto* h = static_cast<Handle*>(hp);
  if (h->f) fclose(h->f);
  delete h;
}

int nkv_set(void* hp, const uint8_t* k, size_t klen, const uint8_t* v,
            size_t vlen) {
  auto* h = static_cast<Handle*>(hp);
  std::lock_guard<std::mutex> lock(h->mu);
  if (append(h, REC_SET, k, klen, v, vlen) != 0) return -1;
  h->index[std::string((const char*)k, klen)] =
      std::string((const char*)v, vlen);
  return 0;
}

int nkv_del(void* hp, const uint8_t* k, size_t klen) {
  auto* h = static_cast<Handle*>(hp);
  std::lock_guard<std::mutex> lock(h->mu);
  if (append(h, REC_DEL, k, klen, nullptr, 0) != 0) return -1;
  h->index.erase(std::string((const char*)k, klen));
  return 0;
}

// returns value length, -1 if absent; *out is malloc'd (nkv_free)
int64_t nkv_get(void* hp, const uint8_t* k, size_t klen, uint8_t** out) {
  auto* h = static_cast<Handle*>(hp);
  std::lock_guard<std::mutex> lock(h->mu);
  auto it = h->index.find(std::string((const char*)k, klen));
  if (it == h->index.end()) return -1;
  *out = (uint8_t*)malloc(it->second.size() ? it->second.size() : 1);
  memcpy(*out, it->second.data(), it->second.size());
  return (int64_t)it->second.size();
}

void nkv_free(uint8_t* p) { free(p); }

int64_t nkv_size(void* hp) {
  auto* h = static_cast<Handle*>(hp);
  std::lock_guard<std::mutex> lock(h->mu);
  return (int64_t)h->index.size();
}

// ordered snapshot iterator over [start, end); empty end = unbounded
void* nkv_iter(void* hp, const uint8_t* start, size_t slen,
               const uint8_t* end, size_t elen) {
  auto* h = static_cast<Handle*>(hp);
  std::lock_guard<std::mutex> lock(h->mu);
  auto* it = new Iter();
  std::string s((const char*)start, slen);
  auto lo = h->index.lower_bound(s);
  if (elen == 0) {
    for (; lo != h->index.end(); ++lo) it->snapshot.push_back(*lo);
  } else {
    std::string e((const char*)end, elen);
    for (; lo != h->index.end() && lo->first < e; ++lo)
      it->snapshot.push_back(*lo);
  }
  return it;
}

int nkv_iter_next(void* ip, const uint8_t** k, size_t* klen,
                  const uint8_t** v, size_t* vlen) {
  auto* it = static_cast<Iter*>(ip);
  if (it->pos >= it->snapshot.size()) return 0;
  const auto& kv = it->snapshot[it->pos++];
  *k = (const uint8_t*)kv.first.data();
  *klen = kv.first.size();
  *v = (const uint8_t*)kv.second.data();
  *vlen = kv.second.size();
  return 1;
}

void nkv_iter_close(void* ip) { delete static_cast<Iter*>(ip); }

int nkv_compact(void* hp) {
  auto* h = static_cast<Handle*>(hp);
  std::lock_guard<std::mutex> lock(h->mu);
  std::string tmp = h->path + ".compact";
  FILE* out = fopen(tmp.c_str(), "wb");
  if (out == nullptr) return -1;
  uint8_t hdr[9];
  for (const auto& kv : h->index) {
    hdr[0] = REC_SET;
    wr32(hdr + 1, (uint32_t)kv.first.size());
    wr32(hdr + 5, (uint32_t)kv.second.size());
    if (fwrite(hdr, 1, 9, out) != 9 ||
        fwrite(kv.first.data(), 1, kv.first.size(), out) !=
            kv.first.size() ||
        fwrite(kv.second.data(), 1, kv.second.size(), out) !=
            kv.second.size()) {
      fclose(out);
      remove(tmp.c_str());
      return -1;
    }
  }
  if (fflush(out) != 0 || fsync(fileno(out)) != 0) {
    fclose(out);
    remove(tmp.c_str());
    return -1;
  }
  fclose(out);
  fclose(h->f);
  h->f = nullptr;
  int rc = rename(tmp.c_str(), h->path.c_str()) == 0 ? 0 : -1;
  // reopen the (renamed or original) log either way: the handle must
  // never be left with a dangling/closed FILE*, or later appends are UB
  h->f = fopen(h->path.c_str(), "ab");
  if (h->f == nullptr) return -1;
  return rc;
}

}  // extern "C"
