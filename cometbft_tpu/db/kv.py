"""Key-value storage backends (the cometbft-db seam, reference go.mod:42,
node/node.go:284).

Three built-in backends:
- MemDB: ordered in-memory map (the memdb analog used across tests),
- FileDB: append-only log + in-memory index with compaction — a simple
  durable store in pure Python,
- NativeDB (db/native): the same record format implemented in C++
  (kvlog.cc, ctypes-bound) — the production storage path, file-
  compatible with FileDB.

Iteration is ordered by raw bytes, matching goleveldb semantics the
reference relies on for height-ordered scans.
"""

from __future__ import annotations

import os
import struct
import threading
from bisect import bisect_left, insort
from typing import Dict, Iterator, List, Optional, Protocol, Tuple


class KVStore(Protocol):
    def get(self, key: bytes) -> Optional[bytes]: ...
    def set(self, key: bytes, value: bytes) -> None: ...
    def delete(self, key: bytes) -> None: ...
    def iterate(self, start: bytes = b"", end: Optional[bytes] = None
                ) -> Iterator[Tuple[bytes, bytes]]: ...
    def write_batch(self, sets: List[Tuple[bytes, bytes]],
                    deletes: List[bytes] = ()) -> None: ...
    def close(self) -> None: ...


class MemDB:
    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []
        self._lock = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if key not in self._data:
                insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        with self._lock:
            if key in self._data:
                del self._data[key]
                i = bisect_left(self._keys, key)
                self._keys.pop(i)

    def iterate(self, start: bytes = b"", end: Optional[bytes] = None
                ) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            i = bisect_left(self._keys, start)
            keys = self._keys[i:]
            snapshot = [(k, self._data[k]) for k in keys
                        if end is None or k < end]
        yield from snapshot

    def write_batch(self, sets, deletes=()):
        with self._lock:
            for k, v in sets:
                self.set(k, v)
            for k in deletes:
                self.delete(k)

    def close(self):
        pass


_REC_SET = 0
_REC_DEL = 1


class FileDB:
    """Append-only log with full in-memory index.

    Record: u8 op | u32 klen | u32 vlen | key | value. Reopen replays the
    log; `compact()` rewrites live records. Durability knob `fsync` mirrors
    the role of the WAL's sync flag (reference internal/autofile)."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self._fsync = fsync
        self._mem = MemDB()
        self._lock = threading.RLock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            good = self._replay()
            if good != os.path.getsize(path):
                # torn tail from a crash mid-append: truncate it, else new
                # appends land after garbage and are lost on next replay
                with open(path, "r+b") as f:
                    f.truncate(good)
        self._f = open(path, "ab")

    def _replay(self) -> int:
        """Replay the log; returns the offset of the last complete record."""
        good = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(9)
                if len(hdr) < 9:
                    break
                op, klen, vlen = struct.unpack("<BII", hdr)
                kv = f.read(klen + vlen)
                if len(kv) < klen + vlen:
                    break  # torn tail write (crash recovery)
                good += 9 + klen + vlen
                key, value = kv[:klen], kv[klen:]
                if op == _REC_SET:
                    self._mem.set(key, value)
                else:
                    self._mem.delete(key)
        return good

    def _append(self, op: int, key: bytes, value: bytes = b""):
        rec = struct.pack("<BII", op, len(key), len(value)) + key + value
        self._f.write(rec)
        self._f.flush()
        if self._fsync:
            os.fsync(self._f.fileno())

    def get(self, key: bytes) -> Optional[bytes]:
        return self._mem.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._append(_REC_SET, key, value)
            self._mem.set(key, value)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._append(_REC_DEL, key)
            self._mem.delete(key)

    def iterate(self, start: bytes = b"", end: Optional[bytes] = None):
        return self._mem.iterate(start, end)

    def write_batch(self, sets, deletes=()):
        with self._lock:
            for k, v in sets:
                self._append(_REC_SET, k, v)
                self._mem.set(k, v)
            for k in deletes:
                self._append(_REC_DEL, k)
                self._mem.delete(k)

    def compact(self):
        with self._lock:
            tmp = self.path + ".compact"
            with open(tmp, "wb") as f:
                for k, v in self._mem.iterate():
                    f.write(struct.pack("<BII", _REC_SET, len(k), len(v))
                            + k + v)
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "ab")

    def close(self):
        self._f.close()


def open_db(backend: str, name: str, directory: str) -> KVStore:
    if backend == "memdb":
        return MemDB()
    if backend == "filedb":
        return FileDB(os.path.join(directory, f"{name}.db"))
    if backend == "native":
        from .native import NativeDB
        return NativeDB(os.path.join(directory, f"{name}.db"))
    raise ValueError(f"unknown db backend {backend!r}")
