"""Key-value storage backends (the cometbft-db seam, reference go.mod:42,
node/node.go:284).

Three built-in backends:
- MemDB: ordered in-memory map (the memdb analog used across tests),
- FileDB: append-only log + in-memory index with compaction — a simple
  durable store in pure Python,
- NativeDB (db/native): the same record format implemented in C++
  (kvlog.cc, ctypes-bound) — the production storage path, file-
  compatible with FileDB.

Iteration is ordered by raw bytes, matching goleveldb semantics the
reference relies on for height-ordered scans.

On-disk log format (docs/STORAGE.md):
  v1 record (legacy, self-committing — still replayed, still written by
  NativeDB):        u8 op(0|1) | u32 klen | u32 vlen | key | value
  v2 record:        u8 op(2|3) | u32 klen | u32 vlen | u32 crc |
                    key | value
  v2 commit marker: u8 4 | u32 0 | u32 4 | u32 crc | u32 count
where crc = crc32(header-sans-crc | key | value). v2 records between
commit markers form one BATCH, replayed all-or-nothing: a torn,
CRC-bad, or uncommitted tail truncates the log back to the last commit
boundary, so a crash at ANY byte offset inside a `write_batch` leaves
the store at the exact pre-batch state — never a prefix (the old v1
`write_batch` was a bare append loop; a mid-batch crash durably applied
meta-without-parts and friends, cometbft_tpu/store/blockstore.py).
`set`/`delete` are single-record batches. v1 logs replay transparently
(each v1 record is its own commit point) and upgrade wholesale to v2 on
the next `compact()`.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from bisect import bisect_left, insort
from typing import Dict, Iterator, List, Optional, Protocol, Tuple

from ..libs import faultio
from ..libs.fail import fail_point


class KVStore(Protocol):
    def get(self, key: bytes) -> Optional[bytes]: ...
    def set(self, key: bytes, value: bytes) -> None: ...
    def delete(self, key: bytes) -> None: ...
    def iterate(self, start: bytes = b"", end: Optional[bytes] = None
                ) -> Iterator[Tuple[bytes, bytes]]: ...
    def write_batch(self, sets: List[Tuple[bytes, bytes]],
                    deletes: List[bytes] = ()) -> None: ...
    def close(self) -> None: ...


class MemDB:
    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []
        self._lock = threading.RLock()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if key not in self._data:
                insort(self._keys, key)
            self._data[key] = value

    def delete(self, key: bytes) -> None:
        with self._lock:
            if key in self._data:
                del self._data[key]
                i = bisect_left(self._keys, key)
                self._keys.pop(i)

    def iterate(self, start: bytes = b"", end: Optional[bytes] = None
                ) -> Iterator[Tuple[bytes, bytes]]:
        with self._lock:
            i = bisect_left(self._keys, start)
            keys = self._keys[i:]
            snapshot = [(k, self._data[k]) for k in keys
                        if end is None or k < end]
        yield from snapshot

    def write_batch(self, sets, deletes=()):
        with self._lock:
            for k, v in sets:
                self.set(k, v)
            for k in deletes:
                self.delete(k)

    def close(self):
        pass


_REC_SET = 0      # v1, self-committing
_REC_DEL = 1      # v1, self-committing
_REC_SET2 = 2     # v2, pending until a commit marker
_REC_DEL2 = 3     # v2, pending until a commit marker
_REC_COMMIT = 4   # v2 batch commit marker; value = u32 record count

_V1_HDR = struct.Struct("<BII")
_V2_HDR = struct.Struct("<BIII")
_U32 = struct.Struct("<I")


def _enc2(op: int, key: bytes, value: bytes = b"") -> bytes:
    crc = zlib.crc32(_V1_HDR.pack(op, len(key), len(value)) + key + value)
    return _V2_HDR.pack(op, len(key), len(value), crc) + key + value


def _storage_metrics():
    """Lazy: store/ imports db/ at module level (blockstore), so the
    reverse edge must resolve at call time, and only on the cold
    corruption/repair paths."""
    from ..store import recovery
    return recovery.metrics()


class FileDB:
    """Append-only log with full in-memory index.

    Reopen replays the log (module docstring has the v1/v2 framing);
    `compact()` rewrites live records as one committed v2 batch.
    Durability knob `fsync` mirrors the role of the WAL's sync flag
    (reference internal/autofile). All file I/O rides the
    libs/faultio seam under labels db:log / db:replay / db:compact."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self._fsync = fsync
        self._mem = MemDB()
        self._lock = threading.RLock()
        # True once replay sees any v1 record: the one-time v2 upgrade
        # happens wholesale at the next compact() (store/recovery's
        # doctor reports it; nothing forces an eager rewrite of a
        # large, healthy log at boot).
        self.needs_upgrade = False
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # crash hygiene: a crash before compact()'s os.replace leaves a
        # stale temp beside the log — stale state, never the live copy
        stale = path + ".compact"
        if os.path.exists(stale):
            os.remove(stale)
            m = _storage_metrics()
            if m is not None:
                m.doctor_repairs.inc(kind="stale-compact")
        if os.path.exists(path):
            good = self._replay()
            if good != os.path.getsize(path):
                # torn/uncommitted/corrupt tail from a crash: truncate
                # back to the last commit boundary, else new appends
                # land after garbage and are lost on next replay
                with faultio.open_file(path, "r+b", label="db:log") as f:
                    f.truncate(good)
        self._f = faultio.open_file(path, "ab", label="db:log")

    def _replay(self) -> int:
        """Replay the log; returns the offset of the last COMMITTED
        byte: the end of the last complete v1 record or v2 commit
        marker. v2 records buffer in `pending` and apply only when
        their commit marker lands with a matching count — a tail of
        pending records without one is a crashed `write_batch` and is
        discarded wholesale (all-or-nothing)."""
        good = 0
        pos = 0
        pending: List[Tuple[int, bytes, bytes]] = []
        crc_bad = torn_batch = False
        with faultio.open_file(self.path, "rb", label="db:replay") as f:
            while True:
                b0 = f.read(1)
                if not b0:
                    break
                op = b0[0]
                if op in (_REC_SET, _REC_DEL):
                    rest = f.read(_V1_HDR.size - 1)
                    if len(rest) < _V1_HDR.size - 1:
                        break
                    _, klen, vlen = _V1_HDR.unpack(b0 + rest)
                    kv = f.read(klen + vlen)
                    if len(kv) < klen + vlen:
                        break  # torn tail write (crash recovery)
                    if pending:
                        # a v1 record can never land inside an open v2
                        # batch — this is corruption, not framing
                        torn_batch = True
                        break
                    key, value = kv[:klen], kv[klen:]
                    if op == _REC_SET:
                        self._mem.set(key, value)
                    else:
                        self._mem.delete(key)
                    self.needs_upgrade = True
                    pos += _V1_HDR.size + klen + vlen
                    good = pos
                elif op in (_REC_SET2, _REC_DEL2, _REC_COMMIT):
                    rest = f.read(_V2_HDR.size - 1)
                    if len(rest) < _V2_HDR.size - 1:
                        break
                    _, klen, vlen, crc = _V2_HDR.unpack(b0 + rest)
                    kv = f.read(klen + vlen)
                    if len(kv) < klen + vlen:
                        break
                    if zlib.crc32(_V1_HDR.pack(op, klen, vlen) + kv) != crc:
                        crc_bad = True
                        break
                    pos += _V2_HDR.size + klen + vlen
                    if op == _REC_COMMIT:
                        if klen != 0 or vlen != _U32.size or \
                                _U32.unpack(kv)[0] != len(pending):
                            torn_batch = True
                            break
                        for p_op, k, v in pending:
                            if p_op == _REC_SET2:
                                self._mem.set(k, v)
                            else:
                                self._mem.delete(k)
                        pending = []
                        good = pos
                    else:
                        pending.append((op, kv[:klen], kv[klen:]))
                else:
                    break  # unknown op: corrupt tail
        m = _storage_metrics()
        if m is not None:
            if crc_bad:
                m.crc_failures.inc()
            if pending or torn_batch:
                m.torn_batches.inc()
        return good

    def get(self, key: bytes) -> Optional[bytes]:
        return self._mem.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self.write_batch([(key, value)])

    def delete(self, key: bytes) -> None:
        self.write_batch([], [key])

    def iterate(self, start: bytes = b"", end: Optional[bytes] = None):
        return self._mem.iterate(start, end)

    def write_batch(self, sets, deletes=()):
        """Crash-atomic: records + commit marker go down in ONE write
        through the faultio seam, and the in-memory index is touched
        only after the disk image is past its commit point — a tear at
        any byte offset replays to the exact pre-batch state."""
        with self._lock:
            buf = bytearray()
            n = 0
            for k, v in sets:
                buf += _enc2(_REC_SET2, k, v)
                n += 1
            for k in deletes:
                buf += _enc2(_REC_DEL2, k)
                n += 1
            if n == 0:
                return
            buf += _enc2(_REC_COMMIT, b"", _U32.pack(n))
            self._f.write(bytes(buf))
            self._f.flush()
            if self._fsync:
                faultio.fsync(self._f)
            for k, v in sets:
                self._mem.set(k, v)
            for k in deletes:
                self._mem.delete(k)

    def compact(self):
        """Rewrite live records as one committed v2 batch into a temp
        file, then atomically swap it in — also the one-time v1→v2
        upgrade. The two fail points bracket the os.replace so the
        crash matrix pins both halves: pre = old log intact + stale
        temp (removed at next open), post = new log already live."""
        with self._lock:
            tmp = self.path + ".compact"
            live = list(self._mem.iterate())
            f = faultio.open_file(tmp, "wb", label="db:compact")
            try:
                buf = bytearray()
                for k, v in live:
                    buf += _enc2(_REC_SET2, k, v)
                buf += _enc2(_REC_COMMIT, b"", _U32.pack(len(live)))
                f.write(bytes(buf))
                f.flush()
                faultio.fsync(f)
            finally:
                f.close()
            self._f.close()
            fail_point("db:pre-compact-replace")
            os.replace(tmp, self.path)
            fail_point("db:post-compact-replace")
            self._f = faultio.open_file(self.path, "ab", label="db:log")
            self.needs_upgrade = False

    def close(self):
        self._f.close()


def open_db(backend: str, name: str, directory: str) -> KVStore:
    if backend == "memdb":
        return MemDB()
    if backend == "filedb":
        return FileDB(os.path.join(directory, f"{name}.db"))
    if backend == "native":
        from .native import NativeDB
        return NativeDB(os.path.join(directory, f"{name}.db"))
    raise ValueError(f"unknown db backend {backend!r}")
