from .kv import KVStore, MemDB, FileDB, open_db  # noqa: F401
