"""Light-block providers (reference light/provider/provider.go interface,
light/provider/http, light/provider/mock).

`BlockStoreProvider` serves light blocks straight from a full node's
BlockStore + StateStore — the in-process analog of the RPC provider, and
what the `light/client_benchmark_test.go:24` mock provider does with its
1000-block chain.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Protocol, Tuple

from ..libs import timesource
from ..libs.env import env_float, env_int
from .types import LightBlock, SignedHeader

# transient-fetch retry knobs (HTTPProvider): one flaky socket must not
# fail a whole multi-step verification — the reference http provider
# retries with backoff the same way (light/provider/http http.go
# maxRetryAttempts). Transient = OSError family ONLY (refused /reset /
# timeout); an RPC-level error answer is a deterministic response and
# retrying it would just triple every byzantine rejection.
ENV_RETRIES = "COMETBFT_TPU_LIGHT_PROVIDER_RETRIES"
ENV_RETRY_BASE = "COMETBFT_TPU_LIGHT_PROVIDER_RETRY_BASE"  # seconds
DEFAULT_RETRIES = 2
DEFAULT_RETRY_BASE_S = 0.05
_JITTER_FRACTION = 0.25


class ProviderError(Exception):
    pass


def retry_transient(fn: Callable, rng: random.Random,
                    retries: Optional[int] = None,
                    base_s: Optional[float] = None,
                    transient: Tuple = (OSError,),
                    sleep: Optional[Callable[[float], None]] = None):
    """Run `fn()` with jittered-exponential-backoff retries on
    `transient` errors; the final failure re-raises. The jitter comes
    from the caller's SEEDED rng (staticcheck's global-rng rule: every
    draw must replay), and the sleep is suppressed while a virtual
    clock is installed — under simnet a wall sleep would stall the
    sim thread without advancing virtual time, and the retry sequence
    must stay byte-identical per seed."""
    if retries is None:
        retries = env_int(ENV_RETRIES, DEFAULT_RETRIES, minimum=0)
    if base_s is None:
        base_s = env_float(ENV_RETRY_BASE, DEFAULT_RETRY_BASE_S,
                           minimum=0.0)
    for attempt in range(retries + 1):
        try:
            return fn()
        except transient:
            if attempt == retries:
                raise
            delay = base_s * (2.0 ** attempt) \
                * (1.0 + _JITTER_FRACTION * rng.random())
            if sleep is not None:
                sleep(delay)
            elif not timesource.installed():
                time.sleep(delay)


class ErrLightBlockNotFound(ProviderError):
    pass


class Provider(Protocol):
    """reference light/provider/provider.go:9-32."""

    def chain_id(self) -> str: ...
    def light_block(self, height: int) -> LightBlock:
        """height 0 means latest. Raises ProviderError."""


class BlockStoreProvider:
    def __init__(self, chain_id: str, block_store, state_store):
        self._chain_id = chain_id
        self._blocks = block_store
        self._states = state_store

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self._blocks.height()
        meta = self._blocks.load_block_meta(height)
        blk = self._blocks.load_block(height)
        commit = self._blocks.load_block_commit(height)
        vals = self._states.load_validators(height)
        if blk is None or commit is None or vals is None:
            raise ErrLightBlockNotFound(
                f"no light block at height {height}")
        return LightBlock(SignedHeader(blk.header, commit), vals)


def fetch_all_validators(rpc_client, height=None, max_pages=64):
    """Merge the paginated /validators pages into one response dict.

    Hardened for the light client's adversary model: later pages are
    PINNED to page 1's block_height (unpinned 'latest' pages could
    straddle a height change and merge two sets — a spurious hash
    failure against an honest primary), an empty page stops the walk
    (no progress), and max_pages bounds it (a byzantine primary
    advertising total=10^9 must not hang the caller; 64 pages × 100 =
    6400 validators, far above any real set). 'count' reflects the
    merged list."""
    merged = None
    page = 1
    while page <= max_pages:
        kw = {"page": page, "per_page": 100}
        if height is not None:
            kw["height"] = height
        r = rpc_client.call("validators", **kw)
        if merged is None:
            merged = r
            height = r.get("block_height", height)  # pin later pages
        else:
            if not r.get("validators"):
                break
            merged["validators"].extend(r["validators"])
        if len(merged["validators"]) >= r.get(
                "total", len(merged["validators"])):
            break
        page += 1
    merged["count"] = len(merged["validators"])
    return merged


class HTTPProvider:
    """Light blocks over a full node's JSON-RPC (reference
    light/provider/http/http.go): /commit gives the signed header,
    /validators the matching set; LightBlock.validate_basic binds them
    via the header's validators_hash."""

    def __init__(self, chain_id: str, rpc_client):
        self._chain_id = chain_id
        self._rpc = rpc_client
        # deterministic backoff jitter (global-rng rule: seeded draws
        # replay; the chain id de-phases providers without entropy)
        self._rng = random.Random(f"light-provider:{chain_id}")

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        from ..rpc.client import RPCClientError
        from ..rpc.codec import (commit_from_json, header_from_json,
                                 validator_set_from_json)
        try:
            # each fetch retries transient socket failures with
            # jittered backoff BEFORE the whole verify gives up: a
            # bisection is many fetches, and one flaky one must not
            # void the verified prefix
            c = retry_transient(
                lambda: self._rpc.commit(height if height else None),
                self._rng)
            sh = SignedHeader(
                header_from_json(c["signed_header"]["header"]),
                commit_from_json(c["signed_header"]["commit"]))
            # the route is paginated (reference http provider walks
            # pages the same way); the FULL set is needed — a truncated
            # one can never match the header's validators_hash
            vals = validator_set_from_json(retry_transient(
                lambda: fetch_all_validators(self._rpc,
                                             height=sh.height),
                self._rng))
        except (RPCClientError, OSError, KeyError, ValueError) as e:
            raise ErrLightBlockNotFound(
                f"height {height}: {e}") from e
        return LightBlock(sh, vals)

    def report_evidence(self, ev) -> None:
        """reference light/provider/http ReportEvidence: hand detector
        evidence to the full node's /broadcast_evidence route, whence
        the evidence reactor gossips it to every proposer. Failures
        surface as ProviderError — the detector's _report treats that
        as best-effort (light/client.py), while direct callers see the
        actual rejection. ValueError covers a byzantine endpoint
        answering 200 with a non-JSON body (same defense as
        light_block above)."""
        from ..rpc.client import RPCClientError
        try:
            self._rpc.call("broadcast_evidence",
                           evidence=ev.encode().hex())
        except (RPCClientError, OSError, KeyError, ValueError) as e:
            raise ProviderError(f"report_evidence: {e}") from e
