"""Light-block providers (reference light/provider/provider.go interface,
light/provider/http, light/provider/mock).

`BlockStoreProvider` serves light blocks straight from a full node's
BlockStore + StateStore — the in-process analog of the RPC provider, and
what the `light/client_benchmark_test.go:24` mock provider does with its
1000-block chain.
"""

from __future__ import annotations

from typing import Optional, Protocol

from .types import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class ErrLightBlockNotFound(ProviderError):
    pass


class Provider(Protocol):
    """reference light/provider/provider.go:9-32."""

    def chain_id(self) -> str: ...
    def light_block(self, height: int) -> LightBlock:
        """height 0 means latest. Raises ProviderError."""


class BlockStoreProvider:
    def __init__(self, chain_id: str, block_store, state_store):
        self._chain_id = chain_id
        self._blocks = block_store
        self._states = state_store

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self._blocks.height()
        meta = self._blocks.load_block_meta(height)
        blk = self._blocks.load_block(height)
        commit = self._blocks.load_block_commit(height)
        vals = self._states.load_validators(height)
        if blk is None or commit is None or vals is None:
            raise ErrLightBlockNotFound(
                f"no light block at height {height}")
        return LightBlock(SignedHeader(blk.header, commit), vals)


def fetch_all_validators(rpc_client, height=None, max_pages=64):
    """Merge the paginated /validators pages into one response dict.

    Hardened for the light client's adversary model: later pages are
    PINNED to page 1's block_height (unpinned 'latest' pages could
    straddle a height change and merge two sets — a spurious hash
    failure against an honest primary), an empty page stops the walk
    (no progress), and max_pages bounds it (a byzantine primary
    advertising total=10^9 must not hang the caller; 64 pages × 100 =
    6400 validators, far above any real set). 'count' reflects the
    merged list."""
    merged = None
    page = 1
    while page <= max_pages:
        kw = {"page": page, "per_page": 100}
        if height is not None:
            kw["height"] = height
        r = rpc_client.call("validators", **kw)
        if merged is None:
            merged = r
            height = r.get("block_height", height)  # pin later pages
        else:
            if not r.get("validators"):
                break
            merged["validators"].extend(r["validators"])
        if len(merged["validators"]) >= r.get(
                "total", len(merged["validators"])):
            break
        page += 1
    merged["count"] = len(merged["validators"])
    return merged


class HTTPProvider:
    """Light blocks over a full node's JSON-RPC (reference
    light/provider/http/http.go): /commit gives the signed header,
    /validators the matching set; LightBlock.validate_basic binds them
    via the header's validators_hash."""

    def __init__(self, chain_id: str, rpc_client):
        self._chain_id = chain_id
        self._rpc = rpc_client

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        from ..rpc.client import RPCClientError
        from ..rpc.codec import (commit_from_json, header_from_json,
                                 validator_set_from_json)
        try:
            c = self._rpc.commit(height if height else None)
            sh = SignedHeader(
                header_from_json(c["signed_header"]["header"]),
                commit_from_json(c["signed_header"]["commit"]))
            # the route is paginated (reference http provider walks
            # pages the same way); the FULL set is needed — a truncated
            # one can never match the header's validators_hash
            vals = validator_set_from_json(
                fetch_all_validators(self._rpc, height=sh.height))
        except (RPCClientError, OSError, KeyError, ValueError) as e:
            raise ErrLightBlockNotFound(
                f"height {height}: {e}") from e
        return LightBlock(sh, vals)

    def report_evidence(self, ev) -> None:
        """reference light/provider/http ReportEvidence: hand detector
        evidence to the full node's /broadcast_evidence route, whence
        the evidence reactor gossips it to every proposer. Failures
        surface as ProviderError — the detector's _report treats that
        as best-effort (light/client.py), while direct callers see the
        actual rejection. ValueError covers a byzantine endpoint
        answering 200 with a non-JSON body (same defense as
        light_block above)."""
        from ..rpc.client import RPCClientError
        try:
            self._rpc.call("broadcast_evidence",
                           evidence=ev.encode().hex())
        except (RPCClientError, OSError, KeyError, ValueError) as e:
            raise ProviderError(f"report_evidence: {e}") from e
