"""Light-block providers (reference light/provider/provider.go interface,
light/provider/http, light/provider/mock).

`BlockStoreProvider` serves light blocks straight from a full node's
BlockStore + StateStore — the in-process analog of the RPC provider, and
what the `light/client_benchmark_test.go:24` mock provider does with its
1000-block chain.
"""

from __future__ import annotations

from typing import Optional, Protocol

from .types import LightBlock, SignedHeader


class ProviderError(Exception):
    pass


class ErrLightBlockNotFound(ProviderError):
    pass


class Provider(Protocol):
    """reference light/provider/provider.go:9-32."""

    def chain_id(self) -> str: ...
    def light_block(self, height: int) -> LightBlock:
        """height 0 means latest. Raises ProviderError."""


class BlockStoreProvider:
    def __init__(self, chain_id: str, block_store, state_store):
        self._chain_id = chain_id
        self._blocks = block_store
        self._states = state_store

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        if height == 0:
            height = self._blocks.height()
        meta = self._blocks.load_block_meta(height)
        blk = self._blocks.load_block(height)
        commit = self._blocks.load_block_commit(height)
        vals = self._states.load_validators(height)
        if blk is None or commit is None or vals is None:
            raise ErrLightBlockNotFound(
                f"no light block at height {height}")
        return LightBlock(SignedHeader(blk.header, commit), vals)


class HTTPProvider:
    """Light blocks over a full node's JSON-RPC (reference
    light/provider/http/http.go): /commit gives the signed header,
    /validators the matching set; LightBlock.validate_basic binds them
    via the header's validators_hash."""

    def __init__(self, chain_id: str, rpc_client):
        self._chain_id = chain_id
        self._rpc = rpc_client

    def chain_id(self) -> str:
        return self._chain_id

    def light_block(self, height: int) -> LightBlock:
        from ..rpc.client import RPCClientError
        from ..rpc.codec import (commit_from_json, header_from_json,
                                 validator_set_from_json)
        try:
            c = self._rpc.commit(height if height else None)
            sh = SignedHeader(
                header_from_json(c["signed_header"]["header"]),
                commit_from_json(c["signed_header"]["commit"]))
            vals = validator_set_from_json(
                self._rpc.validators(sh.height))
        except (RPCClientError, OSError, KeyError, ValueError) as e:
            raise ErrLightBlockNotFound(
                f"height {height}: {e}") from e
        return LightBlock(sh, vals)
