"""Stateless light-client verification core (reference
light/verifier.go:30-145).

Two modes:
- `verify_adjacent` (heights differ by 1): the untrusted header's
  validators_hash must equal the trusted header's next_validators_hash —
  then one VerifyCommitLight over the known set.
- `verify_non_adjacent` (bisection jumps): the TRUSTED set must have
  signed with >= trust_level (default 1/3) power (VerifyCommitLightTrusting),
  AND the untrusted set must have +2/3 on its own commit.

Both go through the same batch-verify seam as consensus/blocksync — on
bulk catch-up the signatures tile onto the TPU kernel.
"""

from __future__ import annotations

from ..types import validation
from ..types.proto import Timestamp
from .types import LightBlock, LightBlockError

# reference light/verifier.go defaultMaxClockDrift
MAX_CLOCK_DRIFT_SECONDS = 10


class VerificationError(Exception):
    pass


class ErrOldHeader(VerificationError):
    pass


class ErrNewValSetCantBeTrusted(VerificationError):
    """Not enough trusted power signed the new header — bisect."""


class ErrInvalidHeader(VerificationError):
    pass


def _expired(trusted: LightBlock, trusting_period_s: int,
             now: Timestamp) -> bool:
    """reference light/verifier.go:204 HeaderExpired."""
    t = trusted.header.time
    return t.seconds + trusting_period_s < now.seconds


def _validate_untrusted(chain_id: str, trusted: LightBlock,
                        untrusted: LightBlock, now: Timestamp,
                        max_drift_s: int) -> None:
    """reference light/verifier.go:149-201 verifyNewHeaderAndVals."""
    try:
        untrusted.validate_basic(chain_id)
    except LightBlockError as e:
        raise ErrInvalidHeader(str(e)) from e
    if untrusted.height <= trusted.height:
        raise ErrInvalidHeader(
            f"untrusted height {untrusted.height} <= trusted "
            f"{trusted.height}")
    if untrusted.header.time <= trusted.header.time:
        raise ErrInvalidHeader("untrusted header time not after trusted")
    if untrusted.header.time.seconds > now.seconds + max_drift_s:
        raise ErrInvalidHeader("untrusted header is from the future")


def verify_adjacent(chain_id: str, trusted: LightBlock,
                    untrusted: LightBlock, trusting_period_s: int,
                    now: Timestamp,
                    max_drift_s: int = MAX_CLOCK_DRIFT_SECONDS) -> None:
    """reference light/verifier.go:91-143 VerifyAdjacent."""
    if untrusted.height != trusted.height + 1:
        raise ErrInvalidHeader("headers must be adjacent in height")
    if _expired(trusted, trusting_period_s, now):
        raise ErrOldHeader("trusted header expired")
    _validate_untrusted(chain_id, trusted, untrusted, now, max_drift_s)
    if untrusted.header.validators_hash != \
            trusted.header.next_validators_hash:
        raise ErrInvalidHeader(
            "untrusted validators_hash != trusted next_validators_hash")
    try:
        validation.verify_commit_light(
            chain_id, untrusted.validator_set,
            untrusted.signed_header.commit.block_id,
            untrusted.height, untrusted.signed_header.commit)
    except validation.CommitVerificationError as e:
        raise ErrInvalidHeader(f"invalid commit: {e}") from e


def verify_non_adjacent(chain_id: str, trusted: LightBlock,
                        untrusted: LightBlock, trusting_period_s: int,
                        now: Timestamp,
                        trust_level: validation.Fraction =
                        validation.DEFAULT_TRUST_LEVEL,
                        max_drift_s: int = MAX_CLOCK_DRIFT_SECONDS) -> None:
    """reference light/verifier.go:30-88 VerifyNonAdjacent."""
    if untrusted.height == trusted.height + 1:
        raise ErrInvalidHeader("use verify_adjacent for adjacent headers")
    if _expired(trusted, trusting_period_s, now):
        raise ErrOldHeader("trusted header expired")
    _validate_untrusted(chain_id, trusted, untrusted, now, max_drift_s)
    try:
        validation.verify_commit_light_trusting(
            chain_id, trusted.validator_set,
            untrusted.signed_header.commit, trust_level)
    except validation.ErrNotEnoughVotingPowerSigned as e:
        raise ErrNewValSetCantBeTrusted(str(e)) from e
    except validation.CommitVerificationError as e:
        raise ErrInvalidHeader(f"trusting verify failed: {e}") from e
    try:
        validation.verify_commit_light(
            chain_id, untrusted.validator_set,
            untrusted.signed_header.commit.block_id,
            untrusted.height, untrusted.signed_header.commit)
    except validation.CommitVerificationError as e:
        raise ErrInvalidHeader(f"invalid commit: {e}") from e


def verify(chain_id: str, trusted: LightBlock, untrusted: LightBlock,
           trusting_period_s: int, now: Timestamp,
           trust_level: validation.Fraction =
           validation.DEFAULT_TRUST_LEVEL) -> None:
    """reference light/verifier.go Verify: dispatch on adjacency."""
    if untrusted.height == trusted.height + 1:
        verify_adjacent(chain_id, trusted, untrusted, trusting_period_s,
                        now)
    else:
        verify_non_adjacent(chain_id, trusted, untrusted,
                            trusting_period_s, now, trust_level)
