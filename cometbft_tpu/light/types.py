"""Light-client data types (reference types/light.go).

A LightBlock is the minimum a light client needs: a SignedHeader
(header + the commit that sealed it) and the validator set that signed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..types.block import Commit, Header
from ..types.validator import ValidatorSet


class LightBlockError(Exception):
    pass


@dataclass
class SignedHeader:
    """reference types/block.go:1430 SignedHeader."""
    header: Header
    commit: Commit

    def validate_basic(self, chain_id: str) -> None:
        """reference types/block.go:1445-1477."""
        if self.header is None:
            raise LightBlockError("missing header")
        if self.commit is None:
            raise LightBlockError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise LightBlockError(
                f"header chain id {self.header.chain_id} != {chain_id}")
        if self.commit.height != self.header.height:
            raise LightBlockError(
                f"commit height {self.commit.height} != header height "
                f"{self.header.height}")
        if self.commit.block_id.hash != self.header.hash():
            raise LightBlockError("commit signs a different header hash")

    @property
    def height(self) -> int:
        return self.header.height


@dataclass
class LightBlock:
    """reference types/light.go:14."""
    signed_header: SignedHeader
    validator_set: ValidatorSet

    def validate_basic(self, chain_id: str) -> None:
        """reference types/light.go:55-79."""
        if self.validator_set is None:
            raise LightBlockError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        if self.signed_header.header.validators_hash != \
                self.validator_set.hash():
            raise LightBlockError(
                "validator set does not match header validators_hash")

    @property
    def height(self) -> int:
        return self.signed_header.height

    @property
    def header(self) -> Header:
        return self.signed_header.header
