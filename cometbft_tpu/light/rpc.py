"""Light RPC proxy: a JSON-RPC endpoint whose answers are verified
against light-client-checked headers before being returned (reference
light/rpc/client.go Client + light/proxy/proxy.go Proxy).

The verifying client forwards reads to a full node and proves them:
- `abci_query` demands a merkle proof and checks it against the
  light-verified app hash (header at query-height+1 — the app hash in a
  header is the result of executing the PREVIOUS block, reference
  light/rpc/client.go ABCIQueryWithOptions);
- `block` / `commit` / `header` check the primary's bytes hash to the
  light-verified header for that height;
- `validators` must hash to the verified header's validators_hash.

The proof leaf contract for `abci_query` is the injective
`0x01 || len(key)_u32be || key || value` form of
`KVStoreApplication.kv_leaf`; apps with provable state expose the same
shape (the reference's analog is its registered merkle ProofRuntime op
set).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..crypto.merkle import AbsenceProof, Proof
from ..rpc.client import RPCClient
from ..rpc.codec import (commit_from_json, header_from_json,
                         proof_from_json, validator_set_from_json)
from ..rpc.server import RPCError, RPCServer
from .client import LightClient


class VerificationFailed(Exception):
    pass


class VerifyingClient:
    """reference light/rpc/client.go Client."""

    def __init__(self, light: LightClient, primary: RPCClient):
        self.light = light
        self.primary = primary

    # --- verified reads -------------------------------------------------------

    def abci_query(self, path: str, data: bytes) -> Dict:
        r = self.primary.abci_query_prove(path, data)
        if r.get("code", 0) != 0:
            return r
        value = bytes.fromhex(r.get("value", ""))
        height = int(r.get("height", 0))
        try:
            proof = proof_from_json(r.get("proof"))
        except (ValueError, KeyError, TypeError) as e:
            raise VerificationFailed(f"malformed proof: {e}")
        if proof is None or height <= 0:
            # a proofless empty value is the key-hiding attack the
            # reference rejects via VerifyAbsence (light/rpc/client.go:
            # 149,182) — never pass it through as a normal OK result
            raise VerificationFailed(
                "primary returned no proof"
                + (" (unverified absence)" if not value else ""))
        lb = self.light.verify_light_block_at_height(height + 1)
        from ..abci.kvstore import KVStoreApplication
        if not value:
            self._verify_absence(proof, lb.header.app_hash, data, height)
            return r
        if isinstance(proof, AbsenceProof):
            raise VerificationFailed(
                "primary sent an absence proof with a non-empty value")
        leaf = KVStoreApplication.kv_leaf(data, value)
        if not proof.verify(lb.header.app_hash, leaf):
            raise VerificationFailed(
                f"query proof does not match app hash at {height + 1}")
        return r

    @staticmethod
    def _verify_absence(proof, app_hash: bytes, data: bytes,
                        height: int) -> None:
        """Check an AbsenceProof really brackets `data`: both neighbors
        are adjacent leaves of the verified tree, the left one sorts
        before the key (or is the index-0 height sentinel for the
        proven height), the right one after it (or the left neighbor is
        the final leaf). Reference analog: light/rpc/client.go:182
        VerifyAbsence over the registered proof runtime."""
        from ..abci.kvstore import KVStoreApplication
        if not isinstance(proof, AbsenceProof):
            raise VerificationFailed(
                "empty value requires an absence proof")
        if not proof.verify_adjacent(app_hash):
            raise VerificationFailed(
                "absence proof neighbors not adjacent in verified tree")
        left_kv = KVStoreApplication.parse_kv_leaf(proof.left_leaf)
        if proof.left.index == 0:
            sentinel = b"\x00" + height.to_bytes(8, "big")
            if proof.left_leaf != sentinel:
                raise VerificationFailed(
                    "absence proof left sentinel is not the height leaf")
        elif left_kv is None or left_kv[0] >= data:
            raise VerificationFailed(
                "absence proof left neighbor does not sort before key")
        if proof.right is not None:
            right_kv = KVStoreApplication.parse_kv_leaf(proof.right_leaf)
            if right_kv is None or right_kv[0] <= data:
                raise VerificationFailed(
                    "absence proof right neighbor does not sort after key")

    def block(self, height: Optional[int] = None) -> Dict:
        r = self.primary.block(height)
        hdr = header_from_json(r["block"]["header"])
        lb = self.light.verify_light_block_at_height(hdr.height)
        if hdr.hash() != lb.header.hash():
            raise VerificationFailed(
                f"primary block header at {hdr.height} does not match "
                f"verified header")
        if bytes.fromhex(r["block_id"]["hash"]) != lb.header.hash():
            raise VerificationFailed("primary block_id mismatch")
        # the header hash only pins the header; the tx list must hash to
        # its data_hash or the primary can attach forged transactions
        from ..types.block import Data
        txs = [bytes.fromhex(t) for t in r["block"]["data"]["txs"]]
        if Data(txs).hash() != lb.header.data_hash:
            raise VerificationFailed(
                "primary block txs do not hash to the verified data_hash")
        return r

    def header(self, height: Optional[int] = None) -> Dict:
        r = self.primary.header(height)
        hdr = header_from_json(r["header"])
        lb = self.light.verify_light_block_at_height(hdr.height)
        if hdr.hash() != lb.header.hash():
            raise VerificationFailed("header mismatch")
        return r

    def commit(self, height: Optional[int] = None) -> Dict:
        r = self.primary.commit(height)
        sh = r["signed_header"]
        hdr = header_from_json(sh["header"])
        commit = commit_from_json(sh["commit"])
        lb = self.light.verify_light_block_at_height(hdr.height)
        if hdr.hash() != lb.header.hash():
            raise VerificationFailed("commit header mismatch")
        if commit.block_id.hash != lb.header.hash():
            raise VerificationFailed("commit is for a different block")
        # a consumer uses this as a signed-header source, so the
        # signatures themselves must carry 2/3 of the verified set —
        # block-id equality alone would relay forged signature lists
        from ..types import validation
        try:
            validation.verify_commit_light(
                self.light.chain_id, lb.validator_set, commit.block_id,
                hdr.height, commit)
        except Exception as e:  # noqa: BLE001 — any verify error
            raise VerificationFailed(f"commit signatures invalid: {e}")
        return r

    def validators(self, height: Optional[int] = None) -> Dict:
        # page through (bounded, height-pinned): the hash check below
        # needs the FULL set at ONE height
        from .provider import fetch_all_validators
        r = fetch_all_validators(self.primary, height=height)
        vals = validator_set_from_json(r)
        h = int(r.get("block_height", 0))
        if h <= 0:
            raise VerificationFailed("primary omitted block_height")
        lb = self.light.verify_light_block_at_height(h)
        if vals.hash() != lb.header.validators_hash:
            raise VerificationFailed(
                "primary validators do not hash to verified header")
        return r

    # --- passthroughs (unverifiable by nature) -------------------------------

    def status(self) -> Dict:
        return self.primary.status()

    def broadcast_tx_sync(self, tx: bytes) -> Dict:
        return self.primary.broadcast_tx_sync(tx)


class LightProxy:
    """reference light/proxy/proxy.go: the verifying client served back
    out as a JSON-RPC endpoint (same server conventions as rpc/server)."""

    def __init__(self, client: VerifyingClient, host: str = "127.0.0.1",
                 port: int = 0):
        c = client

        def _wrap(fn):
            def call(**kw):
                try:
                    return fn(**kw)
                except VerificationFailed as e:
                    raise RPCError(-32001, f"verification failed: {e}")
            return call

        methods = {
            "health": lambda: {},
            "status": _wrap(lambda: c.status()),
            "abci_query": _wrap(
                lambda path="", data="", prove=True:
                c.abci_query(path, bytes.fromhex(data))),
            "block": _wrap(
                lambda height=None: c.block(_h(height))),
            "header": _wrap(
                lambda height=None: c.header(_h(height))),
            "commit": _wrap(
                lambda height=None: c.commit(_h(height))),
            "validators": _wrap(
                lambda height=None: c.validators(_h(height))),
            "broadcast_tx_sync": _wrap(
                lambda tx="": c.broadcast_tx_sync(bytes.fromhex(tx))),
        }
        self._server = RPCServer(None, host, port, methods=methods)
        self.addr = self._server.addr

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop()


def _h(height) -> Optional[int]:
    return None if height is None else int(height)
