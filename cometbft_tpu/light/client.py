"""Light client: sequential + skipping (bisection) verification with
witness cross-checking (reference light/client.go:473,612,705,
light/detector.go).

The third north-star call site: on a 10k-header catch-up, each header's
commit flows through the same batch-verify seam the blocksync tile uses,
so bulk light verification rides the TPU kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..types.proto import Timestamp
from ..types import validation
from . import verifier
from .provider import Provider, ProviderError
from .store import LightStore
from .types import LightBlock, LightBlockError


class LightClientError(Exception):
    pass


class ErrNoWitnesses(LightClientError):
    pass


@dataclass
class ConflictingHeadersError(LightClientError):
    """A witness returned a different header for a verified height — the
    divergence the detector reports as a light-client attack (reference
    light/detector.go:21-92). Carries the constructed
    LightClientAttackEvidence (reference detector.go
    newLightClientAttackEvidence → provider ReportEvidence)."""
    primary: LightBlock
    witness: LightBlock
    witness_index: int
    evidence: object = None

    def __str__(self) -> str:
        return (f"witness {self.witness_index} disagrees at height "
                f"{self.primary.height}")


@dataclass
class TrustOptions:
    """reference light/client.go:58-90."""
    period_seconds: int
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period_seconds <= 0:
            raise LightClientError("trusting period must be positive")
        if self.height <= 0:
            raise LightClientError("trusted height must be positive")
        if len(self.hash) != 32:
            raise LightClientError("trusted hash must be 32 bytes")


class LightClient:
    """reference light/client.go Client (sequential=False selects
    skipping/bisection, the default)."""

    def __init__(self, chain_id: str, trust_options: TrustOptions,
                 primary: Provider, witnesses: List[Provider],
                 store: LightStore, sequential: bool = False,
                 trust_level: validation.Fraction =
                 validation.DEFAULT_TRUST_LEVEL,
                 now_fn=Timestamp.now):
        trust_options.validate()
        self.chain_id = chain_id
        self.trusting_period = trust_options.period_seconds
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = store
        self.sequential = sequential
        self.trust_level = trust_level
        self._now = now_fn
        self._initialize(trust_options)

    def _initialize(self, opts: TrustOptions) -> None:
        """Fetch + pin the trust root (reference client.go:388-470
        initializeWithTrustOptions)."""
        existing = self.store.light_block(opts.height)
        if existing is not None:
            if existing.header.hash() != opts.hash:
                raise LightClientError(
                    "trusted hash does not match stored header")
            return
        lb = self.primary.light_block(opts.height)
        lb.validate_basic(self.chain_id)
        if lb.header.hash() != opts.hash:
            raise LightClientError(
                f"primary returned header hash "
                f"{lb.header.hash().hex()[:16]} != trusted "
                f"{opts.hash.hex()[:16]}")
        # the set that signed must be the one committed to by the header
        validation.verify_commit_light(
            self.chain_id, lb.validator_set,
            lb.signed_header.commit.block_id, lb.height,
            lb.signed_header.commit)
        self.store.save_light_block(lb)

    # --- public API -----------------------------------------------------------

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block(height)

    def latest_trusted(self) -> Optional[LightBlock]:
        return self.store.latest()

    def update(self, now: Optional[Timestamp] = None) -> LightBlock:
        """Verify the primary's latest header (reference client.go:506)."""
        latest = self.primary.light_block(0)
        return self.verify_light_block_at_height(latest.height, now)

    def verify_light_block_at_height(self, height: int,
                                     now: Optional[Timestamp] = None
                                     ) -> LightBlock:
        """reference light/client.go:473-504."""
        now = now or self._now()
        got = self.store.light_block(height)
        if got is not None:
            return got
        latest = self.store.latest()
        if latest is None:
            raise LightClientError("store empty — client not initialized")
        if height < latest.height:
            # backwards verification (reference client.go:934): walk the
            # hash links down from the closest trusted header
            return self._verify_backwards(height)
        lb = self.primary.light_block(height)
        lb.validate_basic(self.chain_id)
        if self.sequential:
            self._verify_sequential(latest, lb, now)
        else:
            self._verify_skipping(latest, lb, now)
        self._cross_check(lb)
        self.store.save_light_block(lb)
        return lb

    # --- verification strategies ----------------------------------------------

    def _verify_sequential(self, trusted: LightBlock, target: LightBlock,
                           now: Timestamp) -> None:
        """reference light/client.go:612-668: fetch and verify EVERY
        header between trusted and target."""
        cur = trusted
        for h in range(trusted.height + 1, target.height + 1):
            nxt = (target if h == target.height
                   else self.primary.light_block(h))
            nxt.validate_basic(self.chain_id)
            verifier.verify_adjacent(
                self.chain_id, cur, nxt, self.trusting_period, now)
            self.store.save_light_block(nxt)
            cur = nxt

    def _verify_skipping(self, trusted: LightBlock, target: LightBlock,
                         now: Timestamp) -> None:
        """Bisection (reference light/client.go:705-772 verifySkipping):
        try the jump; when the trusted set can't vouch (<1/3 overlap),
        bisect toward the trusted header until it can."""
        cur = trusted
        pivots = [target]
        while pivots:
            candidate = pivots[-1]
            try:
                if candidate.height == cur.height + 1:
                    verifier.verify_adjacent(
                        self.chain_id, cur, candidate,
                        self.trusting_period, now)
                else:
                    verifier.verify_non_adjacent(
                        self.chain_id, cur, candidate,
                        self.trusting_period, now, self.trust_level)
            except verifier.ErrNewValSetCantBeTrusted:
                mid = (cur.height + candidate.height) // 2
                if mid in (cur.height, candidate.height):
                    raise LightClientError(
                        "bisection cannot make progress")
                lb = self.primary.light_block(mid)
                lb.validate_basic(self.chain_id)
                pivots.append(lb)
                continue
            self.store.save_light_block(candidate)
            cur = candidate
            pivots.pop()

    def _verify_backwards(self, height: int) -> LightBlock:
        """Hash-linked walk down from the closest trusted header above
        (client.go:934-988)."""
        cur = self.store.lowest_above(height)
        while cur is not None and cur.height > height:
            prev = self.primary.light_block(cur.height - 1)
            prev.validate_basic(self.chain_id)
            if cur.header.last_block_id.hash != prev.header.hash():
                raise LightClientError(
                    f"backwards hash mismatch at {prev.height}")
            self.store.save_light_block(prev)
            cur = prev
        if cur is None or cur.height != height:
            raise LightClientError(f"cannot reach height {height}")
        return cur

    # --- detector ---------------------------------------------------------------

    def _cross_check(self, lb: LightBlock) -> None:
        """Compare the verified header against every witness (reference
        light/detector.go:21-92, compareNewHeaderWithWitness). On
        divergence, build LightClientAttackEvidence against the highest
        trusted (common) header below the conflict and report it to the
        witnesses that can act on it (detector.go ReportEvidence)."""
        for i, w in enumerate(self.witnesses):
            try:
                other = w.light_block(lb.height)
            except ProviderError:
                continue  # witness lagging — reference retries/drops
            if other.header.hash() != lb.header.hash():
                # the disputed header must not stay trusted: the verify
                # strategies saved it before this cross-check ran, and a
                # stored block short-circuits all future verification
                self.store.delete(lb.height)
                # the anchor must be a header BOTH sides share — recent
                # stored headers came from the (possibly lying) primary,
                # so walk down until the witness agrees, evicting every
                # primary-only header passed on the way (the reference
                # detector walks its trace the same way,
                # detector.go examineConflictingHeaderAgainstTrace)
                common = self._common_anchor(w, lb.height)
                ev_witness = self._make_attack_evidence(other, common,
                                                        counterpart=lb)
                ev_primary = self._make_attack_evidence(lb, common,
                                                        counterpart=other)
                self._report(self.primary, ev_witness)
                self._report(w, ev_primary)
                raise ConflictingHeadersError(lb, other, i,
                                              evidence=ev_witness)

    def _common_anchor(self, witness: Provider,
                       below: int) -> Optional[LightBlock]:
        """Highest stored block below `below` whose hash the witness
        confirms; stored blocks the witness disputes (headers only the
        primary vouched for) are evicted rather than trusted."""
        while True:
            cand = self.store.highest_below(below)
            if cand is None:
                return None
            try:
                theirs = witness.light_block(cand.height)
                if theirs.header.hash() == cand.header.hash():
                    return cand
            except ProviderError:
                return cand  # witness can't say; keep the stored anchor
            self.store.delete(cand.height)
            below = cand.height

    @staticmethod
    def _report(provider, evidence) -> None:
        if evidence is None:
            return
        report = getattr(provider, "report_evidence", None)
        if report is not None:
            try:
                report(evidence)
            except ProviderError:
                pass

    def _make_attack_evidence(self, conflicting: LightBlock, common,
                              counterpart: LightBlock = None):
        """Evidence anchored at the highest trusted height below the
        conflict (the common header, detector.go:169).

        The byzantine list MUST use the same per-attack-style formula
        full nodes verify with (evidence/pool.py
        expected_byzantine_validators — lunatic / equivocation /
        amnesia, reference types/evidence.go:250-300): a list built
        with the lunatic formula for a non-lunatic attack would fail
        every pool's completeness check and the genuine evidence would
        be dropped network-wide. `counterpart` is the block the honest
        side holds at the same height (classifies the style)."""
        from ..evidence.pool import expected_byzantine_validators
        from ..types.evidence import LightClientAttackEvidence
        if common is None:
            return None
        ev = LightClientAttackEvidence(
            conflicting_block=conflicting,
            common_height=common.height,
            byzantine_validators=[],
            total_voting_power=common.validator_set.total_voting_power(),
            timestamp=common.header.time)
        byz = expected_byzantine_validators(
            ev, common.validator_set,
            counterpart.header if counterpart is not None else None,
            counterpart.signed_header.commit
            if counterpart is not None else None)
        if byz is None:
            # style undeterminable (no counterpart): fall back to the
            # lunatic formula — verifiers without the trusted block
            # skip completeness too
            signers = {cs.validator_address for cs in
                       conflicting.signed_header.commit.signatures
                       if cs.for_block()}
            byz = [v for v in common.validator_set.validators
                   if v.address in signers]
        ev.byzantine_validators = byz
        return ev
