"""Trusted light-block store (reference light/store/db/db.go) over the
KVStore seam — heights big-endian keyed so iteration is height-ordered.
"""

from __future__ import annotations

from typing import Optional

from ..state.state import _valset_from_json, _valset_to_json
from ..types.block import Commit, Header
from .types import LightBlock, SignedHeader
from ..types import proto

_PREFIX = b"lb:"
_END = _PREFIX + b"\xff" * 9  # past any 8-byte big-endian height key


def _key(height: int) -> bytes:
    return _PREFIX + height.to_bytes(8, "big")


class LightStore:
    def __init__(self, db):
        self._db = db

    def save_light_block(self, lb: LightBlock) -> None:
        body = (proto.f_embed(1, lb.signed_header.header.encode())
                + proto.f_embed(2, lb.signed_header.commit.encode())
                + proto.f_bytes(3, _valset_to_json(lb.validator_set)))
        self._db.set(_key(lb.height), body)

    def light_block(self, height: int) -> Optional[LightBlock]:
        raw = self._db.get(_key(height))
        if raw is None:
            return None
        f = proto.parse_fields(raw)
        return LightBlock(
            SignedHeader(Header.decode(proto.field_bytes(f, 1, b"")),
                         Commit.decode(proto.field_bytes(f, 2, b""))),
            _valset_from_json(proto.field_bytes(f, 3, b"")))

    def latest(self) -> Optional[LightBlock]:
        last = None
        for _k, _v in self._db.iterate(_PREFIX, _END):
            last = _k
        if last is None:
            return None
        return self.light_block(int.from_bytes(last[len(_PREFIX):], "big"))

    def lowest(self) -> Optional[LightBlock]:
        return self.lowest_above(0)

    def lowest_above(self, height: int) -> Optional[LightBlock]:
        """The lowest trusted block with height >= `height`."""
        for k, _v in self._db.iterate(_key(height), _END):
            return self.light_block(int.from_bytes(k[len(_PREFIX):], "big"))
        return None

    def highest_below(self, height: int) -> Optional[LightBlock]:
        """The highest trusted block with height < `height` (one ordered
        key scan, not per-height gets — the detector's common-anchor
        lookup)."""
        last = None
        for k, _v in self._db.iterate(_PREFIX, _key(height)):
            last = k
        if last is None:
            return None
        return self.light_block(int.from_bytes(last[len(_PREFIX):], "big"))

    def delete(self, height: int) -> None:
        """Evict a block (a detected-attack header must not stay
        trusted)."""
        self._db.delete(_key(height))

    def prune(self, keep: int) -> None:
        """Keep the `keep` highest blocks (reference db.go Prune)."""
        keys = [k for k, _ in self._db.iterate(_PREFIX, _END)]
        for k in keys[:max(0, len(keys) - keep)]:
            self._db.delete(k)
