from .client import LightClient, TrustOptions, LightClientError
from .types import LightBlock, SignedHeader
from .store import LightStore
from .provider import Provider, BlockStoreProvider

__all__ = ["LightClient", "TrustOptions", "LightClientError", "LightBlock",
           "SignedHeader", "LightStore", "Provider", "BlockStoreProvider"]
