"""pipeline/ — asynchronous multi-tile verification data plane.

The blocksync catch-up hot loop (engine/blocksync) is host-bound when
run synchronously: the host idles while the device verifies a tile and
the device idles while the host fetches/marshals/applies the next one.
This package keeps K tiles in flight instead:

- `scheduler.py` — bounded-queue staged scheduler (fetch → marshal →
  async device dispatch → sequential apply) plus the verify backends
  (in-process dispatch thread, device-server futures, bench/test stubs);
- `watchdog.py`  — per-dispatch deadlines with sticky device-wedge
  detection draining in-flight tiles to a CPU fallback;
- `cache.py`     — bounded verified-signature cache keyed by
  (pubkey, sign_bytes, sig), consulted by blocksync tiles, consensus
  vote intake, and light-client commit verification.

Only `cache` is imported eagerly (it is dependency-free and consulted
from types/); import `scheduler`/`watchdog` explicitly — they pull in
the engine layer.
"""

from .cache import SigCache, shared_cache  # noqa: F401
