"""Bounded-queue staged scheduler: K verification tiles in flight.

The synchronous blocksync loop (engine/blocksync._sync_tile) serializes
fetch → marshal → verify → apply, so the host idles while the device
verifies and the device idles while the host works. Here the stages
pipeline — the standard answer for verification engines (the FPGA ECDSA
engine of arXiv:2112.02229 overlaps decode/marshal with curve compute):

    fetch    — engine/pool.py lookahead keeps the wire busy already;
               the scheduler pulls whole tile ranges ahead of apply
    marshal  — engine/blocksync.marshal_commit (the lifted standalone
               form of TiledCommitVerifier._add_commit), run on the
               host for tile N+1 while tile N verifies
    dispatch — non-blocking submit to a verify backend: the in-process
               dispatch thread (LocalAsyncBackend — JAX device work for
               tile N overlaps host marshal of tile N+1), the device
               server's DeviceClient.submit() future seam, or a stub
    apply    — strictly SEQUENTIAL, in height order, with the same
               `_verified_seal` digest check and respeculation rules as
               the synchronous loop

Safety is unchanged from the synchronous path because apply is the only
stage that touches state, and it runs the identical per-height checks
(engine/blocksync._apply_one): speculative marshal across a validator-set
change re-verifies on hash mismatch exactly as the current tile loop
does. `depth=1` IS the synchronous path, one tile at a time.

Wedge handling: every dispatch is bounded by the DeviceWatchdog; a
deadline miss drains this and all in-flight tiles to the CPU fallback
(native per-signature verify) so a wedged TPU tunnel degrades catch-up
speed, never liveness. With a DeviceSupervisor attached (device/
health.py) the drain is no longer a one-way door: the scheduler probes
the suspect device with a cheap known-answer batch once per backoff
window and resumes device dispatch when the supervisor returns to
HEALTHY. The supervisor also arms canary lanes — a known-good and
known-bad signature spliced into every device batch and stripped from
the results; a canary verdict mismatch quarantines the device (terminal)
and re-verifies that whole batch on CPU, so device results are never
trusted un-canaried.
"""

from __future__ import annotations

import inspect
import queue
import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..device import health
from ..engine.blocksync import (BlocksyncReactor, SyncStalled,
                                TileApplyError, TileEntry, marshal_commit,
                                settle_tile, verify_lanes)
from ..libs.fail import fail_point
from ..state.execution import BlockValidationError
from ..state.state import State
from ..trace import shared_tracer


# --- futures + verify backends ------------------------------------------------

class VerifyFuture:
    """Minimal future for verify dispatches: result(timeout) returns the
    per-lane verdict sequence or raises (TimeoutError on deadline,
    whatever the backend set otherwise)."""

    def __init__(self):
        self._ev = threading.Event()
        self._out = None
        self._exc: Optional[BaseException] = None

    def set_result(self, out) -> None:
        self._out = out
        self._ev.set()

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._ev.wait(timeout):
            raise TimeoutError("verify dispatch still pending")
        if self._exc is not None:
            raise self._exc
        return self._out


class LocalAsyncBackend:
    """In-process async dispatch: one daemon thread runs the verify
    function (ops/ed25519 via engine/blocksync.verify_lanes) so
    submit() returns immediately — JAX device dispatch of tile N
    overlaps host marshal of tile N+1. A verify crash lands in the
    future as an exception; the watchdog turns it into a CPU fallback."""

    def __init__(self, verify_fn, name: str = "pipeline-verify"):
        self._verify = verify_fn
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def submit(self, pubs, msgs, sigs) -> VerifyFuture:
        fut = VerifyFuture()
        self._q.put((fut, pubs, msgs, sigs))
        return fut

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                fut, pubs, msgs, sigs = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                fut.set_result(self._verify(pubs, msgs, sigs))
            except BaseException as e:  # noqa: BLE001 — surface via future
                fut.set_exception(e)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
        # fail queued-but-undispatched work: a caller blocked in
        # result() with no timeout would otherwise hang forever on a
        # future the (now stopped) worker will never resolve
        while True:
            try:
                fut, _pubs, _msgs, _sigs = self._q.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                fut.set_exception(
                    ConnectionError("verify backend closed"))


class ReconnectBlocked(health.AccountedTransportError):
    """shared_client() could not produce a link: either the connect
    attempt failed (that failure already reported a trip to the
    supervisor) or the half-open backoff window is still closed (no
    attempt was made, so there is no new failure to report). Either
    way neither the dispatch fallback nor supervisor.probe() may
    report a second trip — doing so would double-count one outage and
    deepen the backoff twice."""


class DeviceClientBackend:
    """Dispatch to the host's TPU-owner device server through the
    non-blocking DeviceClient.submit() seam; result() adapts the
    (batch_ok, oks) wire answer to a plain verdict sequence."""

    class _Adapter:
        def __init__(self, fut):
            self._fut = fut

        def done(self) -> bool:
            return self._fut.done()

        def cancel(self) -> None:
            self._fut.cancel()

        def result(self, timeout: Optional[float] = None):
            _batch_ok, oks = self._fut.result(timeout)
            return oks

    def __init__(self, client):
        self._client = client

    def submit(self, pubs, msgs, sigs, ctx=None):
        c = self._client
        if c is None or c._dead is not None:
            # ride the supervisor-gated reconnect: shared_client()
            # drops dead links and honors the half-open backoff — this
            # is what lets a probe reach a RESTARTED device server
            # instead of re-trying the socket this backend was built on
            from ..device.client import shared_client
            c = shared_client()
            if c is None:
                raise ReconnectBlocked(
                    "device link down, no reconnect")
            self._client = c
        # ctx is an opt-in keyword: a reconnect can hand us any client
        # implementation (tests inject plain-signature stubs), so only
        # forward trace context to clients that declare it
        if ctx is not None and "ctx" in inspect.signature(
                c.submit).parameters:
            return self._Adapter(c.submit(pubs, msgs, sigs, ctx=ctx))
        return self._Adapter(c.submit(pubs, msgs, sigs))

    def close(self) -> None:
        pass  # the client is shared process-wide; never closed here


class FixedLatencyBackend:
    """Bench/test stub of an RTT-bound device: every dispatch answers a
    fixed latency after submit, independent of other in-flight
    dispatches (the tunnel's cost is dominated by round-trip + queueing,
    not lane occupancy). verify_fn=None answers all-true (valid-chain
    benchmarks); otherwise verdicts are computed in the timer thread."""

    def __init__(self, latency_s: float, verify_fn=None):
        self.latency_s = latency_s
        self._verify = verify_fn
        self.dispatches = 0

    def submit(self, pubs, msgs, sigs) -> VerifyFuture:
        self.dispatches += 1
        fut = VerifyFuture()

        def fire():
            try:
                out = (self._verify(pubs, msgs, sigs)
                       if self._verify is not None
                       else [True] * len(pubs))
                fut.set_result(out)
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
        t = threading.Timer(self.latency_s, fire)
        t.daemon = True
        t.start()
        return fut

    def close(self) -> None:
        pass


class HangingBackend:
    """The wedge fixture: dispatches never answer (until release())."""

    def __init__(self):
        self._pending: List[Tuple[VerifyFuture, int]] = []
        self.dispatches = 0

    def submit(self, pubs, msgs, sigs) -> VerifyFuture:
        self.dispatches += 1
        fut = VerifyFuture()
        self._pending.append((fut, len(pubs)))
        return fut

    def release(self) -> None:
        for fut, n in self._pending:
            if not fut.done():
                fut.set_result([True] * n)

    def close(self) -> None:
        self.release()  # unblock anything still waiting


class FlakyBackend:
    """Transient-stall fixture (the device-flap model): the first
    `fail_dispatches` submits raise ConnectionError, after which every
    submit answers synchronously with CPU-computed verdicts — so a
    supervisor's half-open probe succeeds once the flap passes and the
    scheduler resumes device dispatch. Synchronous resolution keeps
    simnet logs byte-identical (no wall-clock timer threads)."""

    def __init__(self, fail_dispatches: int = 1, verify_fn=None):
        self._verify = verify_fn or (
            lambda p, m, s: verify_lanes(p, m, s, 0))
        self.fail_left = fail_dispatches
        self.dispatches = 0
        self.served = 0  # successful answers (post-recovery activity)

    def submit(self, pubs, msgs, sigs) -> VerifyFuture:
        self.dispatches += 1
        if self.fail_left > 0:
            self.fail_left -= 1
            raise ConnectionError("device stalled (flap)")
        fut = VerifyFuture()
        fut.set_result(self._verify(pubs, msgs, sigs))
        self.served += 1
        return fut

    def close(self) -> None:
        pass


class CorruptBackend:
    """The silently-corrupt device model: answers every lane True
    regardless of the signature — exactly the failure a canary lane
    exists to catch (the known-bad canary comes back True). Answers
    synchronously for simnet determinism."""

    def __init__(self):
        self.dispatches = 0
        self.served = 0

    def submit(self, pubs, msgs, sigs) -> VerifyFuture:
        self.dispatches += 1
        self.served += 1
        fut = VerifyFuture()
        fut.set_result([True] * len(pubs))
        return fut

    def close(self) -> None:
        pass


# --- the scheduler ------------------------------------------------------------

@dataclass
class _Tile:
    start: int
    end: int
    fetched: Dict[int, tuple]
    entries: List[TileEntry]
    metas: list
    pubs: List[bytes]
    msgs: List[bytes]
    sigs: List[bytes]
    future: object = None            # None => out already final
    out: Optional[np.ndarray] = None
    valset_break: bool = False       # a header announced a new valset
    n_canaries: int = 0              # canary lanes appended at dispatch
    span: object = None              # trace span: build..settle lifetime

    @property
    def n_lanes(self) -> int:
        return len(self.pubs)


class PipelinedBlocksync:
    """Runs a BlocksyncReactor's catch-up with `depth` tiles in flight.

    Constructed by BlocksyncReactor.sync() when pipeline_depth > 1; the
    reactor owns source/executor/store/stats/_verified_seal so the two
    paths share every stage implementation and all bookkeeping."""

    def __init__(self, reactor: BlocksyncReactor, depth: int = 4,
                 backend=None, watchdog=None, metrics=None,
                 supervisor=None):
        self.r = reactor
        self._own_backend = backend is None
        self.backend = backend or LocalAsyncBackend(
            lambda p, m, s: verify_lanes(
                p, m, s, reactor.verifier.batch_size))
        # the bounded queue sizes from the backend's SHARD count: a
        # mesh backend (mesh/executor.MeshExecutor exposes n_shards)
        # needs K tiles in flight PER SHARD for the PR-2 pipeline win
        # and N-chip sharding to compose — depth alone would leave
        # N-1 shards idle between tiles. Single-chip backends report
        # (or default to) 1 shard and keep the old semantics exactly.
        # Clamped to the backend's bounded dispatch queue: a deep
        # pipeline_depth config must shrink here, not overflow the
        # executor into MeshOverloaded trips the watchdog would latch
        # as a wedge.
        shards = max(1, int(getattr(self.backend, "n_shards", 1)))
        depth = max(1, depth) * shards
        cap = getattr(self.backend, "queue_capacity", None)
        if isinstance(cap, int) and cap > 0:
            depth = min(depth, cap)
        self.depth = depth
        # ctx propagation is opt-in per backend (mesh + device client
        # backends take ctx=; the LocalAsyncBackend and injected test
        # backends keep their plain 3-arg submit) — decided once here
        self._backend_takes_ctx = (
            "ctx" in inspect.signature(self.backend.submit).parameters)
        self.watchdog = watchdog
        self.metrics = metrics
        self.supervisor = supervisor  # device/health.DeviceSupervisor
        if supervisor is not None and watchdog is not None \
                and watchdog.supervisor is None:
            watchdog.supervisor = supervisor

    def close(self) -> None:
        if self._own_backend:
            self.backend.close()

    # --- stages -----------------------------------------------------------

    def _build_tile(self, start: int, target: int, spec_vals) -> _Tile:
        """fetch + marshal + dispatch for one tile (raises SyncStalled
        when the source cannot serve the range)."""
        tracer = shared_tracer()
        tspan = tracer.start("pipeline.tile", start=start)
        try:
            return self._build_tile_traced(start, target, spec_vals,
                                           tracer, tspan)
        except BaseException:
            tspan.set_attr("outcome", "error")
            tspan.end()
            raise

    def _build_tile_traced(self, start, target, spec_vals, tracer,
                           tspan) -> _Tile:
        self._occupy("fetch", 1)
        try:
            with tracer.start("pipeline.fetch", parent=tspan):
                fetched, end = self.r._fetch_range(start, target)
        finally:
            self._occupy("fetch", 0)

        self._occupy("marshal", 1)
        marshal_span = tracer.start("pipeline.marshal", parent=tspan)
        try:
            spec_hash = spec_vals.hash()
            entries: List[TileEntry] = []
            valset_break = False
            for h in range(start, end + 1):
                block, _parts, bid = fetched[h]
                if block.header.validators_hash != spec_hash:
                    # valset changes: heights from here respeculate at
                    # apply against the true set, and the scheduler
                    # stops filling until the pipeline drains
                    valset_break = True
                    break
                entries.append(TileEntry(
                    height=h, block=block, block_id=bid, valset=spec_vals,
                    commit=fetched[h + 1][0].last_commit))
            pubs: List[bytes] = []
            msgs: List[bytes] = []
            sigs: List[bytes] = []
            metas = [marshal_commit(self.r.verifier.chain_id, e, pubs,
                                    msgs, sigs, self.r.cache)
                     for e in entries]
        finally:
            marshal_span.end()
            self._occupy("marshal", 0)

        tile = _Tile(start=start, end=end, fetched=fetched,
                     entries=entries, metas=metas, pubs=pubs, msgs=msgs,
                     sigs=sigs, valset_break=valset_break, span=tspan)
        tspan.set_attr("end", end)
        tspan.set_attr("lanes", len(pubs))
        if not pubs:
            tile.out = np.zeros((0,), dtype=bool)  # all cached/absent
        elif self._device_blocked():
            # wedged/suspect/quarantined (and no probe recovered it):
            # don't even dispatch — drain this tile straight to the CPU
            if self.watchdog is not None:
                self.watchdog._fallback()
            with tracer.start("pipeline.cpu_drain", parent=tspan,
                              reason="device-blocked"):
                tile.out = self._cpu_verify(pubs, msgs, sigs)
        else:
            d_pubs, d_msgs, d_sigs = pubs, msgs, sigs
            if self.supervisor is not None and self.supervisor.canary:
                # canary lanes ride every device batch; tile.pubs stays
                # canary-free for the CPU re-verify path
                d_pubs, d_msgs, d_sigs = health.splice_canaries(
                    pubs, msgs, sigs)
                tile.n_canaries = health.CANARY_LANES
            fail_point("pipeline:dispatch")
            try:
                if self._backend_takes_ctx:
                    tile.future = self.backend.submit(
                        d_pubs, d_msgs, d_sigs, ctx=tspan)
                else:
                    tile.future = self.backend.submit(
                        d_pubs, d_msgs, d_sigs)
            except Exception as e:  # noqa: BLE001 — a dead device link
                # at submit degrades exactly like a deadline miss;
                # ReconnectBlocked was already accounted inside
                # shared_client(), so only count the fallback for it
                tile.n_canaries = 0
                accounted = isinstance(e, health.AccountedTransportError)
                if self.watchdog is not None:
                    if not accounted:
                        self.watchdog._trip(e)
                    self.watchdog._fallback()
                elif self.supervisor is not None and not accounted:
                    self.supervisor.report_trip(e)
                with tracer.start("pipeline.cpu_drain", parent=tspan,
                                  reason="submit-error"):
                    tile.out = self._cpu_verify(pubs, msgs, sigs)
                return tile
            if self.metrics is not None:
                self.metrics.tiles_dispatched.inc()
        return tile

    def _device_blocked(self) -> bool:
        """Decide whether this tile may dispatch to the device. The
        supervisor path is half-open: a due probe runs ONE cheap
        known-answer batch against the backend; success resumes device
        dispatch immediately (this very tile)."""
        sup = self.supervisor
        if sup is None:
            return self.watchdog is not None and self.watchdog.wedged
        if sup.can_dispatch():
            return False
        if sup.probe_due():
            return not sup.probe(self._probe_verify)
        return True

    def _probe_verify(self, pubs, msgs, sigs):
        """supervisor.probe adapter: one backend round trip under the
        probe deadline; exceptions (timeout, transport) propagate to
        the supervisor, which deepens the backoff."""
        fut = self.backend.submit(pubs, msgs, sigs)
        try:
            return fut.result(self.supervisor.probe_deadline_s)
        except BaseException:
            cancel = getattr(fut, "cancel", None)
            if cancel is not None:
                cancel()
            raise

    @staticmethod
    def _cpu_verify(pubs, msgs, sigs) -> np.ndarray:
        # the watchdog's drain target: native per-sig verify, never a
        # device (or jit-compile) dependency
        return verify_lanes(pubs, msgs, sigs, 0)

    @staticmethod
    def _cancel(tile: "_Tile") -> None:
        """Abandon a dispatched tile's future (nothing will collect the
        answer — without this, DeviceClient retains late verdicts in
        _results forever)."""
        fut = tile.future
        if fut is not None:
            cancel = getattr(fut, "cancel", None)
            if cancel is not None:
                cancel()

    def _settle(self, tile: _Tile) -> None:
        """Resolve the tile's verdicts (waiting on the dispatch under
        the watchdog deadline; CPU fallback on wedge) and map them onto
        entry.commit_ok."""
        tracer = shared_tracer()
        sspan = tracer.start("pipeline.settle", parent=tile.span)
        try:
            if tile.out is None:
                total = tile.n_lanes + tile.n_canaries
                if self.watchdog is not None:
                    out = self.watchdog.result(tile.future, total)
                    if out is None:  # wedged: drain tile to the CPU
                        self._cancel(tile)
                        with tracer.start("pipeline.cpu_drain",
                                          parent=sspan,
                                          reason="watchdog-wedge"):
                            out = self._cpu_verify(
                                tile.pubs, tile.msgs, tile.sigs)
                    else:
                        out = self._canary_check(tile, out, sspan)
                else:
                    out = self._canary_check(tile, tile.future.result(),
                                             sspan)
                tile.out = np.asarray(out, dtype=bool)
            settle_tile(tile.metas, tile.out, tile.pubs, tile.msgs,
                        tile.sigs, self.r.cache)
            if tile.entries:
                self.r.stats.tiles_flushed += 1
                self.r.stats.sigs_verified += sum(
                    1 for e in tile.entries for cs in e.commit.signatures
                    if not cs.absent_())
        finally:
            sspan.end()
            if tile.span is not None:
                tile.span.end()
                tile.span = None

    def _canary_check(self, tile: _Tile, out, sspan=None):
        """Strip + verify this tile's canary lanes. A mismatch means
        the device returned corrupt VERDICTS (not a transport failure):
        quarantine it and re-verify the whole batch on CPU — a device
        answer is never trusted un-canaried. A correct answer reports
        success (PROBING → HEALTHY after a mid-probe full batch)."""
        if not tile.n_canaries:
            return out
        ok, stripped = health.check_canaries(out, tile.n_lanes)
        if ok:
            if self.supervisor is not None:
                self.supervisor.report_success()
            return stripped
        if sspan is not None:
            sspan.event("canary-failure", tile=tile.start)
        if self.supervisor is not None:
            self.supervisor.report_corruption(
                f"tile {tile.start}..{tile.end} canary mismatch")
        if self.watchdog is not None:
            self.watchdog._fallback()  # count the drain like a wedge
        with shared_tracer().start("pipeline.cpu_drain", parent=sspan,
                                   reason="canary-failure"):
            return self._cpu_verify(tile.pubs, tile.msgs, tile.sigs)

    def _occupy(self, stage: str, n: int) -> None:
        if self.metrics is not None:
            self.metrics.stage_occupancy.set(n, stage=stage)

    def _inflight_gauge(self, n: int) -> None:
        if self.metrics is not None:
            self.metrics.tiles_in_flight.set(n)
            self.metrics.stage_occupancy.set(n, stage="dispatch")

    # --- the run loop -----------------------------------------------------

    def run(self, state: State, target: int) -> State:
        """One catch-up pass: process tiles until target or failure.
        Mirrors _sync_tile's contract: on a bad block the peer is
        banned and either the partially-advanced state returns (caller
        retries the remainder) or BlockValidationError raises when
        nothing was applied this pass."""
        r = self.r
        inflight: "deque[_Tile]" = deque()
        spec_vals = state.validators
        next_start = state.last_block_height + 1
        applied_any = False
        barrier = False  # valset change seen: drain before refilling
        try:
            while state.last_block_height < target or inflight:
                # fill: keep up to `depth` tiles fetched+marshaled+
                # dispatched ahead of the apply stage
                while (not barrier and len(inflight) < self.depth
                       and next_start <= target):
                    try:
                        tile = self._build_tile(next_start, target,
                                                spec_vals)
                    except SyncStalled:
                        if not inflight:
                            raise
                        break  # drain what we have; refill retries fetch
                    inflight.append(tile)
                    next_start = tile.end + 1
                    if tile.valset_break:
                        barrier = True
                self._inflight_gauge(len(inflight))
                if not inflight:
                    if state.last_block_height >= target:
                        break
                    # barrier drained (or stall): resume speculation from
                    # the now-current validator set
                    barrier = False
                    spec_vals = state.validators
                    continue

                tile = inflight.popleft()
                self._inflight_gauge(len(inflight))
                self._settle(tile)
                self._occupy("apply", 1)
                try:
                    by_height = {e.height: e for e in tile.entries}
                    h = tile.start
                    while h <= tile.end:
                        block, parts, block_id = tile.fetched[h]
                        seal_commit = tile.fetched[h + 1][0].last_commit
                        try:
                            state = r._apply_one(
                                state, h, block, parts, block_id,
                                seal_commit, by_height.get(h))
                        except TileApplyError as f:
                            r.source.ban(h)
                            # drop everything speculative: the remainder
                            # refetches (possibly re-routed) in a fresh
                            # pass; cancel abandoned dispatches so the
                            # device client doesn't retain their answers
                            for t in inflight:
                                self._cancel(t)
                            inflight.clear()
                            if applied_any:
                                return state
                            raise BlockValidationError(str(f)) from f
                        applied_any = True
                        h += 1
                finally:
                    self._occupy("apply", 0)
                if barrier and not inflight:
                    barrier = False
                    spec_vals = state.validators
                    next_start = state.last_block_height + 1
        except BaseException:
            # an escape with tiles still speculated (a _settle crash, a
            # SyncStalled with nothing applied) must not strand their
            # dispatches — cancel so the device client drops the
            # answers instead of retaining them for nobody
            for t in inflight:
                self._cancel(t)
            inflight.clear()
            raise
        finally:
            self._inflight_gauge(0)
        return state
