"""Device-wedge watchdog: per-dispatch deadlines for the verification
pipeline.

The TPU tunnel in this environment has wedged mid-round twice
(docs/PERF.md) — a dispatch that will never answer must not hang
blocksync forever. Each tile dispatch gets a deadline scaled by its
lane count; a miss (or any transport/backend error) trips the watchdog:
the current tile and every in-flight tile drain to the CPU fallback
(native per-signature verify in the scheduler) instead of waiting out a
dead device, and each drained tile increments the
pipeline_wedge_fallbacks Prometheus counter.

Recovery is owned by the device health supervisor (device/health.py):
with a supervisor attached, a trip reports SUSPECT and `wedged` tracks
the supervisor's state — the scheduler probes the device with cheap
known-answer batches on a jittered exponential backoff and resumes
device dispatch when the supervisor returns to HEALTHY. Without a
supervisor the original STICKY semantics remain (a wedge latches for
the watchdog's lifetime): probing a dead device once per tile would pay
the full deadline every time, so standalone watchdogs never re-arm.
"""

from __future__ import annotations

from typing import Optional

from ..libs.env import env_float

DEADLINE_BASE_ENV = "COMETBFT_TPU_PIPELINE_DEADLINE_BASE"
DEADLINE_PER_SIG_ENV = "COMETBFT_TPU_PIPELINE_DEADLINE_PER_SIG"
DEFAULT_BASE_S = 30.0      # covers a cold kernel compile on a live device
DEFAULT_PER_SIG_S = 0.005  # generous: a healthy flush is ms for thousands


class DeviceWatchdog:
    """Bounds every pipeline dispatch; wedge detection latches sticky
    unless a DeviceSupervisor owns recovery."""

    def __init__(self, base_deadline_s: Optional[float] = None,
                 per_sig_s: Optional[float] = None, metrics=None,
                 log=None, supervisor=None):
        if base_deadline_s is None:
            base_deadline_s = env_float(DEADLINE_BASE_ENV,
                                        DEFAULT_BASE_S)
        if per_sig_s is None:
            per_sig_s = env_float(DEADLINE_PER_SIG_ENV,
                                  DEFAULT_PER_SIG_S)
        self.base_deadline_s = base_deadline_s
        self.per_sig_s = per_sig_s
        self.metrics = metrics  # libs/metrics_gen.PipelineMetrics or None
        self.log = log
        self.supervisor = supervisor  # device/health.DeviceSupervisor
        self._sticky_wedged = False
        self.trips = 0       # distinct wedge detections
        self.fallbacks = 0   # tiles drained to the CPU fallback
        self.last_error: Optional[BaseException] = None

    @property
    def wedged(self) -> bool:
        """Is the device currently unusable for dispatch? Supervisor-
        backed watchdogs recover when it returns HEALTHY; standalone
        ones stay sticky."""
        if self.supervisor is not None:
            return not self.supervisor.can_dispatch()
        return self._sticky_wedged

    def deadline_for(self, n_lanes: int) -> float:
        return self.base_deadline_s + self.per_sig_s * max(0, n_lanes)

    def result(self, future, n_lanes: int):
        """The per-lane verdicts from `future`, or None when the caller
        must CPU-verify the tile itself (deadline missed, backend
        raised, or the device is currently wedged/suspect)."""
        if self.wedged:
            self._fallback()
            return None
        try:
            return future.result(self.deadline_for(n_lanes))
        except Exception as e:  # noqa: BLE001 — timeout, transport
            # death, or a backend crash: all mean "this device cannot
            # be trusted to answer"; verification correctness is owned
            # by the CPU fallback either way. KeyboardInterrupt/
            # SystemExit propagate — an operator's Ctrl-C mid-dispatch
            # must stop the sync, not be misread as a wedge.
            self._trip(e)
            self._fallback()
            return None

    def _trip(self, exc: BaseException) -> None:
        self.trips += 1
        self.last_error = exc
        # flight-recorder dump keyed per distinct trip: the ring at
        # this moment holds the dispatch/settle spans leading into the
        # wedge (trace/ is a no-op while tracing is disabled)
        from ..trace import trigger_dump
        trigger_dump("watchdog-trip", str(self.trips),
                     f"{type(exc).__name__}: {exc}")
        if self.supervisor is not None:
            self.supervisor.report_trip(exc)
        else:
            self._sticky_wedged = True
        if self.log is not None:
            self.log(f"pipeline watchdog: device wedged "
                     f"({type(exc).__name__}: {exc}); draining to CPU")

    def _fallback(self) -> None:
        self.fallbacks += 1
        if self.metrics is not None:
            self.metrics.wedge_fallbacks.inc()
