"""Device-wedge watchdog: per-dispatch deadlines for the verification
pipeline.

The TPU tunnel in this environment has wedged mid-round twice
(docs/PERF.md) — a dispatch that will never answer must not hang
blocksync forever. Each tile dispatch gets a deadline scaled by its
lane count; a miss (or any transport/backend error) trips the watchdog
STICKY: the current tile and every in-flight or future tile drain to
the CPU fallback (native per-signature verify in the scheduler) instead
of waiting out a dead device, and each drained tile increments the
pipeline_wedge_fallbacks Prometheus counter. Sticky matters: a wedged
tunnel stays wedged (nothing in-repo can reset it), so probing it once
per tile would pay the full deadline every time.
"""

from __future__ import annotations

import os
from typing import Optional

DEADLINE_BASE_ENV = "COMETBFT_TPU_PIPELINE_DEADLINE_BASE"
DEADLINE_PER_SIG_ENV = "COMETBFT_TPU_PIPELINE_DEADLINE_PER_SIG"
DEFAULT_BASE_S = 30.0      # covers a cold kernel compile on a live device
DEFAULT_PER_SIG_S = 0.005  # generous: a healthy flush is ms for thousands


def _env_float(name: str, default: float) -> float:
    """A malformed env knob must degrade to the default, not abort
    blocksync startup (same guard as device/client.deadline_for)."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class DeviceWatchdog:
    """Bounds every pipeline dispatch; wedge detection is sticky."""

    def __init__(self, base_deadline_s: Optional[float] = None,
                 per_sig_s: Optional[float] = None, metrics=None,
                 log=None):
        if base_deadline_s is None:
            base_deadline_s = _env_float(DEADLINE_BASE_ENV,
                                         DEFAULT_BASE_S)
        if per_sig_s is None:
            per_sig_s = _env_float(DEADLINE_PER_SIG_ENV,
                                   DEFAULT_PER_SIG_S)
        self.base_deadline_s = base_deadline_s
        self.per_sig_s = per_sig_s
        self.metrics = metrics  # libs/metrics_gen.PipelineMetrics or None
        self.log = log
        self.wedged = False
        self.trips = 0       # distinct wedge detections (sticky: 0 or 1
        #                      per watchdog lifetime in practice)
        self.fallbacks = 0   # tiles drained to the CPU fallback
        self.last_error: Optional[BaseException] = None

    def deadline_for(self, n_lanes: int) -> float:
        return self.base_deadline_s + self.per_sig_s * max(0, n_lanes)

    def result(self, future, n_lanes: int):
        """The per-lane verdicts from `future`, or None when the caller
        must CPU-verify the tile itself (deadline missed, backend
        raised, or the device already wedged earlier)."""
        if self.wedged:
            self._fallback()
            return None
        try:
            return future.result(self.deadline_for(n_lanes))
        except Exception as e:  # noqa: BLE001 — timeout, transport
            # death, or a backend crash: all mean "this device cannot
            # be trusted to answer"; verification correctness is owned
            # by the CPU fallback either way. KeyboardInterrupt/
            # SystemExit propagate — an operator's Ctrl-C mid-dispatch
            # must stop the sync, not be misread as a wedge.
            self._trip(e)
            self._fallback()
            return None

    def _trip(self, exc: BaseException) -> None:
        self.wedged = True
        self.trips += 1
        self.last_error = exc
        if self.log is not None:
            self.log(f"pipeline watchdog: device wedged "
                     f"({type(exc).__name__}: {exc}); draining to CPU")

    def _fallback(self) -> None:
        self.fallbacks += 1
        if self.metrics is not None:
            self.metrics.wedge_fallbacks.inc()
