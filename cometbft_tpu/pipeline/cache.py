"""Bounded verified-signature cache shared across verification paths.

Consensus gossip re-delivers the same precommit many times, blocksync
re-fetches tile-boundary blocks, and the light client re-verifies
commits blocksync already checked — each re-verification is a wasted
device lane (or a ~400µs host verify). The cache records signatures
that VERIFIED TRUE, keyed by (pubkey, sign_bytes, sig): the sign bytes
embed chain id, height, round, and type, so a hit is exactly "this key
already verified these bytes under this chain" — never a cross-context
confusion. Failed signatures are never cached (attribution paths handle
them), so a hit can only skip work, never flip a verdict.

Intake paths attribute hits/misses per label ("blocksync", "vote",
"commit") — the raw material of the pipeline_sigcache_{hits,misses}
Prometheus counters (libs/metrics_defs.PipelineMetrics). Capacity is
LRU-bounded; COMETBFT_TPU_SIGCACHE_CAPACITY overrides the default
(0 disables the process-wide shared cache entirely).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, Optional

from ..libs.env import env_int

DEFAULT_CAPACITY = 65536
ENV_CAPACITY = "COMETBFT_TPU_SIGCACHE_CAPACITY"


def _key(pub: bytes, sign_bytes: bytes, sig: bytes) -> bytes:
    # length-prefixed concat: no ambiguity between field boundaries
    h = hashlib.sha256()
    for part in (pub, sign_bytes, sig):
        h.update(len(part).to_bytes(4, "big"))
        h.update(part)
    return h.digest()


class SigCache:
    """Thread-safe LRU of verified-true signatures."""

    # guarded-by: _lock: _entries, hits, misses, evictions
    # (enforced by tools/staticcheck's guarded-by rule: any access to
    # the attributes above outside `with self._lock` is a lint error)

    def __init__(self, capacity: int = DEFAULT_CAPACITY, metrics=None):
        self.capacity = capacity
        self.metrics = metrics  # libs/metrics_gen.PipelineMetrics or None
        self._lock = threading.Lock()
        self._entries: "OrderedDict[bytes, None]" = OrderedDict()
        self.evictions = 0
        self.hits: Dict[str, int] = {}
        self.misses: Dict[str, int] = {}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @staticmethod
    def key(pub: bytes, sign_bytes: bytes, sig: bytes) -> bytes:
        """Stable digest of one signature triple — the cache's own
        entry key, exposed so the farm batcher's intra-batch dedup
        collapses identical lanes under the same identity."""
        return _key(pub, sign_bytes, sig)

    def seen(self, pub: bytes, sign_bytes: bytes, sig: bytes,
             path: str = "unknown") -> bool:
        """True iff this exact signature previously verified TRUE.
        Counts a hit or miss against `path`."""
        if self.capacity <= 0:
            return False
        k = _key(pub, sign_bytes, sig)
        with self._lock:
            hit = k in self._entries
            if hit:
                self._entries.move_to_end(k)
                self.hits[path] = self.hits.get(path, 0) + 1
            else:
                self.misses[path] = self.misses.get(path, 0) + 1
        m = self.metrics
        if m is not None:
            (m.cache_hits if hit else m.cache_misses).inc(path=path)
        return hit

    def add(self, pub: bytes, sign_bytes: bytes, sig: bytes) -> None:
        """Record a signature that verified TRUE. Never call for a
        failed verification."""
        if self.capacity <= 0:
            return
        evicted = 0
        k = _key(pub, sign_bytes, sig)
        with self._lock:
            self._entries[k] = None
            self._entries.move_to_end(k)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
            self.evictions += evicted
        if evicted and self.metrics is not None:
            self.metrics.cache_evictions.inc(evicted)

    def hit_rate(self, path: Optional[str] = None) -> float:
        """Hits / (hits + misses), overall or for one intake path."""
        with self._lock:
            if path is None:
                h, m = sum(self.hits.values()), sum(self.misses.values())
            else:
                h, m = self.hits.get(path, 0), self.misses.get(path, 0)
        return h / (h + m) if h + m else 0.0

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits.clear()
            self.misses.clear()
            self.evictions = 0


_shared: Optional[SigCache] = None
_shared_lock = threading.Lock()


def shared_cache() -> SigCache:
    """Process-wide cache instance (consensus vote intake, light client,
    and any blocksync engine not given its own). Capacity from
    COMETBFT_TPU_SIGCACHE_CAPACITY at first use; 0 yields a disabled
    (always-miss, never-store) instance."""
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = SigCache(env_int(ENV_CAPACITY, DEFAULT_CAPACITY))
        return _shared


def reset_shared_cache() -> None:
    """Drop the shared instance (tests; also re-reads the env knob)."""
    global _shared
    with _shared_lock:
        _shared = None
