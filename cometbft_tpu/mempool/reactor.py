"""Mempool gossip reactor (reference mempool/reactor.go:217).

Channel 0x30 carries raw txs. The reference runs a per-peer
broadcastTxRoutine walking the CList; here admission triggers a
broadcast to current peers, and new peers get the current pool replayed
once on add_peer — same delivery guarantee (every peer eventually sees
every pending tx) without per-peer cursors.
"""

from __future__ import annotations

from typing import List

from ..p2p.mconn import ChannelDescriptor

MEMPOOL_CHANNEL = 0x30


class MempoolReactor:
    def __init__(self, mempool, ingest=None):
        self.mempool = mempool
        # ingest/admission.IngestPipeline when [mempool] ingest_batch
        # is on: relayed txs then coalesce into the same shared
        # signature batches as RPC traffic instead of walking a
        # synchronous check_tx on the p2p read thread
        self.ingest = ingest
        self._switch = None
        mempool.on_new_tx(self._on_local_admit)
        self._relaying: List[bytes] = []

    def attach(self, switch) -> None:
        self._switch = switch

    def get_channels(self) -> List[ChannelDescriptor]:
        return [ChannelDescriptor(id=MEMPOOL_CHANNEL, priority=3,
                                  send_queue_capacity=1000)]

    def add_peer(self, peer) -> None:
        for tx in self.mempool.reap_max_txs(-1):
            peer.try_send(MEMPOOL_CHANNEL, tx)

    def remove_peer(self, peer, reason: str) -> None:
        pass

    def receive(self, channel_id: int, peer, tx: bytes) -> None:
        if self.ingest is not None:
            # fire-and-forget: duplicates/sheds drop silently and the
            # background flusher settles the ticket off-thread
            self.ingest.submit_nowait(tx)
            return
        try:
            self.mempool.check_tx(tx)
        except ValueError:
            pass  # duplicate/full/invalid: drop (reference logs only)

    def _on_local_admit(self, tx: bytes) -> None:
        if self._switch is not None:
            self._switch.broadcast(MEMPOOL_CHANNEL, tx)
