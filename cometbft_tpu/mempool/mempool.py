"""Mempool: CheckTx admission, FIFO ordering, reap, recheck
(reference mempool/mempool.go:25-118 interface,
mempool/clist_mempool.go:48-52,251-370, mempool/cache.go).

The reference's CList (concurrent linked list) exists so per-peer gossip
goroutines can hold stable cursors while the list mutates; here an
OrderedDict gives the same FIFO-with-O(1)-removal shape, and gossip
cursors are height-stamped iteration (see p2p reactor) — the
single-writer engine loop serializes mutations (SURVEY §2.3).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Protocol, Tuple

CODE_TYPE_OK = 0


def tx_key(tx: bytes) -> bytes:
    """sha256 identity of a tx (reference types/tx.go Tx.Key)."""
    return hashlib.sha256(tx).digest()


class Mempool(Protocol):
    """reference mempool/mempool.go:25-118 (subset that consensus and
    the block executor consume)."""

    def check_tx(self, tx: bytes) -> int: ...
    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int
                               ) -> List[bytes]: ...
    def reap_max_txs(self, n: int) -> List[bytes]: ...
    def lock(self) -> None: ...
    def unlock(self) -> None: ...
    def update(self, height: int, txs: List[bytes], results) -> None: ...
    def flush(self) -> None: ...
    def size(self) -> int: ...
    def size_bytes(self) -> int: ...


class TxCache:
    """LRU seen-tx cache (reference mempool/cache.go LRUTxCache):
    spam/duplicate filter in front of CheckTx."""

    def __init__(self, size: int = 10000):
        self._size = size
        self._map: "OrderedDict[bytes, None]" = OrderedDict()

    def push(self, key: bytes) -> bool:
        """Returns False if already present (and refreshes recency)."""
        if key in self._map:
            self._map.move_to_end(key)
            return False
        self._map[key] = None
        if len(self._map) > self._size:
            self._map.popitem(last=False)
        return True

    def remove(self, key: bytes) -> None:
        self._map.pop(key, None)

    def reset(self) -> None:
        self._map.clear()

    def __contains__(self, key: bytes) -> bool:
        return key in self._map

    def __len__(self) -> int:
        return len(self._map)


@dataclass
class _MempoolTx:
    """reference mempool/clist_mempool.go mempoolTx."""
    tx: bytes
    height: int        # height at which the tx was admitted
    gas_wanted: int = 0


class TxRemovedError(Exception):
    pass


class CListMempool:
    """FIFO mempool over an app CheckTx callback
    (reference mempool/clist_mempool.go:48-118).

    check_fn(tx) -> (code, gas_wanted); code 0 admits. `keep_in_cache`
    mirrors the reference's config.CacheSize + KeepInvalidTxsInCache
    semantics: invalid txs are evicted from the cache so a later valid
    variant can re-enter, unless keep_invalid is set.
    """

    def __init__(self, check_fn: Callable[[bytes], Tuple[int, int]],
                 max_tx_bytes: int = 1024 * 1024,
                 max_txs_bytes: int = 64 * 1024 * 1024,
                 size: int = 5000, cache_size: int = 10000,
                 keep_invalid_in_cache: bool = False,
                 recheck: bool = True):
        self._check_fn = check_fn
        self._max_tx_bytes = max_tx_bytes
        self._max_txs_bytes = max_txs_bytes
        self._max_size = size
        self._recheck = recheck
        self._keep_invalid = keep_invalid_in_cache
        self.cache = TxCache(cache_size)
        self._txs: "OrderedDict[bytes, _MempoolTx]" = OrderedDict()
        self._bytes = 0
        self._height = 0
        self._update_lock = threading.RLock()
        self._notify: List[Callable[[], None]] = []
        # cache-eviction observers (ingest/admission.TxFilter mirrors
        # this cache: a tx the mempool forgets must be resubmittable
        # through the front door too). cb(key) per eviction; cb(None)
        # on a wholesale reset (flush)
        self._evict_cbs: List[Callable[[Optional[bytes]], None]] = []
        # optional generated metrics struct
        # (libs/metrics_gen.MempoolMetrics — reference
        # mempool/metrics.go); None until the node wires it
        self.metrics = None

    # --- admission -----------------------------------------------------------

    def check_tx(self, tx: bytes) -> int:
        """Admit a tx (reference clist_mempool.go:251-313 CheckTx).
        Returns the app code (0 = admitted). Raises ValueError on
        structural rejection (too large / full / duplicate)."""
        with self._update_lock:
            if len(tx) > self._max_tx_bytes:
                raise ValueError(
                    f"tx too large: {len(tx)} > {self._max_tx_bytes}")
            if (len(self._txs) >= self._max_size
                    or self._bytes + len(tx) > self._max_txs_bytes):
                raise ValueError("mempool is full")
            key = tx_key(tx)
            if not self.cache.push(key):
                raise ValueError("tx already in cache")
            code, gas = self._check_fn(tx)
            if code != CODE_TYPE_OK:
                if not self._keep_invalid:
                    self.cache.remove(key)
                    self._fire_evict(key)
                if self.metrics is not None:
                    self.metrics.failed_txs.inc()
                return code
            self._txs[key] = _MempoolTx(tx, self._height, gas)
            self._bytes += len(tx)
            self._set_gauges()
            for cb in self._notify:
                cb(tx)
            return CODE_TYPE_OK

    def _set_gauges(self) -> None:
        if self.metrics is not None:
            self.metrics.size.set(len(self._txs))
            self.metrics.size_bytes.set(self._bytes)

    def on_new_tx(self, cb: Callable[[bytes], None]) -> None:
        """Subscribe to tx arrival with the admitted tx (gossip relay /
        consensus wake-up)."""
        self._notify.append(cb)

    def on_tx_evicted(self, cb: Callable[[Optional[bytes]], None]) -> None:
        """Subscribe to seen-cache evictions: cb(tx_key) whenever an
        invalid/rechecked tx is dropped from the cache, cb(None) when
        the cache resets wholesale."""
        self._evict_cbs.append(cb)

    def _fire_evict(self, key: Optional[bytes]) -> None:
        for cb in self._evict_cbs:
            cb(key)

    # --- reaping -------------------------------------------------------------

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int
                               ) -> List[bytes]:
        """FIFO reap under byte/gas budgets (reference
        clist_mempool.go:519-552)."""
        with self._update_lock:
            out, total_b, total_g = [], 0, 0
            for mt in self._txs.values():
                nb = total_b + len(mt.tx)
                ng = total_g + mt.gas_wanted
                if max_bytes >= 0 and nb > max_bytes:
                    break
                if max_gas >= 0 and ng > max_gas:
                    break
                out.append(mt.tx)
                total_b, total_g = nb, ng
            return out

    def reap_max_txs(self, n: int) -> List[bytes]:
        with self._update_lock:
            if n < 0:
                return [mt.tx for mt in self._txs.values()]
            return [mt.tx for mt in list(self._txs.values())[:n]]

    def txs_after(self, start: int) -> List[bytes]:
        """Gossip helper: all txs, FIFO (cursor management is the
        caller's; reference mempool/reactor.go:217 broadcastTxRoutine)."""
        return self.reap_max_txs(-1)[start:]

    # --- post-commit update --------------------------------------------------

    def lock(self) -> None:
        # staticcheck: allow(resource-lifecycle)  ## exported lock()/unlock() pair — the caller brackets app.commit()+update() across statements (reference clist_mempool.go Lock/Unlock); pairing is the caller's contract, pinned by test_mempool
        self._update_lock.acquire()

    def unlock(self) -> None:
        self._update_lock.release()

    def update(self, height: int, txs: List[bytes], results=None) -> None:
        """Remove committed txs and recheck survivors against the
        post-commit app state (reference clist_mempool.go:577-649).
        Caller holds lock() around app.commit()+update()."""
        self._height = height
        for i, tx in enumerate(txs):
            key = tx_key(tx)
            # committed txs stay in the cache to block replays; invalid
            # ones are evicted (reference clist_mempool.go:600-612)
            code = (results[i].code if results is not None
                    and i < len(results) else CODE_TYPE_OK)
            if code == CODE_TYPE_OK:
                self.cache.push(key)
            elif not self._keep_invalid:
                self.cache.remove(key)
                self._fire_evict(key)
            mt = self._txs.pop(key, None)
            if mt is not None:
                self._bytes -= len(mt.tx)
        if self._recheck and self._txs:
            self._recheck_txs()
        self._set_gauges()

    def _recheck_txs(self) -> None:
        """Re-run CheckTx on every pending tx (reference
        clist_mempool.go:655-687 recheckTxs)."""
        if self.metrics is not None:
            self.metrics.recheck_times.inc()
        for key in list(self._txs.keys()):
            mt = self._txs[key]
            code, gas = self._check_fn(mt.tx)
            if code != CODE_TYPE_OK:
                del self._txs[key]
                self._bytes -= len(mt.tx)
                if not self._keep_invalid:
                    self.cache.remove(key)
                    self._fire_evict(key)
                if self.metrics is not None:
                    self.metrics.evicted_txs.inc()
            else:
                mt.gas_wanted = gas

    def flush(self) -> None:
        with self._update_lock:
            self._txs.clear()
            self._bytes = 0
            self.cache.reset()
            self._fire_evict(None)
            self._set_gauges()

    # --- introspection -------------------------------------------------------

    def size(self) -> int:
        return len(self._txs)

    def size_bytes(self) -> int:
        return self._bytes

    def contains(self, key: bytes) -> bool:
        return key in self._txs

    def is_empty(self) -> bool:
        return not self._txs


class NopMempool:
    """reference mempool/nop_mempool.go — for apps that disseminate txs
    themselves."""

    def check_tx(self, tx: bytes) -> int:
        raise ValueError("tx rejected: nop mempool")

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int):
        return []

    def reap_max_txs(self, n: int):
        return []

    def lock(self) -> None:
        pass

    def unlock(self) -> None:
        pass

    def update(self, height: int, txs, results=None) -> None:
        pass

    def flush(self) -> None:
        pass

    def size(self) -> int:
        return 0

    def size_bytes(self) -> int:
        return 0
