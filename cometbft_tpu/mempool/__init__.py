from .mempool import CListMempool, Mempool, NopMempool, TxCache

__all__ = ["CListMempool", "Mempool", "NopMempool", "TxCache"]
