"""Shared jax persistent compile-cache setup.

jax is pre-imported by the ambient environment (sitecustomize), so env
vars like JAX_COMPILATION_CACHE_DIR are latched before any entry point
runs — configuration MUST go through jax.config. Every entry point
(tests, bench, graft entry, tools) calls this one helper so the cache
location and threshold stay consistent.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def enable_compile_cache(cache_dir: str | None = None) -> None:
    import jax
    jax.config.update(
        "jax_compilation_cache_dir",
        cache_dir or os.path.join(_REPO_ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
