"""Shared jax persistent compile-cache setup.

jax is pre-imported by the ambient environment (sitecustomize), so env
vars like JAX_COMPILATION_CACHE_DIR are latched before any entry point
runs — configuration MUST go through jax.config. Every entry point
(tests, bench, graft entry, tools) calls this one helper so the cache
location and threshold stay consistent.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def enable_compile_cache(cache_dir: str | None = None) -> None:
    import jax
    # the ambient TPU-tunnel setup pins jax_platforms programmatically
    # (to "axon,cpu"), which BEATS the JAX_PLATFORMS env var — so a
    # subprocess spawned with JAX_PLATFORMS=cpu (e2e nodes, the device
    # server under test) would still try to grab the single-client
    # tunnel first. Re-assert the env var's choice through jax.config,
    # where it wins — but only over the ambient multi-platform default
    # (has a comma / unset), never over an explicit single-platform
    # choice already made in-process (tests' conftest pins "cpu" and
    # may have initialized the backend; re-pointing it would hang).
    plat = os.environ.get("JAX_PLATFORMS")
    current = jax.config.jax_platforms
    if plat and (not current or "," in current):
        jax.config.update("jax_platforms", plat)
    jax.config.update(
        "jax_compilation_cache_dir",
        cache_dir or os.path.join(_REPO_ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
