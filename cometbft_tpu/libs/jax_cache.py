"""Shared jax persistent compile-cache setup + the compile ledger.

jax is pre-imported by the ambient environment (sitecustomize), so env
vars like JAX_COMPILATION_CACHE_DIR are latched before any entry point
runs — configuration MUST go through jax.config. Every entry point
(tests, bench, graft entry, tools) calls this one helper so the cache
location and threshold stay consistent.

The CompileLedger (ROADMAP item-5 residual) persists which
(kernel, shape-bucket) pairs have compiled on which platform/jax
version, how long each compile took, and which pairs CRASHED the
compiler — so bench and device-server runs can (a) attribute
hit/miss/cold-compile in their JSON instead of silently eating a
multi-minute XLA compile, and (b) skip shape buckets known to kill
XLA:CPU outright (docs/PERF.md "known compile hazard") instead of
rediscovering the SIGSEGV every round. On device platforms the jax
persistent cache holds the actual executables; the ledger is the
keying + attribution layer over it (XLA:CPU executables are never
persisted — machine-feature reloads risk SIGILL — so on cpu a "seen"
entry predicts a warm in-process recompile cost, not an artifact
reload).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def raise_compiler_stack_limit() -> None:
    """Root-cause mitigation for the XLA:CPU SIGSEGV at batch >= 256
    (docs/PERF.md "known compile hazard"): XLA's HLO passes recurse
    deeply on the RLC kernel graph and OVERFLOW the default 8MB
    pthread stack (observed: SIGSEGV at the stack guard page inside
    libjax_common). pthreads size their stacks from RLIMIT_STACK at
    thread creation, so raising the soft limit BEFORE the compiler
    thread pool exists removes the crash. Called from
    enable_compile_cache so every entry point gets it; a no-op when
    the limit is already high or the pool already exists."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = 512 * 1024 * 1024
        if hard != resource.RLIM_INFINITY:
            want = min(want, hard)
        if soft != resource.RLIM_INFINITY and soft < want:
            resource.setrlimit(resource.RLIMIT_STACK, (want, hard))
    except (ImportError, ValueError, OSError):  # pragma: no cover
        pass


def first_configured_platform() -> str:
    """First entry of jax.config.jax_platforms WITHOUT initializing a
    backend ("" when undetermined). The shared device-vs-cpu sniff:
    jax.devices() can hang forever on a wedged TPU tunnel, so every
    caller that merely needs to know "is a real device configured?"
    must read the config, never touch the backend."""
    try:
        import jax
        return (jax.config.jax_platforms or "").split(",")[0]
    except Exception:  # noqa: BLE001 — undetermined == no device
        return ""


def is_device_platform() -> bool:
    """True when the first configured platform is a real accelerator
    (not cpu / undetermined)."""
    return first_configured_platform() not in ("", "cpu")


def enable_compile_cache(cache_dir: str | None = None) -> None:
    raise_compiler_stack_limit()
    import jax
    # the ambient TPU-tunnel setup pins jax_platforms programmatically
    # (to "axon,cpu"), which BEATS the JAX_PLATFORMS env var — so a
    # subprocess spawned with JAX_PLATFORMS=cpu (e2e nodes, the device
    # server under test) would still try to grab the single-client
    # tunnel first. Re-assert the env var's choice through jax.config,
    # where it wins — but only over the ambient multi-platform default
    # (has a comma / unset), never over an explicit single-platform
    # choice already made in-process (tests' conftest pins "cpu" and
    # may have initialized the backend; re-pointing it would hang).
    plat = os.environ.get("JAX_PLATFORMS")
    current = jax.config.jax_platforms
    if plat and (not current or "," in current):
        jax.config.update("jax_platforms", plat)
        current = plat
    # the persistent cache is TPU-only: XLA:CPU AOT executables record
    # machine features that fail the host check when another process
    # reloads them ("could lead to SIGILL" — and mesh executables DO
    # segfault, in both the serialize and deserialize paths). Enable
    # only when the FIRST configured platform is explicitly a
    # non-cpu device; anything undetermined could resolve to the CPU
    # backend, so stay conservative and recompile per process.
    first = (current or "").split(",")[0]
    if first in ("", "cpu"):
        disable_persistent_cache()
        return
    jax.config.update(
        "jax_compilation_cache_dir",
        cache_dir or os.path.join(_REPO_ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


class CompileLedger:
    """On-disk record of (kernel, shape-bucket) compiles.

    Entries are keyed "kernel|bucket|platform|jax-version" so a ledger
    written against one backend or jax build never mispredicts
    another. All methods are best-effort on I/O errors: the ledger
    must never be able to fail a measurement run."""

    # guarded-by: _lock: _entries, hits, misses, _proc_warm
    def __init__(self, path: str | None = None):
        self.path = path or os.path.join(_REPO_ROOT, ".jax_cache",
                                         "ledger.json")
        self._lock = threading.Lock()
        self.hits = 0       # compile_guard entries already in the ledger
        self.misses = 0     # cold entries recorded this process
        # keys THIS process compiled (or guarded through) — the only
        # warmth that is cheap on XLA:CPU, where executables are never
        # persisted and an on-disk entry predicts a full recompile
        self._proc_warm: set = set()
        try:
            with open(self.path) as f:
                self._entries: dict = json.load(f)
        except (OSError, ValueError):
            self._entries = {}

    def _save(self, entries: dict) -> None:
        """Persist a snapshot (passed in so every self._entries access
        stays lexically under the lock), MERGED over the on-disk state:
        concurrent writers (bench parent + --measure subprocess, or a
        device server alongside a bench) each contribute their keys
        instead of the last writer erasing the others'. Our entries win
        only on key conflict."""
        try:
            try:
                with open(self.path) as f:
                    merged = json.load(f)
            except (OSError, ValueError):
                merged = {}
            merged.update(entries)
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(merged, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            pass

    @staticmethod
    def _env(platform: str | None = None) -> str:
        try:
            import jax
            ver = jax.__version__
        except Exception:  # noqa: BLE001 — ledger must never fail callers
            ver = "?"
        return f"{platform or first_configured_platform() or 'cpu'}|{ver}"

    def key(self, kernel: str, bucket: int,
            platform: str | None = None) -> str:
        """Entry key; `platform` overrides the process's own configured
        platform — bench's parent process must query/record under the
        platform its MEASURE CHILD actually runs ('cpu' in the
        device-unreachable fallback, while the parent may still be
        configured for the device)."""
        return f"{kernel}|{bucket}|{self._env(platform)}"

    def seen(self, kernel: str, bucket: int,
             platform: str | None = None) -> bool:
        with self._lock:
            e = self._entries.get(self.key(kernel, bucket, platform))
        return bool(e) and not e.get("crashed")

    def known_crash(self, kernel: str, bucket: int,
                    platform: str | None = None) -> bool:
        with self._lock:
            e = self._entries.get(self.key(kernel, bucket, platform))
        return bool(e) and bool(e.get("crashed"))

    def warm_in_process(self, kernel: str, bucket: int) -> bool:
        """True when THIS process already compiled (kernel, bucket) —
        its jit cache makes the next dispatch to that bucket cheap.
        This is deliberately NOT `seen()`: on cpu a ledger entry from
        another process only predicts the recorded compile_s all over
        again, so the 64-lane CPU clamp (crypto/keys) lifts on
        process-local warmth alone."""
        with self._lock:
            return self.key(kernel, bucket) in self._proc_warm

    def record(self, kernel: str, bucket: int, compile_s: float) -> None:
        with self._lock:
            self._proc_warm.add(self.key(kernel, bucket))
            self._entries[self.key(kernel, bucket)] = {
                "kernel": kernel, "bucket": bucket,
                "compile_s": round(float(compile_s), 3),
                "recorded_unix": int(time.time()),  # staticcheck: allow(wallclock)
            }
            self._save(dict(self._entries))

    def record_crash(self, kernel: str, bucket: int,
                     detail: str = "",
                     platform: str | None = None) -> None:
        with self._lock:
            self._entries[self.key(kernel, bucket, platform)] = {
                "kernel": kernel, "bucket": bucket, "crashed": True,
                "detail": detail[:200],
                "recorded_unix": int(time.time()),  # staticcheck: allow(wallclock)
            }
            self._save(dict(self._entries))

    @contextlib.contextmanager
    def compile_guard(self, kernel: str, bucket: int):
        """Wrap a possibly-compiling call: attributes a ledger hit or
        miss, times the first-touch cost, and records it on SUCCESS.
        A raising guard records nothing — a transient runtime failure
        (transport error mid-warm) must not brand a bucket
        compiler-fatal; only explicit record_crash calls (e.g. bench's
        subprocess-killed-by-signal detection) do that, and a later
        successful record() clears the verdict."""
        warm = self.seen(kernel, bucket)
        t0 = time.monotonic()  # staticcheck: allow(wallclock)
        yield
        dt = time.monotonic() - t0  # staticcheck: allow(wallclock)
        with self._lock:
            if warm:
                self.hits += 1
            else:
                self.misses += 1
            self._proc_warm.add(self.key(kernel, bucket))
        if not warm:
            self.record(kernel, bucket, dt)

    def attribution(self) -> dict:
        """Process-level summary for bench JSON."""
        with self._lock:
            return {"ledger": self.path, "hits": self.hits,
                    "misses": self.misses}


_ledger: CompileLedger | None = None
_ledger_lock = threading.Lock()


def ledger() -> CompileLedger:
    global _ledger
    with _ledger_lock:
        if _ledger is None:
            _ledger = CompileLedger()
        return _ledger


def reset_ledger(path: str | None = None) -> None:
    """Point the process at a fresh ledger (tests)."""
    global _ledger
    with _ledger_lock:
        _ledger = CompileLedger(path) if path else None


def disable_persistent_cache() -> None:
    """Turn the on-disk compile cache off for the rest of the process.

    The flag alone is NOT enough once anything has compiled: jax
    memoizes the is-cache-enabled decision globally at first compile,
    so the memo must be reset too (observed: a process that compiled
    plenty beforehand still cache-WROTE a sharded executable — and
    segfaulted serializing it — despite the flag being False)."""
    import jax
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — internal API; flag still set
        pass
