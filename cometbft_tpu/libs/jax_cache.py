"""Shared jax persistent compile-cache setup.

jax is pre-imported by the ambient environment (sitecustomize), so env
vars like JAX_COMPILATION_CACHE_DIR are latched before any entry point
runs — configuration MUST go through jax.config. Every entry point
(tests, bench, graft entry, tools) calls this one helper so the cache
location and threshold stay consistent.
"""

from __future__ import annotations

import os

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def raise_compiler_stack_limit() -> None:
    """Root-cause mitigation for the XLA:CPU SIGSEGV at batch >= 256
    (docs/PERF.md "known compile hazard"): XLA's HLO passes recurse
    deeply on the RLC kernel graph and OVERFLOW the default 8MB
    pthread stack (observed: SIGSEGV at the stack guard page inside
    libjax_common). pthreads size their stacks from RLIMIT_STACK at
    thread creation, so raising the soft limit BEFORE the compiler
    thread pool exists removes the crash. Called from
    enable_compile_cache so every entry point gets it; a no-op when
    the limit is already high or the pool already exists."""
    try:
        import resource
        soft, hard = resource.getrlimit(resource.RLIMIT_STACK)
        want = 512 * 1024 * 1024
        if hard != resource.RLIM_INFINITY:
            want = min(want, hard)
        if soft != resource.RLIM_INFINITY and soft < want:
            resource.setrlimit(resource.RLIMIT_STACK, (want, hard))
    except (ImportError, ValueError, OSError):  # pragma: no cover
        pass


def first_configured_platform() -> str:
    """First entry of jax.config.jax_platforms WITHOUT initializing a
    backend ("" when undetermined). The shared device-vs-cpu sniff:
    jax.devices() can hang forever on a wedged TPU tunnel, so every
    caller that merely needs to know "is a real device configured?"
    must read the config, never touch the backend."""
    try:
        import jax
        return (jax.config.jax_platforms or "").split(",")[0]
    except Exception:  # noqa: BLE001 — undetermined == no device
        return ""


def is_device_platform() -> bool:
    """True when the first configured platform is a real accelerator
    (not cpu / undetermined)."""
    return first_configured_platform() not in ("", "cpu")


def enable_compile_cache(cache_dir: str | None = None) -> None:
    raise_compiler_stack_limit()
    import jax
    # the ambient TPU-tunnel setup pins jax_platforms programmatically
    # (to "axon,cpu"), which BEATS the JAX_PLATFORMS env var — so a
    # subprocess spawned with JAX_PLATFORMS=cpu (e2e nodes, the device
    # server under test) would still try to grab the single-client
    # tunnel first. Re-assert the env var's choice through jax.config,
    # where it wins — but only over the ambient multi-platform default
    # (has a comma / unset), never over an explicit single-platform
    # choice already made in-process (tests' conftest pins "cpu" and
    # may have initialized the backend; re-pointing it would hang).
    plat = os.environ.get("JAX_PLATFORMS")
    current = jax.config.jax_platforms
    if plat and (not current or "," in current):
        jax.config.update("jax_platforms", plat)
        current = plat
    # the persistent cache is TPU-only: XLA:CPU AOT executables record
    # machine features that fail the host check when another process
    # reloads them ("could lead to SIGILL" — and mesh executables DO
    # segfault, in both the serialize and deserialize paths). Enable
    # only when the FIRST configured platform is explicitly a
    # non-cpu device; anything undetermined could resolve to the CPU
    # backend, so stay conservative and recompile per process.
    first = (current or "").split(",")[0]
    if first in ("", "cpu"):
        disable_persistent_cache()
        return
    jax.config.update(
        "jax_compilation_cache_dir",
        cache_dir or os.path.join(_REPO_ROOT, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def disable_persistent_cache() -> None:
    """Turn the on-disk compile cache off for the rest of the process.

    The flag alone is NOT enough once anything has compiled: jax
    memoizes the is-cache-enabled decision globally at first compile,
    so the memo must be reset too (observed: a process that compiled
    plenty beforehand still cache-WROTE a sharded executable — and
    segfaulted serializing it — despite the flag being False)."""
    import jax
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — internal API; flag still set
        pass
