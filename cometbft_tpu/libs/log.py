"""Structured leveled logger (reference libs/log/tm_logger.go, lazy.go).

Key-value structured output with module filtering and lazy evaluation —
callables in kwargs are only invoked if the record is emitted.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, TextIO

DEBUG, INFO, ERROR, NONE = 0, 1, 2, 3
_NAMES = {DEBUG: "D", INFO: "I", ERROR: "E"}


class Logger:
    def __init__(self, out: Optional[TextIO] = None, level: int = INFO,
                 module: str = "", module_levels: Optional[Dict[str, int]]
                 = None, **bound):
        self._out = out or sys.stderr
        self._level = level
        self._module = module
        self._module_levels = module_levels or {}
        self._bound = bound
        self._lock = threading.Lock()

    def with_(self, module: Optional[str] = None, **kv) -> "Logger":
        """Bind context (reference log.With)."""
        child = Logger(self._out, self._level,
                       module if module is not None else self._module,
                       self._module_levels, **{**self._bound, **kv})
        child._lock = self._lock
        return child

    def _enabled(self, level: int) -> bool:
        threshold = self._module_levels.get(self._module, self._level)
        return level >= threshold

    def _emit(self, level: int, msg: str, kv: Dict[str, Any]) -> None:
        if not self._enabled(level):
            return
        parts = [f"{_NAMES[level]}[{time.strftime('%H:%M:%S')}]",
                 msg]
        if self._module:
            parts.append(f"module={self._module}")
        for k, v in {**self._bound, **kv}.items():
            if callable(v):  # lazy (reference lazy.go)
                v = v()
            parts.append(f"{k}={v}")
        line = " ".join(str(p) for p in parts)
        with self._lock:
            self._out.write(line + "\n")
            self._out.flush()

    def debug(self, msg: str, **kv) -> None:
        self._emit(DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._emit(INFO, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._emit(ERROR, msg, kv)


class NopLogger(Logger):
    def __init__(self):
        super().__init__(out=None, level=NONE)

    def _emit(self, level, msg, kv):
        pass
