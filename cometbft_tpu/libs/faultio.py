"""Deterministic I/O fault injection for the durable-storage seam.

Crash injection used to stop at `fail_point()` boundaries BETWEEN
logical operations; nothing could tear a write mid-record, lie about an
fsync, run a disk out of space, or rot a byte on read. This module is
the missing half: a thin file-object wrapper adopted by the three
durable writers (consensus/wal.py, db/kv.py, privval/file.py) whose
faults are each a pure function of (seed, schedule) — the same
determinism contract as simnet's virtual clock and seeded PRNGs, so a
failing (scenario, seed, plan) triple replays byte-identically.

Fault taxonomy (docs/STORAGE.md):
  * torn write — the Nth write through a label persists only a prefix
    (explicit `keep` offset, or seeded) and then the process "loses
    power": `fail_point("faultio:torn-write")` is crossed (env modes
    os._exit, the simnet hook raises SimCrash) and, if that returns,
    `InjectedCrash` is raised for in-process tests.
  * ENOSPC — the Nth write raises OSError(ENOSPC) with nothing written.
  * fsync lie — fsync() reports success but durability does not
    advance; `FaultPlan.apply_crash()` is the power cut, truncating
    each lying file back to its last honestly-fsynced length.
  * bit flip — the Nth read through a label comes back with one seeded
    bit inverted (plausible-length bit-rot for CRC coverage).

When no plan is installed (the production case) `open_file` returns
the RAW builtin file object — zero wrapper overhead on the hot path.
Schedules ride labels, not call sites, so one plan addresses "the 3rd
blockstore batch" without knowing which file carries it; `path_substr`
narrows a rule to one simnet node's directory.

Env arming (malformed-tolerant, like libs/env): COMETBFT_TPU_FAULTIO=
"seed=7;torn@db:log@3;enospc@wal:head@2@;fsynclie@pv:state;
bitflip@wal:read@1" — '@'-separated because labels contain ':'.
Unparseable entries are skipped; zero valid rules installs nothing.
"""

from __future__ import annotations

import errno
import os
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import timesource
from .fail import fail_point

# The one crash-delivery fail point (registered in docs/SIMNET.md).
# A single literal label: simnet arms it with crash_at_label(...) and
# the env modes with COMETBFT_TPU_FAIL_LABEL — which write tears is the
# PLAN's schedule, so the label needs no per-site variants.
TORN_WRITE_LABEL = "faultio:torn-write"

_TORN = "torn"
_ENOSPC = "enospc"
_FSYNC_LIE = "fsynclie"
_BIT_FLIP = "bitflip"


class InjectedFault(OSError):
    """A scheduled I/O error surfaced to the caller (ENOSPC)."""


class InjectedCrash(RuntimeError):
    """Raised after a torn write when no fail_point mode consumed the
    crash — the in-process stand-in for the power cut. Callers that
    model reboot catch this, reopen, and run recovery."""


@dataclass
class _Rule:
    kind: str
    label: str
    nth: int = 1                 # 1-based count of matching operations
    keep: Optional[int] = None   # torn: explicit byte offset to keep
    path_substr: Optional[str] = None
    count: int = 0               # matching ops seen so far (monotonic)
    fired: bool = False

    def matches(self, label: str, path: str) -> bool:
        return (self.label == label
                and (self.path_substr is None
                     or self.path_substr in path))


@dataclass
class FaultPlan:
    """A deterministic fault schedule. Build rules with the chainable
    torn_write/enospc/fsync_lie/bit_flip methods, `install()` it, run
    the workload, and every fault lands at the same operation with the
    same seeded parameters on every run."""

    seed: int = 0
    rules: List[_Rule] = field(default_factory=list)
    # (time_ns, kind, label, path, detail) — observability + the
    # determinism tests' comparison artifact
    events: List[Tuple[int, str, str, str, str]] = field(
        default_factory=list)
    # path -> honestly-durable length, tracked only for fsync-lied files
    _watermarks: Dict[str, int] = field(default_factory=dict)

    # --- schedule construction -------------------------------------------

    def torn_write(self, label: str, nth: int = 1,
                   keep: Optional[int] = None,
                   path_substr: Optional[str] = None) -> "FaultPlan":
        self.rules.append(_Rule(_TORN, label, nth, keep, path_substr))
        return self

    def enospc(self, label: str, nth: int = 1,
               path_substr: Optional[str] = None) -> "FaultPlan":
        self.rules.append(_Rule(_ENOSPC, label, nth, None, path_substr))
        return self

    def fsync_lie(self, label: str,
                  path_substr: Optional[str] = None) -> "FaultPlan":
        self.rules.append(_Rule(_FSYNC_LIE, label, 0, None, path_substr))
        return self

    def bit_flip(self, label: str, nth: int = 1,
                 path_substr: Optional[str] = None) -> "FaultPlan":
        self.rules.append(_Rule(_BIT_FLIP, label, nth, None, path_substr))
        return self

    # --- deterministic parameter derivation ------------------------------

    def _derive(self, *parts: object) -> random.Random:
        """Seeded independently of call order: the same (seed, rule)
        always yields the same tear offset / flipped bit, no matter
        what other I/O happened first."""
        return random.Random("faultio:" + ":".join(
            str(p) for p in (self.seed,) + parts))

    def _note(self, kind: str, label: str, path: str, detail: str) -> None:
        now = timesource.time_ns() if timesource.installed() else 0
        self.events.append((now, kind, label, path, detail))

    def matches_label(self, label: str, path: str) -> bool:
        return any(r.matches(label, path) for r in self.rules)

    # --- fault application (called by FaultFile) -------------------------

    def on_write(self, ff: "FaultFile", data: bytes) -> bytes:
        """Returns the bytes actually written, raising for ENOSPC /
        torn-write faults. The caller has NOT written yet."""
        for r in self.rules:
            if r.fired or not r.matches(ff.label, ff.path):
                continue
            if r.kind == _ENOSPC:
                r.count += 1
                if r.count == r.nth:
                    r.fired = True
                    self._note(_ENOSPC, ff.label, ff.path, "")
                    raise InjectedFault(errno.ENOSPC,
                                        "injected: no space left on device",
                                        ff.path)
            elif r.kind == _TORN:
                r.count += 1
                if r.count == r.nth and len(data) > 0:
                    r.fired = True
                    keep = r.keep
                    if keep is None or not 0 <= keep < len(data):
                        keep = self._derive(
                            _TORN, ff.label, r.nth).randrange(len(data))
                    ff.raw.write(data[:keep])
                    ff.raw.flush()
                    self._note(_TORN, ff.label, ff.path,
                               f"keep={keep}/{len(data)}")
                    # literal (== TORN_WRITE_LABEL) so the failpoint
                    # registry lint can see it
                    fail_point("faultio:torn-write")
                    raise InjectedCrash(
                        f"torn write: {ff.label} {ff.path} "
                        f"kept {keep}/{len(data)}")
        return data

    def on_read(self, ff: "FaultFile", data: bytes) -> bytes:
        for r in self.rules:
            if (r.fired or r.kind != _BIT_FLIP
                    or not r.matches(ff.label, ff.path)):
                continue
            r.count += 1
            if r.count == r.nth and data:
                r.fired = True
                rng = self._derive(_BIT_FLIP, ff.label, r.nth)
                bit = rng.randrange(len(data) * 8)
                i, shift = divmod(bit, 8)
                data = (data[:i] + bytes([data[i] ^ (1 << shift)])
                        + data[i + 1:])
                self._note(_BIT_FLIP, ff.label, ff.path,
                           f"byte={i} bit={shift}")
        return data

    def on_fsync(self, ff: "FaultFile") -> bool:
        """True when the fsync should actually happen."""
        for r in self.rules:
            if r.kind == _FSYNC_LIE and r.matches(ff.label, ff.path):
                self._note(_FSYNC_LIE, ff.label, ff.path, "")
                return False
        return True

    def track_watermark(self, path: str, size: int) -> None:
        self._watermarks[path] = size

    def watermark_registered(self, path: str) -> bool:
        return path in self._watermarks

    def apply_crash(self) -> List[Tuple[str, int]]:
        """The power cut for fsync-lied files: truncate each back to
        its last honestly-durable length. Returns [(path, new_len)]."""
        out: List[Tuple[str, int]] = []
        for path, wm in sorted(self._watermarks.items()):
            if os.path.exists(path) and os.path.getsize(path) > wm:
                with open(path, "r+b") as f:
                    f.truncate(wm)
                out.append((path, wm))
        return out


class FaultFile:
    """File-object wrapper routing reads/writes/fsyncs through the
    installed plan. Only constructed when a rule matches (label, path);
    otherwise adopters hold the raw file object."""

    def __init__(self, plan: FaultPlan, raw, path: str, label: str):
        self.plan = plan
        self.raw = raw
        self.path = path
        self.label = label
        if plan.matches_label(label, path) and any(
                r.kind == _FSYNC_LIE and r.matches(label, path)
                for r in plan.rules):
            if not plan.watermark_registered(path):
                try:
                    plan.track_watermark(
                        path, os.fstat(raw.fileno()).st_size)
                except OSError:
                    plan.track_watermark(path, 0)

    # --- file protocol ----------------------------------------------------

    def write(self, data: bytes) -> int:
        self.plan.on_write(self, data)
        return self.raw.write(data)

    def read(self, n: int = -1) -> bytes:
        return self.plan.on_read(self, self.raw.read(n))

    def fsync(self) -> None:
        self.raw.flush()
        if self.plan.on_fsync(self):
            os.fsync(self.raw.fileno())
            if self.plan.watermark_registered(self.path):
                self.plan.track_watermark(
                    self.path, os.fstat(self.raw.fileno()).st_size)

    def flush(self) -> None:
        self.raw.flush()

    def close(self) -> None:
        self.raw.close()

    def truncate(self, size: Optional[int] = None) -> int:
        return self.raw.truncate(size)

    def seek(self, offset: int, whence: int = 0) -> int:
        return self.raw.seek(offset, whence)

    def tell(self) -> int:
        return self.raw.tell()

    def fileno(self) -> int:
        return self.raw.fileno()

    @property
    def closed(self) -> bool:
        return self.raw.closed

    def __enter__(self) -> "FaultFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# --- module seam -----------------------------------------------------------

_plan: Optional[FaultPlan] = None
_lock = threading.Lock()


def install(plan: FaultPlan) -> None:
    global _plan
    with _lock:
        _plan = plan


def reset() -> None:
    global _plan
    with _lock:
        _plan = None


def current() -> Optional[FaultPlan]:
    return _plan


def open_file(path: str, mode: str = "rb", label: str = ""):
    """The seam: every durable open in consensus/, db/, store/,
    privval/ goes through here (enforced by staticcheck raw-file-io).
    Returns the raw builtin file when no installed rule addresses
    (label, path) — the production path stays wrapper-free."""
    raw = open(path, mode)
    plan = _plan
    if plan is None or not plan.matches_label(label, path):
        return raw
    return FaultFile(plan, raw, path, label)


def fsync(f) -> None:
    """fsync through the seam: honors an fsync-lie rule when `f` is a
    FaultFile, plain os.fsync otherwise."""
    if isinstance(f, FaultFile):
        f.fsync()
    else:
        f.flush()
        os.fsync(f.fileno())


def fsync_path_dir(path: str) -> None:
    """Best-effort fsync of the directory containing `path` (rename
    durability); no-op where directories can't be opened."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _parse_env_spec(raw: str) -> Optional[FaultPlan]:
    """Malformed-tolerant: each ';'-entry is kind@label[@nth[@keep]] or
    seed=N; bad entries are skipped, zero good rules -> None."""
    if not raw:
        return None
    plan = FaultPlan()
    good = 0
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("seed="):
            try:
                plan.seed = int(entry[5:])
            except ValueError:
                pass
            continue
        parts = entry.split("@")
        kind = parts[0]
        if kind not in (_TORN, _ENOSPC, _FSYNC_LIE, _BIT_FLIP) \
                or len(parts) < 2 or not parts[1]:
            continue
        label = parts[1]
        try:
            nth = int(parts[2]) if len(parts) > 2 and parts[2] else 1
            keep = int(parts[3]) if len(parts) > 3 and parts[3] else None
        except ValueError:
            continue
        if kind == _TORN:
            plan.torn_write(label, nth, keep)
        elif kind == _ENOSPC:
            plan.enospc(label, nth)
        elif kind == _FSYNC_LIE:
            plan.fsync_lie(label)
        else:
            plan.bit_flip(label, nth)
        good += 1
    return plan if good else None


_env_plan = _parse_env_spec(os.environ.get("COMETBFT_TPU_FAULTIO", ""))
if _env_plan is not None:
    install(_env_plan)
