"""BitArray: the vote/part bitmap exchanged between peers
(reference internal/bits/bit_array.go).

Backed by a single python int (arbitrary-precision bitmask) instead of the
reference's []uint64 — the operations consensus gossip needs (or/and/sub,
pick-random-set-bit, copy) are one-liners on an int and the proto wire form
([]uint64 little-endian words) is produced only at the boundary.
"""

from __future__ import annotations

import random
from typing import List, Optional


class BitArray:
    __slots__ = ("bits", "_mask")

    def __init__(self, bits: int):
        if bits < 0:
            raise ValueError("negative size")
        self.bits = bits
        self._mask = 0

    # --- element access ------------------------------------------------------

    def size(self) -> int:
        return self.bits

    def get_index(self, i: int) -> bool:
        if not (0 <= i < self.bits):
            return False
        return bool((self._mask >> i) & 1)

    def set_index(self, i: int, v: bool) -> bool:
        if not (0 <= i < self.bits):
            return False
        if v:
            self._mask |= (1 << i)
        else:
            self._mask &= ~(1 << i)
        return True

    def copy(self) -> "BitArray":
        out = BitArray(self.bits)
        out._mask = self._mask
        return out

    # --- set algebra (sizes may differ; result max size, ref behavior) -------

    def or_(self, other: "BitArray") -> "BitArray":
        out = BitArray(max(self.bits, other.bits))
        out._mask = self._mask | other._mask
        return out

    def and_(self, other: "BitArray") -> "BitArray":
        out = BitArray(min(self.bits, other.bits))
        out._mask = self._mask & other._mask & ((1 << out.bits) - 1)
        return out

    def not_(self) -> "BitArray":
        out = BitArray(self.bits)
        out._mask = ~self._mask & ((1 << self.bits) - 1)
        return out

    def sub(self, other: "BitArray") -> "BitArray":
        """Bits set in self but not in other (reference Sub: trailing bits
        of a shorter `other` are treated as unset)."""
        out = BitArray(self.bits)
        out._mask = self._mask & ~other._mask
        return out

    def update(self, other: "BitArray") -> None:
        """Overwrite contents from other (sizes must match, ref Update)."""
        if other.bits != self.bits:
            raise ValueError("BitArray sizes differ")
        self._mask = other._mask

    # --- queries -------------------------------------------------------------

    def is_empty(self) -> bool:
        return self._mask == 0

    def is_full(self) -> bool:
        return self.bits > 0 and self._mask == (1 << self.bits) - 1

    def ones(self) -> List[int]:
        m = self._mask
        out = []
        i = 0
        while m:
            if m & 1:
                out.append(i)
            m >>= 1
            i += 1
        return out

    def num_true_bits(self) -> int:
        return self._mask.bit_count()

    def pick_random(self, rng: Optional[random.Random] = None
                    ) -> Optional[int]:
        """A uniformly random set bit, or None (reference PickRandom)."""
        ones = self.ones()
        if not ones:
            return None
        return (rng or random).choice(ones)

    # --- wire ----------------------------------------------------------------

    def to_words(self) -> List[int]:
        """[]uint64 little-endian words (proto libs.bits.v1.BitArray elems)."""
        n = (self.bits + 63) // 64
        return [(self._mask >> (64 * i)) & 0xFFFFFFFFFFFFFFFF
                for i in range(n)]

    @classmethod
    def from_words(cls, bits: int, words: List[int]) -> "BitArray":
        out = cls(bits)
        m = 0
        for i, w in enumerate(words):
            m |= (w & 0xFFFFFFFFFFFFFFFF) << (64 * i)
        out._mask = m & ((1 << bits) - 1) if bits else 0
        return out

    def __eq__(self, other) -> bool:
        return (isinstance(other, BitArray) and other.bits == self.bits
                and other._mask == self._mask)

    def __repr__(self) -> str:
        s = "".join("x" if self.get_index(i) else "_"
                    for i in range(min(self.bits, 60)))
        return f"BA{{{self.bits}:{s}}}"
