"""Tolerant environment-knob parsing shared by every tunable subsystem.

A malformed env override must degrade to the compiled-in default, never
abort node boot or blocksync startup: operators fat-finger
`COMETBFT_TPU_*` knobs in systemd units and container manifests, and a
ValueError from deep inside the verify path would turn a typo into an
outage. Previously this guard was copy-pasted in pipeline/watchdog.py
and device/client.py (with subtly different blast radius — the client
variant reset BOTH knobs when either was malformed); it lives here once
and also serves the device-health backoff knobs.
"""

from __future__ import annotations

import os


def env_float(name: str, default: float) -> float:
    """float(os.environ[name]) with `default` for unset OR malformed."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def env_bool(name: str, default: bool) -> bool:
    """Boolean knob: 1/true/yes/on (any case) is True, 0/false/no/off
    is False, unset or unrecognized is `default`."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    val = raw.strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    return default
