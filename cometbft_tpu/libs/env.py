"""Tolerant environment-knob parsing shared by every tunable subsystem.

A malformed env override must degrade to the compiled-in default, never
abort node boot or blocksync startup: operators fat-finger
`COMETBFT_TPU_*` knobs in systemd units and container manifests, and a
ValueError from deep inside the verify path would turn a typo into an
outage. Previously this guard was copy-pasted in pipeline/watchdog.py
and device/client.py (with subtly different blast radius — the client
variant reset BOTH knobs when either was malformed); it lives here once
and also serves the device-health backoff knobs, the p2p keepalive
windows, the Pallas tile size, and the signature-cache capacity.

`tools/staticcheck`'s raw-env rule enforces the seam: a bare
`int(os.environ.get(...))` outside this module is a lint error, so new
knobs inherit the malformed-tolerant behavior automatically.

Semantics shared by env_float/env_int:
  * unset → default
  * unparseable (empty, whitespace, wrong radix, "1.5" for an int) →
    default
  * NaN → default (a NaN knob poisons every comparison it feeds)
  * `minimum` given and value < minimum → default (negative deadlines,
    capacities, intervals are nonsensical; +inf stays allowed — it
    reads as "never")
"""

from __future__ import annotations

import math
import os


def env_float(name: str, default: float,
              minimum: "float | None" = None) -> float:
    """float(os.environ[name]) with `default` for unset, malformed,
    NaN, or below `minimum`."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = float(raw)
    except ValueError:
        return default
    if math.isnan(val):
        return default
    if minimum is not None and val < minimum:
        return default
    return val


def env_int(name: str, default: int,
            minimum: "int | None" = None) -> int:
    """int(os.environ[name]) with `default` for unset, malformed
    (including float strings like "1.5"), or below `minimum`."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        val = int(raw.strip())
    except ValueError:
        return default
    if minimum is not None and val < minimum:
        return default
    return val


def env_bool(name: str, default: bool) -> bool:
    """Boolean knob: 1/true/yes/on (any case) is True, 0/false/no/off
    is False, unset or unrecognized is `default`."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    val = raw.strip().lower()
    if val in ("1", "true", "yes", "on"):
        return True
    if val in ("0", "false", "no", "off"):
        return False
    return default
