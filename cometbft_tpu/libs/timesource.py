"""Process-wide time seam — the virtual-clock contract for simnet.

Production code that (a) stamps protocol data (`types/proto.Timestamp.now`)
or (b) makes rate/timeout decisions outside the consensus ticker
(consensus/reactor catch-up budgets, blocksync status deadlines) reads
time through this module instead of `time` directly. By default both
functions are the stdlib clocks, so live nodes behave identically to
before the seam existed.

`cometbft_tpu/simnet` installs a virtual source for the duration of a
simulation run: all N in-process nodes then observe one deterministic
clock that only advances when the event queue says so, which is what
makes two runs with the same seed produce byte-identical event logs
(docs/SIMNET.md "virtual-clock seam contract").

The seam is deliberately tiny:

  install(now_ns_fn)  — now_ns_fn() -> int nanoseconds since the Unix
                        epoch (virtual). monotonic() is derived from it,
                        so one function drives both clock families.
  reset()             — back to wall clocks.

Code holding a long-lived reference to `time.monotonic` (thread loops
that must keep running during a sim, e.g. mconn ping routines) is
intentionally NOT routed through here — the seam covers only paths the
simulator executes on its own thread.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

_virtual_now_ns: Optional[Callable[[], int]] = None


def install(now_ns_fn: Callable[[], int]) -> None:
    """Route monotonic()/time_ns() through `now_ns_fn` (simnet only)."""
    global _virtual_now_ns
    _virtual_now_ns = now_ns_fn


def reset() -> None:
    global _virtual_now_ns
    _virtual_now_ns = None


def installed() -> bool:
    return _virtual_now_ns is not None


def time_ns() -> int:
    """Wall (or virtual) nanoseconds since the epoch — feeds
    types/proto.Timestamp.now and therefore every vote/block time."""
    if _virtual_now_ns is not None:
        return _virtual_now_ns()
    return _time.time_ns()


def monotonic() -> float:
    """Monotonic seconds for elapsed-time decisions (token buckets,
    reconcile budgets, status deadlines). Under a virtual source this is
    simply virtual-epoch seconds — virtual time never goes backwards."""
    if _virtual_now_ns is not None:
        return _virtual_now_ns() / 1e9
    return _time.monotonic()


def sleep(seconds: float) -> None:
    """Polling-loop pause. Real sleep on wall clocks; under a virtual
    source a short REAL yield instead — the loop's deadline math reads
    the virtual clock, so blocking this thread for virtual seconds
    would deadlock the simulator that owns clock advancement."""
    if _virtual_now_ns is not None:
        _time.sleep(0.001)
        return
    _time.sleep(seconds)
