"""Crash-injection points (reference internal/fail/fail.go:47 — the
FAIL_TEST_INDEX mechanism sprinkled through the commit path,
state/execution.go:262-312, consensus state.go:1857-1897).

Set COMETBFT_TPU_FAIL_INDEX=N (or call set_fail_index) and the Nth
`fail_point()` crossed in the process exits hard — exercising every
crash-recovery class (WAL replay, handshake replay, torn files) without
hand-placed kill timing.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_counter = 0
_target = int(os.environ.get("COMETBFT_TPU_FAIL_INDEX", "-1"))
# label-targeted variant: COMETBFT_TPU_FAIL_LABEL="wal:pre-rotate-rename:0"
# crashes at the k-th crossing of exactly that label (for points that are
# crossed data-dependently, e.g. WAL rotation, where a global index is
# not predictable)
_label_target: "tuple[str, int] | None" = None
_label_counter = 0
_env_label = os.environ.get("COMETBFT_TPU_FAIL_LABEL", "")
if _env_label:
    # labels contain colons ("wal:pre-rotate-rename"), so the :k
    # suffix is optional — a bare label means its first crossing
    _name, _, _k = _env_label.rpartition(":")
    if _name and _k.isdigit():
        _label_target = (_name, int(_k))
    else:
        _label_target = (_env_label, 0)


def set_fail_index(n: int) -> None:
    global _target, _counter
    with _lock:
        _target = n
        _counter = 0


def set_fail_label(label: str, k: int = 0) -> None:
    global _label_target, _label_counter
    with _lock:
        _label_target = (label, k)
        _label_counter = 0


def fail_point(label: str = "") -> None:
    """Crash (os._exit, no cleanup — like a power cut) when this is the
    configured failure index, or the k-th crossing of the configured
    failure label."""
    global _counter, _label_counter
    if _target < 0 and _label_target is None:
        return
    hit = False
    with _lock:
        if _target >= 0:
            hit = _counter == _target
            _counter += 1
        if not hit and _label_target is not None \
                and label == _label_target[0]:
            hit = _label_counter == _label_target[1]
            _label_counter += 1
    if hit:
        import sys
        print(f"FAIL_POINT hit: {label}", file=sys.stderr, flush=True)
        os._exit(99)
