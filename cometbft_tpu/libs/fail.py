"""Crash-injection points (reference internal/fail/fail.go:47 — the
FAIL_TEST_INDEX mechanism sprinkled through the commit path,
state/execution.go:262-312, consensus state.go:1857-1897).

Set COMETBFT_TPU_FAIL_INDEX=N (or call set_fail_index) and the Nth
`fail_point()` crossed in the process exits hard — exercising every
crash-recovery class (WAL replay, handshake replay, torn files) without
hand-placed kill timing.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_counter = 0
_target = int(os.environ.get("COMETBFT_TPU_FAIL_INDEX", "-1"))


def set_fail_index(n: int) -> None:
    global _target, _counter
    with _lock:
        _target = n
        _counter = 0


def fail_point(label: str = "") -> None:
    """Crash (os._exit, no cleanup — like a power cut) when this is the
    configured failure index."""
    global _counter
    if _target < 0:
        return
    with _lock:
        hit = _counter == _target
        _counter += 1
    if hit:
        import sys
        print(f"FAIL_POINT hit: {label}", file=sys.stderr, flush=True)
        os._exit(99)
