"""Crash-injection points (reference internal/fail/fail.go:47 — the
FAIL_TEST_INDEX mechanism sprinkled through the commit path,
state/execution.go:262-312, consensus state.go:1857-1897).

Set COMETBFT_TPU_FAIL_INDEX=N (or call set_fail_index) and the Nth
`fail_point()` crossed in the process exits hard — exercising every
crash-recovery class (WAL replay, handshake replay, torn files) without
hand-placed kill timing.

A third mode serves the in-process simulator (cometbft_tpu/simnet):
`set_fail_hook(fn)` registers a callable invoked at every fail point
crossing with the point's label. The hook may raise to unwind the
current node's stack at exactly the label's position — simnet raises
its `SimCrash` there and models the crash by discarding the node's
in-memory state while keeping its stores/WAL, the in-process analog of
the os._exit the env-var modes perform. The env modes take precedence:
in a process where either is configured, the hook never runs and the
crossing counters stay exact.
"""

from __future__ import annotations

import os
import threading

from .env import env_int

_lock = threading.Lock()
_counter = 0
# malformed index = disarmed (-1), not an import-time crash
_target = env_int("COMETBFT_TPU_FAIL_INDEX", -1)
# label-targeted variant: COMETBFT_TPU_FAIL_LABEL="wal:pre-rotate-rename:0"
# crashes at the k-th crossing of exactly that label (for points that are
# crossed data-dependently, e.g. WAL rotation, where a global index is
# not predictable)
_label_target: "tuple[str, int] | None" = None
_label_counter = 0
_env_label = os.environ.get("COMETBFT_TPU_FAIL_LABEL", "")
if _env_label:
    # labels contain colons ("wal:pre-rotate-rename"), so the :k
    # suffix is optional — a bare label means its first crossing
    _name, _, _k = _env_label.rpartition(":")
    if _name and _k.isdigit():
        _label_target = (_name, int(_k))
    else:
        _label_target = (_env_label, 0)


def set_fail_index(n: int) -> None:
    global _target, _counter
    with _lock:
        _target = n
        _counter = 0


def set_fail_label(label: str, k: int = 0) -> None:
    global _label_target, _label_counter
    with _lock:
        _label_target = (label, k)
        _label_counter = 0


# in-process hook (simnet crash schedules); None = disabled. Read
# without the lock — a single-slot reference swap, and the simulator
# that installs it is single-threaded by construction.
_hook = None


def set_fail_hook(fn) -> None:
    """Register fn(label) to run at every fail point crossing. The
    callable may raise to simulate a crash in-process (simnet)."""
    global _hook
    _hook = fn


def clear_fail_hook() -> None:
    global _hook
    _hook = None


def fail_point(label: str = "") -> None:
    """Crash (os._exit, no cleanup — like a power cut) when this is the
    configured failure index, or the k-th crossing of the configured
    failure label. The env-configured crash modes take precedence over
    a registered hook: while either is armed, crossings feed their
    counters (and crash at the target) exactly as if no hook existed;
    the hook receives crossings only in processes with no env mode
    configured — the simulator's case."""
    global _counter, _label_counter
    if _target < 0 and _label_target is None:
        if _hook is not None:
            _hook(label)
        return
    hit = False
    with _lock:
        if _target >= 0:
            hit = _counter == _target
            _counter += 1
        if not hit and _label_target is not None \
                and label == _label_target[0]:
            hit = _label_counter == _label_target[1]
            _label_counter += 1
    if hit:
        import sys
        print(f"FAIL_POINT hit: {label}", file=sys.stderr, flush=True)
        os._exit(99)
