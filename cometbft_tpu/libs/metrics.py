"""Prometheus-style metrics (reference scripts/metricsgen + the
per-package metrics.go structs, e.g. internal/consensus/metrics.go:34).

Counters, gauges, and histograms with label support, rendered in the
Prometheus text exposition format. `Registry.expose()` plugs into any
HTTP handler (config [instrumentation], reference config.go:1378-1384).
Metrics structs come in two flavors: hand-written (ConsensusMetrics
below — predates the codegen and is kept in place to avoid churning
consensus wiring) and GENERATED from libs/metrics_defs.py by
tools/metricsgen.py into libs/metrics_gen.py (the reference's
scripts/metricsgen pattern). New structs should use the spec +
generator.
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Dict, List, Optional, Sequence, Tuple


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str]):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return tuple(labels.get(n, "") for n in self.label_names)

    @staticmethod
    def _fmt_labels(names, values) -> str:
        if not names:
            return ""
        inner = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
        return "{" + inner + "}"


class Counter(_Metric):
    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        with self._lock:  # writers insert label keys concurrently
            items = sorted(self._values.items())
        for k, v in items:
            out.append(f"{self.name}"
                       f"{self._fmt_labels(self.label_names, k)} {v}")
        return out


class Gauge(_Metric):
    def __init__(self, name, help_="", label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def add(self, amount: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} gauge"]
        with self._lock:  # writers insert label keys concurrently
            items = sorted(self._values.items())
        for k, v in items:
            out.append(f"{self.name}"
                       f"{self._fmt_labels(self.label_names, k)} {v}")
        return out


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 2.5, 5, 10)


class Histogram(_Metric):
    """Step-duration histograms double as consensus timing metrics
    (reference RoundDurationSeconds, BlockProcessingTime)."""

    def __init__(self, name, help_="", label_names=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            counts = self._counts.setdefault(
                k, [0] * (len(self.buckets) + 1))
            counts[bisect_right(self.buckets, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:  # snapshot: observe() mutates concurrently
            items = [(k, list(c), self._sums[k])
                     for k, c in sorted(self._counts.items())]
        for k, counts, total_sum in items:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                names = self.label_names + ("le",)
                vals = k + (str(b),)
                out.append(f"{self.name}_bucket"
                           f"{self._fmt_labels(names, vals)} {cum}")
            total = sum(counts)
            names = self.label_names + ("le",)
            out.append(f"{self.name}_bucket"
                       f"{self._fmt_labels(names, k + ('+Inf',))} {total}")
            out.append(f"{self.name}_sum"
                       f"{self._fmt_labels(self.label_names, k)} "
                       f"{total_sum}")
            out.append(f"{self.name}_count"
                       f"{self._fmt_labels(self.label_names, k)} {total}")
        return out


class CallbackGauge(_Metric):
    """Gauge whose value is read from a callable at scrape time — for
    counters owned by modules that must not depend on a Registry (e.g.
    the ops-layer pallas canary, ops/ed25519.canary_stats)."""

    def __init__(self, name, help_="", fn=None):
        super().__init__(name, help_, ())
        self._fn = fn or (lambda: 0.0)

    def value(self) -> float:
        return float(self._fn())

    def expose(self) -> List[str]:
        try:
            v = float(self._fn())
        except Exception:  # noqa: BLE001 — scrape must never die
            v = float("nan")
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} gauge",
                f"{self.name} {v}"]


class Registry:
    def __init__(self, namespace: str = "cometbft_tpu"):
        self.namespace = namespace
        self._metrics: List[_Metric] = []
        self._lock = threading.Lock()

    def counter(self, name, help_="", label_names=()) -> Counter:
        return self._add(Counter(f"{self.namespace}_{name}", help_,
                                 label_names))

    def gauge(self, name, help_="", label_names=()) -> Gauge:
        return self._add(Gauge(f"{self.namespace}_{name}", help_,
                               label_names))

    def histogram(self, name, help_="", label_names=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._add(Histogram(f"{self.namespace}_{name}", help_,
                                   label_names, buckets))

    def callback_gauge(self, name, help_="", fn=None) -> CallbackGauge:
        return self._add(CallbackGauge(f"{self.namespace}_{name}",
                                       help_, fn))

    def _add(self, m):
        with self._lock:
            self._metrics.append(m)
        return m

    def expose(self) -> str:
        lines: List[str] = []
        for m in self._metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class ConsensusMetrics:
    """The reference's consensus metrics struct
    (internal/consensus/metrics.go), constructed over a Registry."""

    def __init__(self, reg: Registry):
        self.height = reg.gauge("consensus_height", "Committed height")
        self.rounds = reg.counter("consensus_rounds",
                                  "Rounds entered", ["reason"])
        self.round_duration = reg.histogram(
            "consensus_round_duration_seconds",
            "Time spent per consensus round")
        self.block_processing = reg.histogram(
            "consensus_block_processing_seconds",
            "ApplyBlock wall time")
        self.validators = reg.gauge("consensus_validators",
                                    "Validator-set size")
        self.byzantine_validators = reg.counter(
            "consensus_byzantine_validators",
            "Conflicting votes observed")
        self.sigs_verified = reg.counter(
            "crypto_sigs_verified", "Signatures verified", ["path"])
