"""Declarative metric definitions — input to tools/metricsgen.py
(reference scripts/metricsgen/metricsgen.go, which generates
metrics.gen.go constructors from struct tags; here the "struct tags"
are this spec and the generated constructors land in
libs/metrics_gen.py).

Regenerate after editing:  python tools/metricsgen.py
A freshness test (tests/test_metricsgen.py) fails if the generated
file drifts from this spec.
"""

# struct name -> list of (kind, field, metric_name, help, label_names)
# kind in {"counter", "gauge", "histogram"}
#
# ConsensusMetrics stays hand-written in libs/metrics.py: it predates
# this generator and migrating it would churn consensus wiring for no
# behavior change; every NEW struct belongs here.
METRICS_SPEC = {
    # reference p2p/metrics.go
    "P2PMetrics": [
        ("gauge", "peers", "p2p_peers",
         "Number of connected peers", ()),
        ("counter", "message_send_bytes_total",
         "p2p_message_send_bytes_total",
         "Bytes sent to peers, by channel", ("ch_id",)),
        ("counter", "message_receive_bytes_total",
         "p2p_message_receive_bytes_total",
         "Bytes received from peers, by channel", ("ch_id",)),
        ("counter", "peer_dial_failures", "p2p_peer_dial_failures",
         "Failed outbound dial attempts", ()),
    ],
    # pipeline/ — the asynchronous multi-tile verification data plane
    # (pipeline/scheduler.py, watchdog.py, cache.py); cache hit rate =
    # hits / (hits + misses) per intake path
    "PipelineMetrics": [
        ("gauge", "tiles_in_flight", "pipeline_tiles_in_flight",
         "Tiles dispatched to the verify backend but not yet applied",
         ()),
        ("gauge", "stage_occupancy", "pipeline_stage_occupancy",
         "Tiles resident per pipeline stage", ("stage",)),
        ("counter", "tiles_dispatched", "pipeline_tiles_dispatched",
         "Tiles submitted to the verify backend", ()),
        ("counter", "wedge_fallbacks", "pipeline_wedge_fallbacks",
         "Tiles drained to the CPU fallback by the device-wedge "
         "watchdog", ()),
        ("counter", "cache_hits", "pipeline_sigcache_hits",
         "Verified-signature cache hits, by intake path", ("path",)),
        ("counter", "cache_misses", "pipeline_sigcache_misses",
         "Verified-signature cache misses, by intake path", ("path",)),
        ("counter", "cache_evictions", "pipeline_sigcache_evictions",
         "Verified-signature cache LRU evictions", ()),
    ],
    # device/health.py — the verification-backend health supervisor
    # (HEALTHY=0 SUSPECT=1 PROBING=2 QUARANTINED=3 state machine,
    # known-answer probes, canary-lane corruption detection)
    "DeviceMetrics": [
        ("gauge", "health_state", "device_health_state",
         "Verify-backend health state (0=healthy 1=suspect 2=probing "
         "3=quarantined)", ()),
        ("counter", "probes_total", "device_probes_total",
         "Known-answer probe batches sent to a suspect verify backend",
         ()),
        ("counter", "quarantines_total", "device_quarantines_total",
         "Terminal verify-backend quarantines (corrupt verdicts)", ()),
        ("counter", "canary_failures", "device_canary_failures",
         "Device batches whose canary lanes answered wrong", ()),
    ],
    # farm/ — the light-client verification farm (farm/service.py,
    # batcher.py, session.py): many clients' skipping checks coalesced
    # into shared device batches
    "FarmMetrics": [
        ("gauge", "sessions", "farm_sessions",
         "Active light-client sessions", ()),
        ("counter", "headers_accepted", "farm_headers_accepted",
         "Headers accepted into session trust stores", ()),
        ("counter", "headers_rejected", "farm_headers_rejected",
         "Verify/subscribe requests rejected by the acceptance rules",
         ()),
        ("counter", "batches", "farm_batches",
         "Coalesced verify batches flushed", ()),
        ("gauge", "batch_width", "farm_batch_width",
         "Unique-lane width of the most recent coalesced batch", ()),
        ("counter", "lanes", "farm_lanes_verified",
         "Signature lanes verified, by backend (device = server seam, "
         "kernel = ledger-warm local batch kernel, cpu = per-sig "
         "native)", ("backend",)),
        ("counter", "dedup_hits", "farm_dedup_hits",
         "Lanes skipped by dedup (batch=intra-batch; SigCache hits "
         "show under pipeline_sigcache_hits path=farm)", ("kind",)),
        ("counter", "shed", "farm_shed_total",
         "Requests shed by backpressure (session cap or lane queue)",
         ()),
    ],
    # ingest/ — the batched CheckTx admission pipeline (admission.py,
    # batcher.py, dispatcher.py): broadcast_tx_* / p2p-relayed txs
    # coalesced into shared signature batches with explicit
    # backpressure (docs/INGEST.md)
    "IngestMetrics": [
        ("gauge", "queue_depth", "ingest_queue_depth",
         "Txs parked in the admission queue awaiting a batch flush",
         ()),
        ("gauge", "batch_width", "ingest_batch_width",
         "Unique signature lanes in the most recent admission batch",
         ()),
        ("counter", "batches", "ingest_batches",
         "Coalesced admission batches flushed", ()),
        ("counter", "admitted", "ingest_admitted_txs",
         "Txs admitted into the mempool through the ingest pipeline",
         ()),
        ("counter", "rejected", "ingest_rejected_txs",
         "Txs rejected at admission, by reason (sig=bad envelope "
         "signature, app=app CheckTx code, mempool=structural)",
         ("reason",)),
        ("counter", "shed", "ingest_shed_total",
         "Txs shed by admission-queue backpressure", ()),
        ("counter", "dedup_hits", "ingest_dedup_hits",
         "Admission dedup hits (txhash=duplicate filter, batch=intra-"
         "batch lane collapse; SigCache hits show under "
         "pipeline_sigcache_hits path=ingest)", ("kind",)),
        ("counter", "lanes", "ingest_lanes_verified",
         "Tx signature lanes verified, by backend (device vs cpu)",
         ("backend",)),
        ("histogram", "admission_latency",
         "ingest_admission_latency_seconds",
         "Submit-to-verdict admission latency, seconds", ()),
    ],
    # aggsig/ — the BLS aggregate-commit fast path (aggsig/verify.py):
    # one multi-pairing check per commit instead of n signature
    # verifies, kernel-batched final exponentiations during blocksync
    "AggsigMetrics": [
        ("counter", "pairings_total", "aggsig_pairings_total",
         "Miller-loop evaluations spent verifying aggregated commits "
         "(the O(1)-per-commit evidence vs 2n per-signature)", ()),
        ("counter", "aggregates_verified", "aggsig_aggregates_verified",
         "Aggregated-commit final-exponentiation verdicts, by backend "
         "(kernel vs cpu)", ("backend",)),
        ("counter", "pop_rejections", "aggsig_pop_rejections",
         "Proof-of-possession failures (bad PoP at admission, or an "
         "aggregate signer without a registered PoP)", ()),
        ("counter", "canary_failures", "aggsig_canary_failures",
         "Kernel batches whose known-answer final-exp canaries "
         "answered wrong (kernel quarantined, batch re-run on CPU)",
         ()),
    ],
    # mesh/ — multi-chip sharded verification (topology.py,
    # planner.py, executor.py, shard_health.py): the serving device
    # mesh, its degrade/regrow arc, and per-shard verdict safety
    "MeshMetrics": [
        ("gauge", "shards_total", "mesh_shards_total",
         "Devices discovered into the verification mesh", ()),
        ("gauge", "shards_healthy", "mesh_shards_healthy",
         "Shards currently serving (total minus masked)", ()),
        ("counter", "refactors", "mesh_refactors_total",
         "Topology re-factorings (shard masked out or regrown)", ()),
        ("counter", "shard_quarantines", "mesh_shard_quarantines_total",
         "Shards masked out for wrong canary/pad verdicts", ()),
        ("counter", "shard_regrows", "mesh_shard_regrows_total",
         "Masked shards readmitted after a correct known-answer probe",
         ()),
        ("counter", "shard_probes", "mesh_shard_probes_total",
         "Known-answer regrow probes sent to masked shards", ()),
        ("counter", "shard_canary_failures",
         "mesh_shard_canary_failures",
         "Per-shard canary/pad rows that answered wrong (dispatch or "
         "probe)", ()),
        ("counter", "tiles", "mesh_tiles_dispatched",
         "Batches dispatched through the mesh executor", ()),
        ("counter", "lanes", "mesh_lanes_verified",
         "Signature lanes verified, by backend (mesh; cpu = the "
         "canary-failure re-verify or the cold-shape fallback while a "
         "re-factored mesh compiles in the background)", ("backend",)),
    ],
    # trace/ — the flight-recorder span pipeline (trace/span.py,
    # recorder.py): bounded ring occupancy, drop-oldest evictions, and
    # dump-on-trigger counts by trigger kind (docs/TRACE.md)
    "TraceMetrics": [
        ("counter", "spans", "trace_spans_recorded",
         "Spans recorded into the flight-recorder ring", ()),
        ("counter", "dropped", "trace_spans_dropped",
         "Spans evicted from the full ring (drop-oldest)", ()),
        ("counter", "dumps", "trace_dumps_total",
         "Flight-recorder dumps, by trigger kind (watchdog-trip, "
         "canary-failure, shard-quarantine, shed-burst)", ("kind",)),
        ("gauge", "ring_occupancy", "trace_ring_occupancy",
         "Spans currently resident in the flight-recorder ring", ()),
    ],
    # sealsync/ — aggregate-seal catch-up (provider.py serving,
    # adopter.py settlement + install; docs/SEALSYNC.md). The headline
    # ratio is pairings_skipped / (pivots_verified + pairings_skipped):
    # the fraction of decided heights adopted without their own pairing
    "SealsyncMetrics": [
        ("counter", "seals_served", "sealsync_seals_served",
         "Seal tuples served to catching-up peers", ()),
        ("counter", "serve_sheds", "sealsync_serve_sheds",
         "Seal-range requests shed by provider backpressure", ()),
        ("counter", "seals_adopted", "sealsync_seals_adopted",
         "Decided heights adopted from seals (pivot or skipped)", ()),
        ("counter", "pivots_verified", "sealsync_pivots_verified",
         "Pivot seals settled through the pairing checker", ()),
        ("counter", "pairings_skipped", "sealsync_pairings_skipped",
         "Adopted heights whose pairing was elided by hash-chain "
         "binding to a verified pivot", ()),
        ("counter", "adoptions_rejected", "sealsync_adoptions_rejected",
         "Seal spans rejected (chain-rule violation, bad epoch PoP, "
         "or forged pivot pairing)", ()),
        ("counter", "pop_rejections", "sealsync_pop_rejections",
         "Epoch validator-set PoPs that failed verification during "
         "adoption", ()),
        ("gauge", "adopted_tip", "sealsync_adopted_tip",
         "Highest height with adopted (seal-derived) finality", ()),
    ],
    # storage crash consistency (db/kv.py v2 replay, consensus/wal.py,
    # store/recovery.py boot doctor): every boot-time repair is
    # attributed by kind, and disk damage (CRC failures, mid-group WAL
    # corruption, discarded uncommitted batches) is counted instead of
    # silently truncating replay (docs/STORAGE.md)
    "StorageMetrics": [
        ("counter", "doctor_runs", "storage_doctor_runs",
         "Boot-time recovery-doctor passes completed", ()),
        ("counter", "doctor_repairs", "storage_doctor_repairs",
         "Recovery-doctor repairs applied, by kind (meta-without-parts,"
         " orphaned-adopted-seal, stale-compact, stale-pv-tmp)",
         ("kind",)),
        ("counter", "wal_corruption", "storage_wal_corruption",
         "Mid-group WAL CRC/length corruption events that truncated "
         "replay (disk damage, not crash-repair)", ()),
        ("counter", "torn_batches", "storage_torn_batches",
         "Uncommitted FileDB batch tails discarded at replay "
         "(crashed write_batch rolled back all-or-nothing)", ()),
        ("counter", "crc_failures", "storage_crc_failures",
         "FileDB v2 records failing CRC at replay (bit-rot detected "
         "instead of silently replayed)", ()),
    ],
    # reference mempool/metrics.go
    "MempoolMetrics": [
        ("gauge", "size", "mempool_size",
         "Transactions in the mempool", ()),
        ("gauge", "size_bytes", "mempool_size_bytes",
         "Total byte size of mempool transactions", ()),
        ("counter", "failed_txs", "mempool_failed_txs",
         "Transactions rejected by CheckTx", ()),
        ("counter", "evicted_txs", "mempool_evicted_txs",
         "Txs removed as invalid on post-commit recheck", ()),
        ("counter", "recheck_times", "mempool_recheck_times",
         "Post-commit recheck passes over the pool", ()),
    ],
}
