"""VerificationFarm: the service object behind the light_* RPC routes.

Lifecycle per client:

  subscribe(height, hash, period)  pin a trust root exactly like
                                   light/client.py _initialize (hash
                                   match + the root commit verified
                                   through the shared batch)
  verify(session, height)          plan the bisection schedule from
                                   the session's latest trusted header
                                   (planner.py), coalesce its lanes
                                   with every other in-flight request
                                   (batcher.py), then commit verified
                                   steps to the session store IN ORDER
                                   — a failed step rejects the request
                                   and nothing past it is trusted
  status([session])                farm-wide counters or one session's
                                   trust state

Two-phase verify (`begin_verify` / `finish_verify`) is the coalescing
seam: the RPC route calls blocking `verify()` (concurrent HTTP worker
threads coalesce through the batcher's window), while deterministic
drivers — the light-farm simnet scenario, `bench_light.py --farm` —
begin a whole wave of clients, flush once, and finish each.

Every accepted header appends a decision record
(tools/check_light_spec.check_decisions re-judges them against the
spec/LightClient.tla acceptance rules); `decision_log` is bounded so a
long-lived farm does not grow without bound.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..libs.env import env_int
from ..libs.fail import fail_point
from ..light import verifier
from ..light.provider import ProviderError
from ..light.types import LightBlock, LightBlockError
from ..pipeline.cache import SigCache, shared_cache
from ..types.proto import Timestamp
from ..types.validation import (CommitVerificationError,
                                DEFAULT_TRUST_LEVEL, Fraction)
from . import planner
from .batcher import CheckTicket, FarmBatcher, QueueFull
from .session import FarmSession, SessionError, SessionLimitExceeded, \
    SessionManager

ENV_MAX_FETCHES = "COMETBFT_TPU_FARM_MAX_FETCHES"
DEFAULT_MAX_FETCHES = 128
ENV_DECISION_LOG = "COMETBFT_TPU_FARM_DECISION_LOG"
DEFAULT_DECISION_LOG = 4096


class FarmError(Exception):
    pass


class FarmOverloaded(FarmError):
    """Shed: session limit or verify queue full — retryable."""


class UnknownSession(FarmError):
    pass


class VerifyRejected(FarmError):
    """The request failed the acceptance rules (or a provider could
    not serve the needed headers). Carries the reason; the session
    stays usable at its previous trust state."""


@dataclass
class PendingVerify:
    """An in-flight verify between begin and finish."""
    session: FarmSession
    target_height: int
    steps: List[planner.VerifyStep]
    tickets: List[List[CheckTicket]]  # per step, aligned with checks
    cached: Optional[LightBlock] = None  # already-trusted fast path


@dataclass
class PendingSubscribe:
    session: FarmSession
    root: LightBlock
    tickets: List[CheckTicket] = field(default_factory=list)


class VerificationFarm:
    """One farm per served chain; thread-safe."""

    def __init__(self, chain_id: str, provider,
                 cache: Optional[SigCache] = None,
                 sessions: Optional[SessionManager] = None,
                 batcher: Optional[FarmBatcher] = None,
                 metrics=None,
                 now_fn: Callable[[], Timestamp] = Timestamp.now,
                 max_fetches: Optional[int] = None):
        self.chain_id = chain_id
        self.provider = provider
        self.metrics = metrics  # libs/metrics_gen.FarmMetrics or None
        self.cache = cache if cache is not None else shared_cache()
        # `is not None`, not `or`: an EMPTY SessionManager is falsy
        # (it defines __len__), and a caller's bounded instance must
        # never be silently swapped for the unbounded default
        self.sessions = (sessions if sessions is not None
                         else SessionManager(metrics=metrics))
        self.batcher = (batcher if batcher is not None
                        else FarmBatcher(cache=self.cache,
                                         metrics=metrics))
        self._now = now_fn
        if max_fetches is None:
            max_fetches = env_int(ENV_MAX_FETCHES, DEFAULT_MAX_FETCHES,
                                  minimum=1)
        self.max_fetches = max_fetches
        self._lock = threading.Lock()
        # guarded-by: _lock: decision_log, headers_accepted, headers_rejected
        self.decision_log: List[Dict] = []
        self._decision_cap = env_int(ENV_DECISION_LOG,
                                     DEFAULT_DECISION_LOG, minimum=0)
        self.headers_accepted = 0
        self.headers_rejected = 0

    # --- subscribe --------------------------------------------------------

    def begin_subscribe(self, trusted_height: int, trusted_hash: bytes,
                        trusting_period_s: int,
                        trust_level: Fraction = DEFAULT_TRUST_LEVEL
                        ) -> PendingSubscribe:
        """Pin a trust root (light/client.py _initialize): fetch the
        client's chosen header, demand its hash, and queue the root
        commit's lanes. Sheds when the farm is at session capacity."""
        if trusting_period_s <= 0:
            raise VerifyRejected("trusting period must be positive")
        if trusted_height <= 0:
            raise VerifyRejected("trusted height must be positive")
        if len(trusted_hash) != 32:
            raise VerifyRejected("trusted hash must be 32 bytes")
        try:
            lb = self.provider.light_block(trusted_height)
        except ProviderError as e:
            raise VerifyRejected(f"provider: {e}") from e
        try:
            lb.validate_basic(self.chain_id)
        except LightBlockError as e:
            raise VerifyRejected(f"invalid root light block: {e}") from e
        if lb.header.hash() != trusted_hash:
            raise VerifyRejected(
                f"provider header hash {lb.header.hash().hex()[:16]} != "
                f"trusted {trusted_hash.hex()[:16]}")
        try:
            root_check = planner.plan_commit_light(
                self.chain_id, lb.validator_set,
                lb.signed_header.commit.block_id, lb.height,
                lb.signed_header.commit, self.cache)
        except CommitVerificationError as e:
            raise VerifyRejected(f"root commit: {e}") from e
        try:
            session = self.sessions.create(self.chain_id,
                                           trusting_period_s, trust_level)
        except SessionLimitExceeded as e:
            raise FarmOverloaded(str(e)) from e
        pending = PendingSubscribe(session, lb)
        try:
            pending.tickets = [self.batcher.submit(root_check)]
        except QueueFull as e:
            self.sessions.drop(session.session_id)
            raise FarmOverloaded(str(e)) from e
        return pending

    def finish_subscribe(self, pending: PendingSubscribe) -> FarmSession:
        self.batcher.wait(pending.tickets)
        bad = next((t.error for t in pending.tickets
                    if t.error is not None), None)
        if bad is not None:
            self.sessions.drop(pending.session.session_id)
            raise VerifyRejected(f"root commit: {bad}")
        pending.session.store.save_light_block(pending.root)
        return pending.session

    def subscribe(self, trusted_height: int, trusted_hash: bytes,
                  trusting_period_s: int,
                  trust_level: Fraction = DEFAULT_TRUST_LEVEL
                  ) -> FarmSession:
        return self.finish_subscribe(self.begin_subscribe(
            trusted_height, trusted_hash, trusting_period_s, trust_level))

    def unsubscribe(self, session_id: str) -> bool:
        return self.sessions.drop(session_id)

    # --- verify -----------------------------------------------------------

    def begin_verify(self, session_id: str, height: int = 0,
                     now: Optional[Timestamp] = None) -> PendingVerify:
        """Plan + enqueue one client's update. height 0 = provider
        tip. Raises UnknownSession / FarmOverloaded / VerifyRejected
        (host-side rules: expiry, ordering, power, bisection budget)."""
        try:
            session = self.sessions.get(session_id)
        except SessionError as e:
            raise UnknownSession(str(e)) from e
        now = now or self._now()
        try:
            target = self.provider.light_block(height)
        except ProviderError as e:
            self._reject(session)
            raise VerifyRejected(f"provider: {e}") from e
        latest = session.latest()
        if latest is None:
            self._reject(session)
            raise VerifyRejected("session has no trust root")
        got = session.store.light_block(target.height)
        if got is not None:
            return PendingVerify(session, target.height, [], [],
                                 cached=got)
        if target.height <= latest.height:
            # the farm serves FORWARD verification; a backwards walk
            # is a per-client hash-link chase with no batchable work —
            # the client keeps its own verified headers for that
            self._reject(session)
            raise VerifyRejected(
                f"height {target.height} <= trusted {latest.height} "
                f"(farm verifies forward only)")
        try:
            target.validate_basic(self.chain_id)
            steps = planner.plan_update(
                self.chain_id, latest, target, self.provider, now,
                session.trusting_period_s, session.trust_level,
                self.cache, max_fetches=self.max_fetches)
        except (verifier.VerificationError, CommitVerificationError,
                LightBlockError, ProviderError) as e:
            self._reject(session)
            raise VerifyRejected(str(e)) from e
        tickets: List[List[CheckTicket]] = []
        queued: List[CheckTicket] = []
        try:
            for step in steps:
                row: List[CheckTicket] = []
                for check in step.checks:
                    # one at a time, recording each ticket BEFORE the
                    # next submit can raise — cancel() below must see
                    # every check this request actually queued
                    row.append(self.batcher.submit(check))
                    queued.append(row[-1])
                tickets.append(row)
        except QueueFull as e:
            # shed the WHOLE request — and WITHDRAW the checks already
            # queued for it: a shed request never reaches wait(), so
            # its orphaned lanes would otherwise hold the bounded
            # queue's budget forever (every later request then sheds
            # against dead weight nothing will ever flush)
            self.batcher.cancel(queued)
            raise FarmOverloaded(str(e)) from e
        return PendingVerify(session, target.height, steps, tickets)

    def finish_verify(self, pending: PendingVerify) -> Dict:
        """Wait for the coalesced verdicts, then commit verified steps
        in order. Returns the accepted-tip summary dict."""
        if pending.cached is not None:
            return self._accept_summary(pending.session, pending.cached,
                                        steps=0)
        flat = [t for row in pending.tickets for t in row]
        self.batcher.wait(flat)
        session = pending.session
        accepted = 0
        for step, row in zip(pending.steps, pending.tickets):
            bad = next((t.error for t in row if t.error is not None),
                       None)
            if bad is not None:
                self._reject(session)
                raise VerifyRejected(
                    f"height {step.lb.height}: {bad}") from bad
            fail_point("farm:commit-session")
            session.store.save_light_block(step.lb)
            session.headers_accepted += 1
            accepted += 1
            self._log_decision(session, step)
        return self._accept_summary(
            session, session.store.light_block(pending.target_height),
            steps=accepted)

    def verify(self, session_id: str, height: int = 0,
               now: Optional[Timestamp] = None) -> Dict:
        return self.finish_verify(self.begin_verify(session_id, height,
                                                    now))

    # --- status -----------------------------------------------------------

    def status(self, session_id: Optional[str] = None) -> Dict:
        if session_id is not None:
            try:
                return self.sessions.get(session_id).status()
            except SessionError as e:
                raise UnknownSession(str(e)) from e
        b = self.batcher
        with self._lock:
            accepted, rejected = self.headers_accepted, \
                self.headers_rejected
        return {
            "sessions": len(self.sessions),
            "max_sessions": self.sessions.max_sessions,
            "headers_accepted": accepted,
            "requests_rejected": rejected,
            "batches": b.batches,
            "last_batch_width": b.last_batch_width,
            "max_batch_width": b.max_batch_width,
            "lanes_by_backend": dict(b.lanes_by_backend),
            "dedup_batch_hits": b.dedup_batch_hits,
            "cache_hit_rate": round(
                self.cache.hit_rate(planner.CACHE_PATH), 4),
            "shed": b.shed,
        }

    # --- internals --------------------------------------------------------

    def _accept_summary(self, session: FarmSession, lb: LightBlock,
                        steps: int) -> Dict:
        return {"session": session.session_id, "height": lb.height,
                "hash": lb.header.hash().hex(),
                "validators_hash": lb.header.validators_hash.hex(),
                "steps": steps}

    def _reject(self, session: FarmSession) -> None:
        session.requests_rejected += 1
        with self._lock:
            self.headers_rejected += 1
        if self.metrics is not None:
            self.metrics.headers_rejected.inc()

    def _log_decision(self, session: FarmSession,
                      step: planner.VerifyStep) -> None:
        record = dict(step.record)
        record["session"] = session.session_id
        with self._lock:
            self.headers_accepted += 1
            self.decision_log.append(record)
            if len(self.decision_log) > self._decision_cap:
                del self.decision_log[:-self._decision_cap or None]
        if self.metrics is not None:
            self.metrics.headers_accepted.inc()

    def drain_decisions(self) -> List[Dict]:
        """Pop the accumulated decision records (the simnet scenario's
        spec-oracle feed)."""
        with self._lock:
            out, self.decision_log = self.decision_log, []
        return out
