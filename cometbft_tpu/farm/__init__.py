"""farm/ — light-client verification farm.

Serves verification *as the product* (ROADMAP item 4): many thin
clients outsource their skipping-verification checks to one service,
which coalesces the pending VerifyCommitLight /
VerifyCommitLightTrusting work across ALL sessions into shared device
batches. The shape is PAPERS.md's verification-outsourcing line — 2G2T
constant-size MSM outsourcing (arXiv 2602.23464) and TS-Verkle's
on-chain verifier (arXiv 2605.08682) both centralize many clients'
checks on one prover/verifier — applied to CometBFT light clients on
the batch-shaped commit-verify kernel PRs 2-3 built.

Pieces:

  session.py   per-client trust state: a LightStore-backed session
               pinned at subscribe time, bounded by a shed limit
  planner.py   each client's bisection schedule (the light/verifier.py
               adjacent / non-adjacent rules) expanded HOST-SIDE into
               signature-lane work items — threshold tallies never
               need the device, so bisection decisions cost no round
               trips
  batcher.py   coalesces pending lanes across every session into one
               shared batch: SigCache + intra-batch dedup, dispatch
               through the DeviceClient.submit() seam with canary
               lanes and supervisor-driven CPU fallback, bounded
               queue with an explicit shed path
  service.py   VerificationFarm: subscribe / verify / status, the
               object rpc/server.py's light_* endpoints call

The spec/LightClient.tla acceptance rules are the oracle: every
accepted header's decision record is checkable by
tools/check_light_spec.check_decisions, and the `light-farm` simnet
scenario does exactly that for hundreds of virtual clients per seed.
"""

from .service import (FarmError, FarmOverloaded, UnknownSession,
                      VerificationFarm, VerifyRejected)
from .session import FarmSession, SessionManager

__all__ = ["VerificationFarm", "FarmError", "FarmOverloaded",
           "UnknownSession", "VerifyRejected", "FarmSession",
           "SessionManager"]
