"""Cross-session lane coalescing: many clients' pending checks become
one shared device batch.

The batcher owns a bounded pending queue of PlannedChecks. A flush
snapshots everything pending, dedups identical (pub, msg, sig) lanes
ACROSS checks — two clients verifying the same header pay for each
signature once — and dispatches the unique lanes through the
`DeviceClient.submit()` seam with the PR-3 protections intact: canary
lanes spliced per batch, a canary mismatch quarantines the device via
the shared supervisor, and transport failures degrade to the native
CPU per-signature path. Without a device server at all, WIDE batches
route through the actual batch kernel when the CompileLedger proves
the shape bucket warm (`_fallback_verify` — ROADMAP item-4 residual);
a cold bucket keeps the per-sig native clamp, because a farm flush
must never pay a multi-minute CPU jit (docs/PERF.md "known compile
hazard"). The chosen backend per batch (device / kernel / cpu) lands
in `FarmMetrics.lanes_verified{backend}`.

Backpressure is explicit: `submit()` raises QueueFull once the pending
queue holds `max_pending_lanes` — the RPC layer turns that into a
retryable shed error instead of letting an open-ended client crowd
queue unbounded work. Verified-TRUE lanes land in the SigCache, so the
NEXT client at a nearby trusted height hits cache instead of lanes.

Flushing is cooperative (no background thread): callers block on their
ticket with a small coalescing window, and whichever caller wakes
first flushes everything pending — concurrent RPC threads coalesce,
while single-threaded drivers (the light-farm simnet scenario, the
bench) submit a whole wave and flush once, deterministically.
"""

from __future__ import annotations

import inspect
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..libs.env import env_bool, env_float, env_int
from ..libs.fail import fail_point
from ..pipeline.cache import SigCache
from ..trace import shared_tracer, trigger_dump
from ..types.validation import ErrWrongSignature
from .planner import Lane, PlannedCheck

ENV_MAX_PENDING_LANES = "COMETBFT_TPU_FARM_MAX_PENDING_LANES"
ENV_COALESCE_WINDOW = "COMETBFT_TPU_FARM_COALESCE_WINDOW"
ENV_ADAPTIVE_WINDOW = "COMETBFT_TPU_FARM_ADAPTIVE_WINDOW"
DEFAULT_MAX_PENDING_LANES = 16_384
DEFAULT_COALESCE_WINDOW_S = 0.002
# a wedged flush must surface, not hang an RPC worker forever; the
# device seam's own deadline (device/client.deadline_for) is far below
FLUSH_WAIT_S = 120.0

# adaptive coalescing: the fixed window splits into this many sub-polls,
# and once PLATEAU_POLLS consecutive polls observe the same pending
# width the waiter flushes early — at low load a lone submitter pays
# window/ADAPTIVE_POLLS*2 instead of the full window, while a still-
# growing batch keeps coalescing up to the fixed ceiling (ROADMAP
# item 4 headroom: the fixed knob stays the ceiling).
ADAPTIVE_POLLS = 4
PLATEAU_POLLS = 2

ED25519 = "ed25519"


def coalesce_wait(ev: threading.Event, window_s: float,
                  width_fn: Callable[[], int], adaptive: bool) -> bool:
    """Wait for `ev` up to the coalescing window; returns True iff the
    event fired (someone else's flush resolved the ticket). With
    `adaptive`, the window is sampled in ADAPTIVE_POLLS sub-polls of
    `width_fn` (the pending queue width): when PLATEAU_POLLS
    consecutive polls see no growth the batch has stopped widening and
    waiting longer only adds tail latency — return early so the caller
    flushes now. Shared by the farm and ingest batchers."""
    if window_s <= 0:
        return ev.is_set()
    if not adaptive:
        return ev.wait(window_s)
    poll = window_s / ADAPTIVE_POLLS
    last, flat = -1, 0
    for _ in range(ADAPTIVE_POLLS):
        if ev.wait(poll):
            return True
        width = width_fn()
        if width == last:
            flat += 1
            if flat >= PLATEAU_POLLS - 1:
                return False  # width plateaued: flush early
        else:
            last, flat = width, 0
    return False


class QueueFull(Exception):
    """The pending queue is at capacity — this request is shed."""


class CheckTicket:
    """Handle for one submitted PlannedCheck; resolved by a flush.
    `ctx` is the submitter's trace context — the explicit propagation
    handle the coalesced flush span links (never a thread-local)."""

    def __init__(self, planned: PlannedCheck, ctx=None):
        self.planned = planned
        self.error: Optional[Exception] = None
        self._ev = threading.Event()
        self.ctx = ctx  # trace.TraceContext or None

    def done(self) -> bool:
        return self._ev.is_set()

    def ok(self) -> bool:
        return self.done() and self.error is None


def _native_verify(lanes: Sequence[Lane]) -> Tuple[List[bool], str]:
    """CPU fallback: per-signature native verify (~50µs/sig via the C
    fast path) — the same clamp blocksync applies on CPU nodes."""
    return [lane.pk.verify_signature(lane.msg, lane.sig)
            for lane in lanes], "cpu"


# a farm flush narrower than this stays per-sig native even when the
# kernel is warm: dispatch + padding overhead beats ~50µs/sig only
# once the batch is wide
FARM_KERNEL_MIN_LANES = 128


def _fallback_verify(lanes: Sequence[Lane]) -> Tuple[List[bool], str]:
    """The no-device-server path, with the ROADMAP item-4 residual
    closed: a WIDE all-ed25519 batch routes through the actual batch
    kernel when the CompileLedger proves the bucket warm — process-
    local warmth always (the jit cache makes the wide kernel the
    cheaper path, same lift as crypto/keys.Ed25519BatchVerifier), or a
    clean on-disk entry on a real device platform (the persistent
    cache reloads the executable). A cold or compiler-fatal bucket
    keeps the per-sig native clamp — a farm flush must never pay a
    multi-minute XLA:CPU jit (docs/PERF.md "known compile hazard").
    The chosen backend lands in FarmMetrics.lanes_verified{backend}
    via the label this returns."""
    n = len(lanes)
    if n >= FARM_KERNEL_MIN_LANES \
            and all(lane.pk.type_() == ED25519 for lane in lanes) \
            and max(len(lane.msg) for lane in lanes) <= 128:
        # the <=128 guard pins the msg-cap kernel variant: the ledger
        # keys (kernel, bucket) without the cap dimension, and the
        # warmed executables (prewarm, earlier flushes) are the
        # cap-128 ones — a longer message would select a DIFFERENT
        # never-compiled variant and pay the multi-minute jit this
        # clamp exists to avoid
        from ..libs.jax_cache import is_device_platform, ledger
        eff = 1 << (n - 1).bit_length()
        lg = ledger()
        warm = lg.warm_in_process("ed25519-rlc", eff) or (
            is_device_platform() and lg.seen("ed25519-rlc", eff))
        if warm and not lg.known_crash("ed25519-rlc", eff):
            from ..ops.ed25519 import verify_batch
            with lg.compile_guard("ed25519-rlc", eff):
                out = verify_batch([lane.pub for lane in lanes],
                                   [lane.msg for lane in lanes],
                                   [lane.sig for lane in lanes],
                                   batch_size=eff)
            return [bool(v) for v in out], "kernel"
    return _native_verify(lanes)


def _mesh_verify(lanes: Sequence[Lane],
                 ctx=None) -> Optional[Tuple[List[bool], str]]:
    """Route a batch through the process-wide MeshExecutor when the
    node owns its mesh in-process (no device server configured but
    [device] mesh is on): the same submit()/future seam the pipeline
    rides, per-shard canaries + CPU re-verify inside the executor —
    verdict safety is the executor's own contract, so no second canary
    splice here. Returns None when no shared executor is serving (the
    caller falls through to the kernel/native ladder); overload and
    transport failures also fall through — the farm must degrade, not
    shed, exactly like a dead device server."""
    from .. import mesh
    if not mesh.mesh_enabled():
        return None
    ex = mesh.shared_executor()
    if ex is None:
        return None
    from ..device.client import deadline_for
    from ..mesh import MeshOverloaded
    pubs = [lane.pub for lane in lanes]
    msgs = [lane.msg for lane in lanes]
    sigs = [lane.sig for lane in lanes]
    try:
        oks = ex.submit(pubs, msgs, sigs,
                        ctx=ctx).result(deadline_for(len(pubs)))
    except (MeshOverloaded, TimeoutError, ConnectionError, OSError):
        return None
    return [bool(v) for v in oks], "mesh"


def device_or_cpu_backend(lanes: Sequence[Lane],
                          ctx=None) -> Tuple[List[bool], str]:
    """Default verify backend: the DeviceClient.submit() seam with
    canary lanes + supervisor gating (the RemoteBatchVerifier contract,
    restated here because the farm attributes device-vs-CPU verdicts
    per batch); without a device server, the shared in-process mesh
    executor when one is serving (lanes_verified{backend="mesh"}); CPU
    per-sig otherwise. `ctx` is the flush span's trace context,
    forwarded through whichever submit seam is taken."""
    from ..device import health
    from ..device.client import DeviceUnprocessable, shared_client
    if any(lane.pk.type_() != ED25519 for lane in lanes):
        return _native_verify(lanes)  # kernels are ed25519-only
    client = shared_client()
    if client is None:
        got = _mesh_verify(lanes, ctx=ctx)
        if got is not None:
            return got
        return _fallback_verify(lanes)
    sup = health.shared_supervisor()
    if not sup.allow_connect():
        return _fallback_verify(lanes)
    pubs = [lane.pub for lane in lanes]
    msgs = [lane.msg for lane in lanes]
    sigs = [lane.sig for lane in lanes]
    canaried = sup.canary
    if canaried:
        pubs, msgs, sigs = health.splice_canaries(pubs, msgs, sigs)
    try:
        _ok, oks = client.submit(pubs, msgs, sigs, ctx=ctx).result()
    except DeviceUnprocessable:
        return _native_verify(lanes)
    except (TimeoutError, ConnectionError, OSError) as e:
        sup.report_trip(e)
        return _native_verify(lanes)
    if canaried:
        ok, oks = health.check_canaries(oks, len(lanes))
        if not ok:
            sup.report_corruption("farm batch canary mismatch")
            return _native_verify(lanes)
        sup.report_success()
        return [bool(v) for v in oks], "device"
    sup.report_success()
    # the operator turned canary splicing OFF (COMETBFT_TPU_DEVICE_CANARY=0
    # / [device] canary=false): verdicts are deliberately trusted un-gated
    # in that configuration — the explicit, reviewed opt-out
    # staticcheck: allow(verdict-taint)
    return [bool(v) for v in oks], "device"


class FarmBatcher:
    """Bounded, coalescing, deduplicating verify queue."""

    # guarded-by: _lock: _tickets, _pending_lanes, shed
    # guarded-by: _lock: _shed_burst_open
    # guarded-by: _flush_lock: batches, dedup_batch_hits, lanes_by_backend
    # guarded-by: _flush_lock: last_batch_width, max_batch_width
    # (flow-aware: _run_batch only runs from flush() under _flush_lock,
    # so the batch stats it mutates are serialized by that lock)

    def __init__(self, cache: Optional[SigCache] = None,
                 max_pending_lanes: Optional[int] = None,
                 coalesce_window_s: Optional[float] = None,
                 verify_backend: Optional[Callable] = None,
                 metrics=None, adaptive: Optional[bool] = None):
        if max_pending_lanes is None:
            max_pending_lanes = env_int(ENV_MAX_PENDING_LANES,
                                        DEFAULT_MAX_PENDING_LANES,
                                        minimum=1)
        if coalesce_window_s is None:
            coalesce_window_s = env_float(ENV_COALESCE_WINDOW,
                                          DEFAULT_COALESCE_WINDOW_S,
                                          minimum=0.0)
        if adaptive is None:
            adaptive = env_bool(ENV_ADAPTIVE_WINDOW, True)
        self.max_pending_lanes = max_pending_lanes
        self.coalesce_window_s = coalesce_window_s
        self.adaptive = adaptive
        self.cache = cache if cache is not None else SigCache(0)
        self.metrics = metrics  # libs/metrics_gen.FarmMetrics or None
        self._backend = verify_backend or device_or_cpu_backend
        # ctx propagation is opt-in per backend (injected test/sim
        # backends keep the plain (lanes) signature) — decided once
        self._backend_takes_ctx = (
            "ctx" in inspect.signature(self._backend).parameters)
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._tickets: List[CheckTicket] = []
        self._pending_lanes = 0
        # stats (monotonic counters; light_status surfaces them)
        self.batches = 0
        self.lanes_by_backend: Dict[str, int] = {}
        self.dedup_batch_hits = 0
        self.shed = 0
        self.last_batch_width = 0
        self.max_batch_width = 0
        # shed storms dump the flight recorder once per burst (ingest
        # discipline): opens at the first shed, closes on a flush
        self._shed_burst_open = False

    # --- intake -----------------------------------------------------------

    def submit(self, planned: PlannedCheck, ctx=None) -> CheckTicket:
        """Queue one check; QueueFull once the lane budget is spent.
        A check with no pending lanes (all cache hits) resolves
        immediately — the dedup fast path costs no queue space. `ctx`
        is the submitter's trace context; it rides the ticket so the
        coalesced flush span can link back to the request."""
        ticket = CheckTicket(planned, ctx=ctx)
        if not planned.lanes:
            ticket._ev.set()
            return ticket
        with self._lock:
            if self._pending_lanes + len(planned.lanes) \
                    > self.max_pending_lanes:
                self.shed += 1
                if self.metrics is not None:
                    self.metrics.shed.inc()
                if not self._shed_burst_open:
                    self._shed_burst_open = True
                    trigger_dump(
                        "shed-burst", f"farm:{self.shed}",
                        f"lane budget {self.max_pending_lanes} spent")
                raise QueueFull(
                    f"farm verify queue full "
                    f"({self._pending_lanes} lanes pending)")
            self._tickets.append(ticket)
            self._pending_lanes += len(planned.lanes)
        return ticket

    def cancel(self, tickets: Sequence[CheckTicket]) -> None:
        """Withdraw not-yet-flushed tickets. A request that sheds
        mid-plan MUST release the lane budget its earlier checks
        claimed: nothing on the RPC path flushes a shed request's
        orphans, so without this the bounded queue fills with dead
        lanes and the farm sheds every later request while idle."""
        with self._lock:
            for ticket in tickets:
                try:
                    self._tickets.remove(ticket)
                except ValueError:
                    continue  # already snapshotted by a flush
                self._pending_lanes -= len(ticket.planned.lanes)

    def wait(self, tickets: Sequence[CheckTicket]) -> None:
        """Block until every ticket resolves, coalescing with other
        submitters: wait up to one window for someone else's flush
        (adaptively cut short once the pending width plateaus —
        coalesce_wait), then flush whatever is pending ourselves."""
        for ticket in tickets:
            if coalesce_wait(ticket._ev, self.coalesce_window_s,
                             self._pending_width, self.adaptive):
                continue
            self.flush()
            if not ticket._ev.wait(FLUSH_WAIT_S):
                raise RuntimeError("farm flush did not resolve ticket")

    def _pending_width(self) -> int:
        with self._lock:
            return self._pending_lanes

    # --- the shared batch -------------------------------------------------

    def flush(self) -> int:
        """Verify everything pending in ONE coalesced batch; returns
        the unique-lane width dispatched. Serialized: a concurrent
        flush waits, then sees an empty queue and returns 0."""
        with self._flush_lock:
            with self._lock:
                tickets, self._tickets = self._tickets, []
                self._pending_lanes = 0
                self._shed_burst_open = False  # storm (if any) is over
            if not tickets:
                return 0
            fail_point("farm:flush")
            try:
                return self._run_batch(tickets)
            except Exception as e:  # noqa: BLE001 — a backend bug must
                # fail the waiting RPC threads, never strand them
                for ticket in tickets:
                    ticket.error = e
                    ticket._ev.set()
                raise

    def _run_batch(self, tickets: List[CheckTicket]) -> int:
        # intra-batch dedup: one device lane per unique signature, with
        # every (ticket, lane) that needs its verdict fanned back out
        unique: List[Lane] = []
        index: Dict[bytes, int] = {}
        owners: List[List[Tuple[CheckTicket, Lane]]] = []
        for ticket in tickets:
            for lane in ticket.planned.lanes:
                key = self.cache.key(lane.pub, lane.msg, lane.sig)
                at = index.get(key)
                if at is None:
                    index[key] = len(unique)
                    unique.append(lane)
                    owners.append([(ticket, lane)])
                else:
                    self.dedup_batch_hits += 1
                    if self.metrics is not None:
                        self.metrics.dedup_hits.inc(kind="batch")
                    owners[at].append((ticket, lane))
        # coalescing seam: one flush serves many submitters — a root
        # span linking each ticket's submit-side context
        tracer = shared_tracer()
        with tracer.start("farm.flush", tickets=len(tickets),
                          lanes=len(unique)) as span:
            if tracer.enabled:
                for ticket in tickets:
                    span.link(ticket.ctx)
            if self._backend_takes_ctx:
                oks, backend = self._backend(unique, ctx=span)
            else:
                oks, backend = self._backend(unique)
            span.set_attr("backend", backend)
        if len(oks) != len(unique):
            raise RuntimeError(
                f"verify backend answered {len(oks)} lanes "
                f"for {len(unique)}")
        self.batches += 1
        self.last_batch_width = len(unique)
        self.max_batch_width = max(self.max_batch_width, len(unique))
        self.lanes_by_backend[backend] = (
            self.lanes_by_backend.get(backend, 0) + len(unique))
        if self.metrics is not None:
            self.metrics.batches.inc()
            self.metrics.batch_width.set(len(unique))
            self.metrics.lanes.inc(len(unique), backend=backend)
        failures: Dict[int, int] = {}  # ticket id -> first bad sig idx
        for at, ok in enumerate(oks):
            lane = unique[at]
            if ok:
                self.cache.add(lane.pub, lane.msg, lane.sig)
                continue
            for ticket, owner_lane in owners[at]:
                failures.setdefault(id(ticket), owner_lane.sig_index)
        for ticket in tickets:
            bad = failures.get(id(ticket))
            if bad is not None:
                ticket.error = ErrWrongSignature(
                    bad, ticket.planned.commit.signatures[bad].signature)
            ticket._ev.set()
        return len(unique)
