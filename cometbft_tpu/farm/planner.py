"""Bisection planning: expand one client's update into signature-lane
work items, entirely host-side.

The enabling observation: both light-client threshold rules are pure
functions of ADDRESSES and voting power — `verify_commit_light_trusting`
tallies the power of trusted-set members who signed, and
`verify_commit_light` tallies claimed-set power — so whether a skipping
jump CAN be trusted (the bisection decision, light/client.py
`_verify_skipping`'s ErrNewValSetCantBeTrusted branch) is decided before
any signature is cryptographically verified. types/validation.py's own
batch path works the same way: it tallies optimistically while ADDING
lanes to the batch verifier, early-exits the scan at the threshold, and
only then verifies the added lanes (a false lane fails the whole check
afterwards). The planner mirrors that exact semantics, which is what
makes farm verdicts equal to LightClient verdicts lane for lane.

So a whole bisection schedule — every pivot, every threshold decision —
costs only provider fetches and hashing; the signature lanes it emits
are verified LATER, coalesced with every other session's lanes in one
shared device batch (batcher.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..light import verifier
from ..light.types import LightBlock
from ..pipeline.cache import SigCache
from ..types.block import Commit
from ..types.proto import Timestamp
from ..types.validation import (CommitVerificationError,
                                DEFAULT_TRUST_LEVEL,
                                ErrNotEnoughVotingPowerSigned, Fraction)
from ..types.validator import ValidatorSet

CACHE_PATH = "farm"  # SigCache attribution label for farm lanes


class PlanBudgetExceeded(verifier.VerificationError):
    """The bisection needed more provider fetches than the farm's
    per-request budget allows — a byzantine target (or a pathological
    valset-rotation chain) must not let one client pin the service."""


@dataclass
class Lane:
    """One pending signature verification: a device batch lane."""
    pub: bytes          # raw pubkey bytes (device wire form)
    msg: bytes          # canonical vote sign-bytes
    sig: bytes
    pk: object          # crypto PubKey (CPU-fallback verify)
    sig_index: int      # index into the commit's signature list


@dataclass
class PlannedCheck:
    """One VerifyCommitLight / VerifyCommitLightTrusting whose
    threshold already passed host-side; `lanes` await verification."""
    kind: str                     # "light" | "trusting"
    commit: Commit
    lanes: List[Lane] = field(default_factory=list)
    tallied: int = 0              # power tallied at early-exit
    total: int = 0                # total power of the tallying set
    needed: int = 0               # strict floor (accept iff tallied >)
    cache_hits: int = 0           # lanes skipped via SigCache


def plan_commit_light(chain_id: str, vals: ValidatorSet, block_id,
                      height: int, commit: Commit,
                      cache: SigCache) -> PlannedCheck:
    """Lane plan for types/validation.verify_commit_light (+2/3 of the
    header's OWN claimed set, early-exit at the threshold). Raises the
    same structural/power errors; signature verdicts come later."""
    _basic(vals, commit, height, block_id)
    total = vals.total_voting_power()
    needed = total * 2 // 3
    planned = PlannedCheck("light", commit, total=total, needed=needed)
    for idx, cs in enumerate(commit.signatures):
        if not cs.for_block():
            continue
        _validate_sig(cs, idx)
        val = vals.get_by_index(idx)
        _add_lane(planned, chain_id, commit, idx, val, cs, cache)
        planned.tallied += val.voting_power
        if planned.tallied > needed:
            break
    if planned.tallied <= needed:
        raise ErrNotEnoughVotingPowerSigned(planned.tallied, needed)
    return planned


def plan_commit_trusting(chain_id: str, vals: ValidatorSet,
                         commit: Commit, trust_level: Fraction,
                         cache: SigCache) -> PlannedCheck:
    """Lane plan for verify_commit_light_trusting (trust_level of the
    TRUSTED set, matched by address, double votes rejected)."""
    if vals is None:
        raise CommitVerificationError("nil validator set")
    if commit is None:
        raise CommitVerificationError("nil commit")
    if trust_level.denominator == 0:
        raise CommitVerificationError("trustLevel has zero denominator")
    total = vals.total_voting_power()
    needed = (total * trust_level.numerator) // trust_level.denominator
    planned = PlannedCheck("trusting", commit, total=total, needed=needed)
    seen: Dict[int, int] = {}
    for idx, cs in enumerate(commit.signatures):
        if not cs.for_block():
            continue
        _validate_sig(cs, idx)
        val_idx, val = vals.get_by_address(cs.validator_address)
        if val is None:
            continue  # signer outside the trusted set: no vouching power
        if val_idx in seen:
            raise CommitVerificationError(
                f"double vote from validator {val_idx} "
                f"({seen[val_idx]} and {idx})")
        seen[val_idx] = idx
        _add_lane(planned, chain_id, commit, idx, val, cs, cache)
        planned.tallied += val.voting_power
        if planned.tallied > needed:
            break
    if planned.tallied <= needed:
        raise ErrNotEnoughVotingPowerSigned(planned.tallied, needed)
    return planned


def _basic(vals: ValidatorSet, commit: Commit, height: int,
           block_id) -> None:
    """types/validation._verify_basic, restated (it is private there)."""
    if vals is None:
        raise CommitVerificationError("nil validator set")
    if commit is None:
        raise CommitVerificationError("nil commit")
    if len(vals) != len(commit.signatures):
        raise CommitVerificationError(
            f"validator set size {len(vals)} != "
            f"{len(commit.signatures)} sigs")
    if height != commit.height:
        raise CommitVerificationError(
            f"invalid commit height: want {height}, got {commit.height}")
    if block_id != commit.block_id:
        raise CommitVerificationError("invalid commit -- wrong block ID")


def _validate_sig(cs, idx: int) -> None:
    try:
        cs.validate_basic()
    except ValueError as e:
        raise CommitVerificationError(
            f"invalid signature at index {idx}: {e}") from e


def _add_lane(planned: PlannedCheck, chain_id: str, commit: Commit,
              idx: int, val, cs, cache: SigCache) -> None:
    msg = commit.vote_sign_bytes(chain_id, idx)
    pkb = val.pub_key.bytes_()
    if cache.seen(pkb, msg, cs.signature, path=CACHE_PATH):
        planned.cache_hits += 1  # previously verified TRUE: no lane
        return
    planned.lanes.append(Lane(pkb, msg, cs.signature, val.pub_key, idx))


# --- the per-client schedule --------------------------------------------------


@dataclass
class VerifyStep:
    """One header acceptance: the checks must ALL verify for `lb` to
    become trusted; `record` is the decision in the vocabulary
    tools/check_light_spec.check_decisions validates."""
    lb: LightBlock
    adjacent: bool
    checks: List[PlannedCheck]
    record: Dict


def plan_update(chain_id: str, trusted: LightBlock, target: LightBlock,
                provider, now: Timestamp, trusting_period_s: int,
                trust_level: Fraction = DEFAULT_TRUST_LEVEL,
                cache: Optional[SigCache] = None,
                max_fetches: int = 128,
                max_drift_s: int = verifier.MAX_CLOCK_DRIFT_SECONDS
                ) -> List[VerifyStep]:
    """The light/client.py `_verify_skipping` loop with verification
    deferred: returns the ordered steps (pivot chain) whose checks the
    batcher verifies in shared batches. Raises the verifier/validation
    errors for every host-side rejection (expiry, time/height ordering,
    valset-hash binding, insufficient power, bisection stall)."""
    cache = cache if cache is not None else SigCache(0)  # 0 = disabled
    steps: List[VerifyStep] = []
    cur = trusted
    pivots = [target]
    fetches = 0
    while pivots:
        candidate = pivots[-1]
        adjacent = candidate.height == cur.height + 1
        if verifier._expired(cur, trusting_period_s, now):
            raise verifier.ErrOldHeader("trusted header expired")
        verifier._validate_untrusted(chain_id, cur, candidate, now,
                                     max_drift_s)
        trusting: Optional[PlannedCheck] = None
        if adjacent:
            if candidate.header.validators_hash != \
                    cur.header.next_validators_hash:
                raise verifier.ErrInvalidHeader(
                    "untrusted validators_hash != trusted "
                    "next_validators_hash")
        else:
            try:
                trusting = plan_commit_trusting(
                    chain_id, cur.validator_set,
                    candidate.signed_header.commit, trust_level, cache)
            except ErrNotEnoughVotingPowerSigned:
                # the trusted set cannot vouch: bisect toward it
                # (light/client.py:180-188)
                mid = (cur.height + candidate.height) // 2
                if mid in (cur.height, candidate.height):
                    raise verifier.ErrInvalidHeader(
                        "bisection cannot make progress")
                if fetches >= max_fetches:
                    raise PlanBudgetExceeded(
                        f"bisection exceeded {max_fetches} fetches")
                fetches += 1
                lb = provider.light_block(mid)
                lb.validate_basic(chain_id)
                pivots.append(lb)
                continue
        own = plan_commit_light(
            chain_id, candidate.validator_set,
            candidate.signed_header.commit.block_id, candidate.height,
            candidate.signed_header.commit, cache)
        checks = [own] if trusting is None else [trusting, own]
        steps.append(VerifyStep(candidate, adjacent, checks, _record(
            cur, candidate, adjacent, trusting, own, trust_level)))
        cur = candidate
        pivots.pop()
    return steps


def _record(cur: LightBlock, candidate: LightBlock, adjacent: bool,
            trusting: Optional[PlannedCheck], own: PlannedCheck,
            trust_level: Fraction) -> Dict:
    """Decision record — the farm's acceptance restated as the power
    tallies tools/check_light_spec.check_decisions re-judges."""
    return {
        "height": candidate.height,
        "from_height": cur.height,
        "adjacent": adjacent,
        "valhash_bound": adjacent,  # checked above for adjacent steps
        "own_signed": own.tallied,
        "own_total": own.total,
        "trusted_signed": trusting.tallied if trusting else 0,
        "trusted_total": trusting.total if trusting else 0,
        "trust_num": trust_level.numerator,
        "trust_den": trust_level.denominator,
        "hash": candidate.header.hash().hex(),
    }
