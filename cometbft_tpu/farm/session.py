"""Per-client trust state for the verification farm.

A session is what a light client would keep locally if it verified for
itself: a trust root pinned by (height, hash) at subscribe time, a
trusting period, and the store of headers verified so far. The farm
holds one per subscribed client so repeat `light_verify` calls resume
from the client's own latest trusted header, exactly like
light/client.py resumes from its LightStore.

Sessions are bounded: `max_sessions` is the farm's first backpressure
surface (the second is the batcher's pending-lane queue). A subscribe
over the limit is SHED — rejected immediately with FarmOverloaded —
rather than queued, so an open-ended crowd of clients degrades into
explicit rejections instead of unbounded memory growth.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..db.kv import MemDB
from ..libs.env import env_int
from ..light.store import LightStore
from ..light.types import LightBlock
from ..types.validation import DEFAULT_TRUST_LEVEL, Fraction

ENV_MAX_SESSIONS = "COMETBFT_TPU_FARM_MAX_SESSIONS"
DEFAULT_MAX_SESSIONS = 10_000


class SessionError(Exception):
    pass


class SessionLimitExceeded(SessionError):
    """max_sessions reached — the subscribe was shed."""


@dataclass
class FarmSession:
    """One client's trust state (the farm-side LightClient residue)."""
    session_id: str
    chain_id: str
    trusting_period_s: int
    trust_level: Fraction = DEFAULT_TRUST_LEVEL
    store: LightStore = field(default_factory=lambda: LightStore(MemDB()))
    headers_accepted: int = 0
    requests_rejected: int = 0

    def latest(self) -> Optional[LightBlock]:
        return self.store.latest()

    def status(self) -> Dict:
        latest = self.latest()
        return {
            "session": self.session_id,
            "trusting_period": self.trusting_period_s,
            "latest_height": latest.height if latest else 0,
            "latest_hash": (latest.header.hash().hex()
                            if latest else ""),
            "headers_accepted": self.headers_accepted,
            "requests_rejected": self.requests_rejected,
        }


class SessionManager:
    """Bounded registry of live sessions. Thread-safe: RPC worker
    threads subscribe/drop concurrently while verify calls read."""

    # guarded-by: _lock: _sessions, _next_id
    # (tools/staticcheck guarded-by rule enforces the annotation)

    def __init__(self, max_sessions: Optional[int] = None, metrics=None):
        if max_sessions is None:
            max_sessions = env_int(ENV_MAX_SESSIONS,
                                   DEFAULT_MAX_SESSIONS, minimum=1)
        self.max_sessions = max_sessions
        self.metrics = metrics  # libs/metrics_gen.FarmMetrics or None
        self._lock = threading.Lock()
        self._sessions: Dict[str, FarmSession] = {}
        self._next_id = 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    def create(self, chain_id: str, trusting_period_s: int,
               trust_level: Fraction = DEFAULT_TRUST_LEVEL) -> FarmSession:
        """New session, or SessionLimitExceeded when the farm is full.
        Ids are a plain process-local counter — deterministic for the
        simnet scenario and meaningless to forge (a session holds no
        authority; it only names a trust root the CLIENT chose)."""
        with self._lock:
            if len(self._sessions) >= self.max_sessions:
                if self.metrics is not None:
                    self.metrics.shed.inc()
                raise SessionLimitExceeded(
                    f"farm at capacity ({self.max_sessions} sessions)")
            sid = f"s{self._next_id}"
            self._next_id += 1
            session = FarmSession(sid, chain_id, trusting_period_s,
                                  trust_level)
            self._sessions[sid] = session
        self._emit_gauge()
        return session

    def get(self, session_id: str) -> FarmSession:
        with self._lock:
            session = self._sessions.get(session_id)
        if session is None:
            raise SessionError(f"unknown session {session_id!r}")
        return session

    def drop(self, session_id: str) -> bool:
        with self._lock:
            gone = self._sessions.pop(session_id, None)
        self._emit_gauge()
        return gone is not None

    def all_sessions(self) -> Dict[str, FarmSession]:
        with self._lock:
            return dict(self._sessions)

    def _emit_gauge(self) -> None:
        if self.metrics is not None:
            self.metrics.sessions.set(len(self))
