"""In-process key-value example application (reference
abci/example/kvstore/kvstore.go) — the standard fake backend for engine
tests and benchmarks.

Tx formats:
  "key=value"                   store a pair
  "val:<pubkey_hex>!<power>"    validator power update
"""

from __future__ import annotations

import hashlib
import json
from typing import List

from .application import (
    BaseApplication, CheckTxResult, ExecTxResult, RequestFinalizeBlock,
    ResponseCommit, ResponseFinalizeBlock, ResponseInfo, Snapshot,
    ValidatorUpdate, CODE_TYPE_OK,
)

CODE_TYPE_INVALID_FORMAT = 1

VALIDATOR_PREFIX = b"val:"


class KVStoreApplication(BaseApplication):
    def __init__(self):
        self.state: dict = {}
        self.pending_updates: List[ValidatorUpdate] = []
        self.last_height = 0
        self.last_app_hash = b""
        self.staged: dict | None = None

    # --- helpers -------------------------------------------------------------

    def _compute_app_hash(self, state: dict, height: int) -> bytes:
        blob = json.dumps(
            {k: state[k] for k in sorted(state)}, separators=(",", ":"),
        ).encode() + height.to_bytes(8, "big")
        return hashlib.sha256(blob).digest()

    @staticmethod
    def is_validator_tx(tx: bytes) -> bool:
        return tx.startswith(VALIDATOR_PREFIX)

    # --- mempool -------------------------------------------------------------

    def check_tx(self, tx: bytes) -> CheckTxResult:
        if self.is_validator_tx(tx):
            try:
                self._parse_validator_tx(tx)
                return CheckTxResult(code=CODE_TYPE_OK, gas_wanted=1)
            except ValueError as e:
                return CheckTxResult(code=CODE_TYPE_INVALID_FORMAT,
                                     log=str(e))
        if b"=" not in tx:
            return CheckTxResult(code=CODE_TYPE_INVALID_FORMAT,
                                 log="tx must be key=value")
        return CheckTxResult(code=CODE_TYPE_OK, gas_wanted=1)

    def _parse_validator_tx(self, tx: bytes) -> ValidatorUpdate:
        body = tx[len(VALIDATOR_PREFIX):].decode()
        if "!" not in body:
            raise ValueError("val tx must be val:<pubkey_hex>!<power>")
        pk_hex, power_s = body.split("!", 1)
        pk = bytes.fromhex(pk_hex)
        if len(pk) != 32:
            raise ValueError("pubkey must be 32 bytes")
        return ValidatorUpdate("ed25519", pk, int(power_s))

    # --- consensus -----------------------------------------------------------

    def init_chain(self, chain_id, initial_height, validators,
                   app_state_bytes):
        if app_state_bytes:
            self.state = json.loads(app_state_bytes)
        return [], self._compute_app_hash(self.state, 0)

    def info(self) -> ResponseInfo:
        return ResponseInfo(data="kvstore-tpu", version="1",
                            last_block_height=self.last_height,
                            last_block_app_hash=self.last_app_hash)

    def process_proposal(self, txs, height) -> bool:
        return all(self.check_tx(tx).code == CODE_TYPE_OK for tx in txs)

    def finalize_block(self, req: RequestFinalizeBlock
                       ) -> ResponseFinalizeBlock:
        state = dict(self.state)
        results, updates = [], []
        for tx in req.txs:
            if self.is_validator_tx(tx):
                try:
                    upd = self._parse_validator_tx(tx)
                except ValueError as e:
                    results.append(ExecTxResult(
                        code=CODE_TYPE_INVALID_FORMAT, log=str(e)))
                    continue
                updates.append(upd)
                results.append(ExecTxResult(data=tx))
            elif b"=" in tx:
                k, v = tx.split(b"=", 1)
                state[k.decode(errors="replace")] = v.decode(errors="replace")
                results.append(ExecTxResult(data=tx))
            else:
                results.append(ExecTxResult(code=CODE_TYPE_INVALID_FORMAT,
                                            log="tx must be key=value"))
        app_hash = self._compute_app_hash(state, req.height)
        self.staged = state
        self.last_height = req.height
        self.last_app_hash = app_hash
        self.pending_updates = updates
        return ResponseFinalizeBlock(tx_results=results,
                                     validator_updates=updates,
                                     app_hash=app_hash)

    def commit(self) -> ResponseCommit:
        if self.staged is not None:
            self.state = self.staged
            self.staged = None
        return ResponseCommit(retain_height=0)

    def query(self, path: str, data: bytes) -> tuple[int, bytes]:
        if path == "/store" or path == "":
            v = self.state.get(data.decode(errors="replace"))
            return CODE_TYPE_OK, (v.encode() if v is not None else b"")
        return 1, b"unknown path"

    # --- statesync snapshots (reference kvstore.go snapshot support) ---------

    SNAPSHOT_CHUNK_SIZE = 1 << 16

    def _snapshot_blob(self) -> bytes:
        return json.dumps({"state": {k: self.state[k]
                                     for k in sorted(self.state)},
                           "height": self.last_height},
                          separators=(",", ":")).encode()

    def list_snapshots(self) -> List[Snapshot]:
        """One snapshot of the current committed state, with its blob
        CAPTURED at advertise time — chunks must stay byte-stable while
        later blocks commit, or the restorer's hash check fails (the
        reference snapshots to disk on an interval for the same reason).
        """
        if self.last_height == 0:
            return []
        blob = self._snapshot_blob()
        if not hasattr(self, "_snapshot_blobs"):
            self._snapshot_blobs = {}
        self._snapshot_blobs[self.last_height] = blob
        n = max(1, (len(blob) + self.SNAPSHOT_CHUNK_SIZE - 1)
                // self.SNAPSHOT_CHUNK_SIZE)
        return [Snapshot(height=self.last_height, format=1, chunks=n,
                         hash=hashlib.sha256(blob).digest())]

    def load_snapshot_chunk(self, height: int, format_: int,
                            chunk: int) -> bytes:
        blob = getattr(self, "_snapshot_blobs", {}).get(height)
        if blob is None:
            return b""  # unknown snapshot: restorer will RETRY elsewhere
        lo = chunk * self.SNAPSHOT_CHUNK_SIZE
        return blob[lo:lo + self.SNAPSHOT_CHUNK_SIZE]

    def offer_snapshot(self, snapshot: Snapshot, app_hash: bytes) -> str:
        if snapshot.format != 1 or snapshot.chunks < 1:
            return "REJECT_FORMAT"
        self._restore = {"snapshot": snapshot, "chunks": [],
                         "app_hash": app_hash}
        return "ACCEPT"

    def apply_snapshot_chunk(self, index: int, chunk: bytes,
                             sender: str) -> str:
        r = getattr(self, "_restore", None)
        if r is None:
            return "ABORT"
        # position by index: sources may re-deliver or reorder chunks
        # (the reference chunk queue slots by index the same way)
        if index < len(r["chunks"]):
            return "ACCEPT"  # duplicate: already have it
        if index > len(r["chunks"]):
            return "RETRY_SNAPSHOT"  # gap: restart this snapshot
        r["chunks"].append(chunk)
        if len(r["chunks"]) < r["snapshot"].chunks:
            return "ACCEPT"
        blob = b"".join(r["chunks"])
        if hashlib.sha256(blob).digest() != r["snapshot"].hash:
            self._restore = None
            return "RETRY_SNAPSHOT"
        d = json.loads(blob)
        state, height = d["state"], d["height"]
        if self._compute_app_hash(state, height) != r["app_hash"]:
            # light-client-verified app hash disagrees: poisoned snapshot
            self._restore = None
            return "REJECT_SNAPSHOT"
        self.state = state
        self.last_height = height
        self.last_app_hash = r["app_hash"]
        self._restore = None
        return "COMPLETE"
