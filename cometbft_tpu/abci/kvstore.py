"""In-process key-value example application (reference
abci/example/kvstore/kvstore.go) — the standard fake backend for engine
tests and benchmarks.

Tx formats:
  "key=value"                   store a pair
  "val:<pubkey_hex>!<power>"    validator power update
"""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional, Tuple

from ..crypto import merkle
from .application import (
    BaseApplication, CheckTxResult, ExecTxResult, RequestFinalizeBlock,
    ResponseCommit, ResponseFinalizeBlock, ResponseInfo, Snapshot,
    ValidatorUpdate, CODE_TYPE_OK,
)

CODE_TYPE_INVALID_FORMAT = 1

VALIDATOR_PREFIX = b"val:"


class KVStoreApplication(BaseApplication):
    def __init__(self):
        self.state: dict = {}
        self.pending_updates: List[ValidatorUpdate] = []
        self.last_height = 0
        self.last_app_hash = b""
        self.staged: dict | None = None
        # previous committed snapshot: the newest state whose app hash
        # already appears in a STORED header (state at H-1 hashes into
        # header H; the tip state's hash only lands in header H+1) —
        # what provable queries are answered from. One attribute so a
        # reader on the RPC thread can't tear (state, height) apart
        # while commit() swaps them on the consensus thread.
        self._prev: tuple | None = None
        # height -> captured snapshot blob; replaced wholesale (never
        # mutated in place) so snapshot-connection readers see a
        # consistent dict without locks
        self._snapshot_blobs: dict = {}

    # --- helpers -------------------------------------------------------------

    @staticmethod
    def kv_leaf(key: bytes, value: bytes) -> bytes:
        """Injective leaf encoding: tag byte + length-prefixed key.
        (A `key || 0x00 || value` form would be forgeable — a key
        containing 0x00 lets a lying primary prove a different split of
        the same bytes as some other pair.)"""
        return b"\x01" + len(key).to_bytes(4, "big") + key + value

    @classmethod
    def _state_leaves(cls, state: dict, height: int) -> List[bytes]:
        """Leaf 0 (tag 0x00) commits the height; then one kv_leaf per
        sorted entry. The merkle root IS the app hash, so any key's
        presence (and value) is provable against a light-verified header
        — what the light RPC proxy's verified `abci_query` checks
        (reference light/rpc/client.go ABCIQueryWithOptions + proof ops;
        provable state is the app's contract there too)."""
        leaves = [b"\x00" + height.to_bytes(8, "big")]
        leaves.extend(cls.kv_leaf(k.encode(), state[k].encode())
                      for k in sorted(state))
        return leaves

    def _compute_app_hash(self, state: dict, height: int) -> bytes:
        return merkle.hash_from_byte_slices(
            self._state_leaves(state, height))

    @staticmethod
    def is_validator_tx(tx: bytes) -> bool:
        return tx.startswith(VALIDATOR_PREFIX)

    # --- mempool -------------------------------------------------------------

    @staticmethod
    def _unwrap(tx: bytes) -> bytes:
        """App-visible payload: signed-envelope txs (ingest/tx.py) shed
        their authentication header — the envelope is admission-layer
        concern, the app's tx grammar is unchanged. A malformed
        envelope surfaces as an invalid-format payload (the ingest
        pipeline rejects those before the app when enabled)."""
        from ..ingest.tx import MalformedTx, unwrap_payload
        try:
            return unwrap_payload(tx)
        except MalformedTx:
            return tx

    def check_tx(self, tx: bytes) -> CheckTxResult:
        tx = self._unwrap(tx)
        if self.is_validator_tx(tx):
            try:
                self._parse_validator_tx(tx)
                return CheckTxResult(code=CODE_TYPE_OK, gas_wanted=1)
            except ValueError as e:
                return CheckTxResult(code=CODE_TYPE_INVALID_FORMAT,
                                     log=str(e))
        if b"=" not in tx:
            return CheckTxResult(code=CODE_TYPE_INVALID_FORMAT,
                                 log="tx must be key=value")
        return CheckTxResult(code=CODE_TYPE_OK, gas_wanted=1)

    def _parse_validator_tx(self, tx: bytes) -> ValidatorUpdate:
        body = tx[len(VALIDATOR_PREFIX):].decode()
        if "!" not in body:
            raise ValueError(
                "val tx must be val:<pubkey_hex>!<power>[!<pop_hex>]")
        pk_hex, rest = body.split("!", 1)
        pop = b""
        if "!" in rest:
            power_s, pop_hex = rest.split("!", 1)
            pop = bytes.fromhex(pop_hex)
        else:
            power_s = rest
        pk = bytes.fromhex(pk_hex)
        if len(pk) == 32:
            if pop:
                raise ValueError("ed25519 keys take no proof of possession")
            return ValidatorUpdate("ed25519", pk, int(power_s))
        if len(pk) == 48:
            # compressed-G1 bls12_381 pubkey: a mid-chain BLS admission
            # MUST ship its PoP or aggregation is rogue-key-unsound
            # (genesis keys are admitted via GenesisDoc.bls_pops)
            if len(pop) != 96:
                raise ValueError(
                    "bls12_381 validator tx needs a 96-byte proof of "
                    "possession: val:<pk_hex>!<power>!<pop_hex>")
            return ValidatorUpdate("bls12_381", pk, int(power_s), pop)
        raise ValueError("pubkey must be 32 (ed25519) or 48 (bls) bytes")

    # --- consensus -----------------------------------------------------------

    def init_chain(self, chain_id, initial_height, validators,
                   app_state_bytes):
        if app_state_bytes:
            self.state = json.loads(app_state_bytes)
        return [], self._compute_app_hash(self.state, 0)

    def info(self) -> ResponseInfo:
        return ResponseInfo(data="kvstore-tpu", version="1",
                            last_block_height=self.last_height,
                            last_block_app_hash=self.last_app_hash)

    def process_proposal(self, txs, height) -> bool:
        return all(self.check_tx(tx).code == CODE_TYPE_OK for tx in txs)

    def finalize_block(self, req: RequestFinalizeBlock
                       ) -> ResponseFinalizeBlock:
        state = dict(self.state)
        results, updates = [], []
        for tx in req.txs:
            tx = self._unwrap(tx)
            if self.is_validator_tx(tx):
                try:
                    upd = self._parse_validator_tx(tx)
                except ValueError as e:
                    results.append(ExecTxResult(
                        code=CODE_TYPE_INVALID_FORMAT, log=str(e)))
                    continue
                updates.append(upd)
                results.append(ExecTxResult(data=tx))
            elif b"=" in tx:
                k, v = tx.split(b"=", 1)
                state[k.decode(errors="replace")] = v.decode(errors="replace")
                results.append(ExecTxResult(data=tx))
            else:
                results.append(ExecTxResult(code=CODE_TYPE_INVALID_FORMAT,
                                            log="tx must be key=value"))
        app_hash = self._compute_app_hash(state, req.height)
        self.staged = state
        self.last_height = req.height
        self.last_app_hash = app_hash
        self.pending_updates = updates
        return ResponseFinalizeBlock(tx_results=results,
                                     validator_updates=updates,
                                     app_hash=app_hash)

    @property
    def prev_state(self) -> dict | None:
        return self._prev[0] if self._prev else None

    @property
    def prev_height(self) -> int:
        return self._prev[1] if self._prev else 0

    def commit(self) -> ResponseCommit:
        if self.staged is not None:
            self._prev = (self.state, self.last_height - 1)
            self.state = self.staged
            self.staged = None
            if self.last_height % self.SNAPSHOT_INTERVAL == 0:
                # capture an interval snapshot (reference kvstore.go
                # snapshot_interval): advertising the live tip instead
                # would race the restorer's light anchor — header H+1
                # doesn't exist yet when the snapshot IS the tip, and
                # re-discovery would chase the tip forever.
                # Copy-on-write + single assignment: the snapshot
                # connection reads this dict from another thread (same
                # no-tear discipline as _prev above)
                blobs = dict(self._snapshot_blobs)
                blobs[self.last_height] = self._snapshot_blob()
                for h in sorted(blobs)[:-self.SNAPSHOT_KEEP]:
                    del blobs[h]
                self._snapshot_blobs = blobs
        return ResponseCommit(retain_height=0)

    def query(self, path: str, data: bytes) -> tuple[int, bytes]:
        if path == "/store" or path == "":
            v = self.state.get(data.decode(errors="replace"))
            return CODE_TYPE_OK, (v.encode() if v is not None else b"")
        return 1, b"unknown path"

    def query_prove(self, path: str, data: bytes
                    ) -> Tuple[int, bytes, int, Optional[merkle.Proof]]:
        """(code, value, height, inclusion proof) answered from the
        previous committed snapshot, whose app hash is already inside a
        stored header — the proof verifies against
        header(height+1).app_hash (the reference's light/rpc client
        checks query proofs at exactly that offset)."""
        # snapshot once: commit() on the consensus thread swaps the
        # snapshot concurrently with RPC-thread queries
        prev = self._prev
        prev_state, prev_height = prev if prev else (None, 0)
        if prev_state is None or path not in ("/store", ""):
            code, value = self.query(path, data)
            return code, value, self.last_height, None
        key = data.decode(errors="replace")
        v = prev_state.get(key)
        if v is None or key.encode() != data:
            # second clause: a lossily-decoded (invalid UTF-8) query can
            # alias a stored key; byte-level bracketing below still
            # proves `data` itself is absent from the leaf set
            return (CODE_TYPE_OK, b"", prev_height,
                    self._absence_proof(prev_state, prev_height, data))
        value = v.encode()
        leaves = self._state_leaves(prev_state, prev_height)
        idx = leaves.index(self.kv_leaf(data, value))
        _root, proofs = merkle.proofs_from_byte_slices(leaves)
        return CODE_TYPE_OK, value, prev_height, proofs[idx]

    @classmethod
    def _absence_proof(cls, state: dict, height: int, data: bytes
                       ) -> merkle.AbsenceProof:
        """Prove `data` is NOT a key: inclusion of the two adjacent
        leaves bracketing its sorted position. The height leaf at index
        0 is the left sentinel (every kv key sorts after it); a missing
        right neighbor is provable because Proof.total pins the tree
        size. UTF-8 preserves code-point order, so the str sort of
        `_state_leaves` and the byte-level bisect here agree."""
        import bisect
        ekeys = [k.encode() for k in sorted(state)]
        pos = bisect.bisect_left(ekeys, data)  # count of keys < data
        leaves = cls._state_leaves(state, height)
        _root, proofs = merkle.proofs_from_byte_slices(leaves)
        li = pos               # kv leaf j sits at tree index j+1
        ri = pos + 1 if pos < len(ekeys) else None
        return merkle.AbsenceProof(
            proofs[li], leaves[li],
            proofs[ri] if ri is not None else None,
            leaves[ri] if ri is not None else None)

    @staticmethod
    def parse_kv_leaf(leaf: bytes) -> Optional[Tuple[bytes, bytes]]:
        """(key, value) from a kv_leaf, or None if not one (e.g. the
        height sentinel leaf). Inverse of `kv_leaf` — used by verifying
        clients to check absence-proof neighbors bracket the query."""
        if len(leaf) < 5 or leaf[0] != 0x01:
            return None
        klen = int.from_bytes(leaf[1:5], "big")
        if len(leaf) < 5 + klen:
            return None
        return leaf[5:5 + klen], leaf[5 + klen:]

    # --- statesync snapshots (reference kvstore.go snapshot support) ---------

    SNAPSHOT_CHUNK_SIZE = 1 << 16
    SNAPSHOT_INTERVAL = 5   # capture every N commits (kvstore.go analog)
    SNAPSHOT_KEEP = 2       # retain the most recent K interval snapshots

    def _snapshot_blob(self) -> bytes:
        return json.dumps({"state": {k: self.state[k]
                                     for k in sorted(self.state)},
                           "height": self.last_height},
                          separators=(",", ":")).encode()

    def list_snapshots(self) -> List[Snapshot]:
        """The retained interval snapshots, blobs captured at commit
        time — chunks must stay byte-stable while later blocks commit,
        or the restorer's hash check fails. An app that has not crossed
        an interval yet serves nothing (the reference behaves the same
        before its first interval); writing a fallback capture HERE
        would mutate the dict from the snapshot-connection thread and
        re-introduce the advertise-the-live-tip anchor race commit()
        exists to avoid."""
        if self.last_height == 0:
            return []
        blobs = self._snapshot_blobs  # atomic ref: see commit()
        out = []
        for h in sorted(blobs, reverse=True):
            blob = blobs[h]
            n = max(1, (len(blob) + self.SNAPSHOT_CHUNK_SIZE - 1)
                    // self.SNAPSHOT_CHUNK_SIZE)
            out.append(Snapshot(height=h, format=1, chunks=n,
                                hash=hashlib.sha256(blob).digest()))
        return out

    def load_snapshot_chunk(self, height: int, format_: int,
                            chunk: int) -> bytes:
        blob = self._snapshot_blobs.get(height)
        if blob is None:
            return b""  # unknown snapshot: restorer will RETRY elsewhere
        lo = chunk * self.SNAPSHOT_CHUNK_SIZE
        return blob[lo:lo + self.SNAPSHOT_CHUNK_SIZE]

    def offer_snapshot(self, snapshot: Snapshot, app_hash: bytes) -> str:
        if snapshot.format != 1 or snapshot.chunks < 1:
            return "REJECT_FORMAT"
        self._restore = {"snapshot": snapshot, "chunks": [],
                         "app_hash": app_hash}
        return "ACCEPT"

    def apply_snapshot_chunk(self, index: int, chunk: bytes,
                             sender: str) -> str:
        r = getattr(self, "_restore", None)
        if r is None:
            return "ABORT"
        # position by index: sources may re-deliver or reorder chunks
        # (the reference chunk queue slots by index the same way)
        if index < len(r["chunks"]):
            return "ACCEPT"  # duplicate: already have it
        if index > len(r["chunks"]):
            return "RETRY_SNAPSHOT"  # gap: restart this snapshot
        r["chunks"].append(chunk)
        if len(r["chunks"]) < r["snapshot"].chunks:
            return "ACCEPT"
        blob = b"".join(r["chunks"])
        if hashlib.sha256(blob).digest() != r["snapshot"].hash:
            self._restore = None
            return "RETRY_SNAPSHOT"
        d = json.loads(blob)
        state, height = d["state"], d["height"]
        if self._compute_app_hash(state, height) != r["app_hash"]:
            # light-client-verified app hash disagrees: poisoned snapshot
            self._restore = None
            return "REJECT_SNAPSHOT"
        self.state = state
        self.last_height = height
        self.last_app_hash = r["app_hash"]
        self._prev = None  # pre-restore snapshot no longer provable
        self._restore = None
        return "COMPLETE"
