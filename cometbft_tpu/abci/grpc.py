"""ABCI over gRPC: server hosting an Application and the matching
client (reference abci/server/grpc_server.go, abci/client/grpc_client.go,
api/cometbft/abci/v1/service.pb.go ABCIService).

Surface parity is by fully-qualified method name — the service is
`cometbft.abci.v1.ABCIService` with the reference's sixteen unary
methods. Message bodies reuse the transport-independent JSON codec
shared with the socket flavor (abci/socket.py `dispatch_request` /
`AppClientCodec`): both of this framework's transports are in-tree, so
the codec is node-local by design, exactly as the socket flavor
documents. The two Query shapes (plain / with proof) multiplex on a
`prove` flag in the body, mirroring the reference's
QueryRequest.prove field.
"""

from __future__ import annotations

import json
import threading
from concurrent import futures

import grpc

from .application import Application
from .socket import (AppClientCodec, dispatch_request,
                     _M_ECHO, _M_FLUSH, _M_INFO, _M_CHECK_TX, _M_PREPARE,
                     _M_PROCESS, _M_FINALIZE, _M_COMMIT, _M_QUERY,
                     _M_INIT_CHAIN, _M_QUERY_PROVE, _M_LIST_SNAPSHOTS,
                     _M_LOAD_SNAPSHOT_CHUNK, _M_OFFER_SNAPSHOT,
                     _M_APPLY_SNAPSHOT_CHUNK, _M_EXTEND_VOTE,
                     _M_VERIFY_VOTE_EXT)

SERVICE_NAME = "cometbft.abci.v1.ABCIService"

# reference service.pb.go ABCIServiceServer method set. _M_QUERY_PROVE
# shares the "Query" RPC (the body's `prove` flag picks the app call,
# like QueryRequest.prove).
_METHOD_IDS = {
    "Echo": _M_ECHO,
    "Flush": _M_FLUSH,
    "Info": _M_INFO,
    "CheckTx": _M_CHECK_TX,
    "Query": _M_QUERY,
    "Commit": _M_COMMIT,
    "InitChain": _M_INIT_CHAIN,
    "ListSnapshots": _M_LIST_SNAPSHOTS,
    "OfferSnapshot": _M_OFFER_SNAPSHOT,
    "LoadSnapshotChunk": _M_LOAD_SNAPSHOT_CHUNK,
    "ApplySnapshotChunk": _M_APPLY_SNAPSHOT_CHUNK,
    "PrepareProposal": _M_PREPARE,
    "ProcessProposal": _M_PROCESS,
    "ExtendVote": _M_EXTEND_VOTE,
    "VerifyVoteExtension": _M_VERIFY_VOTE_EXT,
    "FinalizeBlock": _M_FINALIZE,
}
_GRPC_NAMES = {mid: name for name, mid in _METHOD_IDS.items()}
_GRPC_NAMES[_M_QUERY_PROVE] = "Query"


def _ser(body: dict) -> bytes:
    return json.dumps(body).encode()


def _de(raw: bytes) -> dict:
    return json.loads(raw or b"{}")


class GRPCServer:
    """Hosts an Application for remote consensus engines over gRPC
    (reference abci/server/grpc_server.go GRPCServer)."""

    def __init__(self, app: Application, host: str = "127.0.0.1",
                 port: int = 0, max_workers: int = 8):
        self.app = app
        # gRPC handlers run concurrently; the app contract is a
        # serialized request stream (same global ordering the socket
        # server enforces across its 4 named connections)
        self._app_lock = threading.Lock()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers,
                thread_name_prefix="abci-grpc"))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(
                SERVICE_NAME,
                {name: grpc.unary_unary_rpc_method_handler(
                    self._make_handler(name),
                    request_deserializer=_de, response_serializer=_ser)
                 for name in _METHOD_IDS}),))
        bound = self._server.add_insecure_port(f"{host}:{port}")
        if bound == 0:
            raise OSError(
                f"ABCI gRPC server could not bind {host}:{port}")
        self.addr = (host, bound)

    def _make_handler(self, name: str):
        method = _METHOD_IDS[name]

        def handle(body: dict, context):
            mid = method
            if name == "Query" and body.pop("prove", False):
                mid = _M_QUERY_PROVE
            try:
                with self._app_lock:
                    return dispatch_request(self.app, mid, body)
            except Exception as e:  # noqa: BLE001 — surface app errors
                # as gRPC status instead of a dropped stream
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")
        return handle

    def start(self) -> None:
        self._server.start()

    def stop(self) -> None:
        self._server.stop(grace=0.5)


class GRPCClient(AppClientCodec):
    """Application-shaped proxy over a gRPC channel (reference
    abci/client/grpc_client.go)."""

    def __init__(self, host: str, port: int,
                 connect_retry_s: float = 30.0):
        self._channel = grpc.insecure_channel(f"{host}:{port}")
        # the reference gRPC client dials with retry too: under a
        # process supervisor the app routinely comes up after the node
        try:
            grpc.channel_ready_future(self._channel).result(
                timeout=connect_retry_s)
        except grpc.FutureTimeoutError:
            self._channel.close()
            raise ConnectionError(
                f"ABCI gRPC app at {host}:{port} not reachable "
                f"within {connect_retry_s}s")
        self._stubs = {
            name: self._channel.unary_unary(
                f"/{SERVICE_NAME}/{name}",
                request_serializer=_ser, response_deserializer=_de)
            for name in _METHOD_IDS}

    def _call(self, method: int, body: dict) -> dict:
        name = _GRPC_NAMES[method]
        if method == _M_QUERY_PROVE:
            body = dict(body, prove=True)
        try:
            return self._stubs[name](body)
        except grpc.RpcError as e:
            raise ConnectionError(
                f"ABCI gRPC {name}: {e.code().name}: {e.details()}")

    def close(self) -> None:
        self._channel.close()


def serve_app(app: Application, host: str = "127.0.0.1",
              port: int = 0) -> GRPCServer:
    """Convenience used by `cmd abci-cli`-style tooling and tests."""
    srv = GRPCServer(app, host, port)
    srv.start()
    return srv
