"""ABCI conformance grammar: validates that the sequence of ABCI calls
a node made against its application follows the protocol's legal order
(reference test/e2e/pkg/grammar/checker.go, whose gogll grammar encodes
the ABCI spec's connection-interleaving rules; this is a hand-rolled
recursive-descent over the same shape).

Grammar (clean-start and crash-recovery forms):

    start            := clean_start | recovery
    clean_start      := init_chain state_sync? consensus_exec
    state_sync       := attempt* success_sync
    attempt          := offer_snapshot apply_snapshot_chunk*
    success_sync     := offer_snapshot apply_snapshot_chunk+
    recovery         := consensus_exec
    consensus_exec   := consensus_height+
    consensus_height := round* finalize_block commit
    round            := proposer | non_proposer
    proposer         := prepare_proposal process_proposal?
    non_proposer     := process_proposal

Query/mempool-connection calls (info, query, check_tx, echo) run on
their own connections with no ordering contract against consensus
(reference proxy/multi_app_conn.go isolates them), so the recorder
drops them before checking.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

CONSENSUS_CALLS = frozenset({
    "init_chain", "offer_snapshot", "apply_snapshot_chunk",
    "prepare_proposal", "process_proposal", "finalize_block", "commit",
    "extend_vote", "verify_vote_extension",
})


class GrammarError(Exception):
    def __init__(self, pos: int, got: str, expected: str):
        self.pos, self.got, self.expected = pos, got, expected
        super().__init__(
            f"ABCI call #{pos} {got!r}: expected {expected}")


class _Parser:
    def __init__(self, calls: List[str]):
        # extend/verify vote ride inside a height's rounds at times the
        # vote schedule (not the ABCI contract) decides — strip like the
        # reference's checker filters non-grammar calls
        self.calls = [c for c in calls
                      if c not in ("extend_vote", "verify_vote_extension")]
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.calls[self.i] if self.i < len(self.calls) else None

    def eat(self, name: str, expected: str) -> None:
        got = self.peek()
        if got != name:
            raise GrammarError(self.i, got or "<end>", expected)
        self.i += 1

    # --- productions ---------------------------------------------------------

    def start(self, clean_start: bool) -> None:
        if clean_start:
            self.eat("init_chain", "init_chain (clean start)")
            if self.peek() == "offer_snapshot":
                self.state_sync()
        self.consensus_exec()
        if self.i != len(self.calls):
            raise GrammarError(self.i, self.calls[self.i],
                               "<end of execution>")

    def state_sync(self) -> None:
        # attempts may abort mid-chunks; only the LAST attempt must
        # complete with >=1 chunk (the success-sync). Greedy: consume
        # every offer+chunks group, remember whether the final group
        # applied anything.
        last_had_chunks = False
        while self.peek() == "offer_snapshot":
            self.i += 1
            last_had_chunks = False
            while self.peek() == "apply_snapshot_chunk":
                self.i += 1
                last_had_chunks = True
        if not last_had_chunks:
            raise GrammarError(self.i, self.peek() or "<end>",
                               "apply_snapshot_chunk completing the "
                               "final snapshot attempt")

    def consensus_exec(self) -> None:
        self.consensus_height()
        while self.peek() is not None:
            self.consensus_height()

    def consensus_height(self) -> None:
        while self.peek() in ("prepare_proposal", "process_proposal"):
            if self.peek() == "prepare_proposal":
                self.i += 1
                if self.peek() == "process_proposal":
                    self.i += 1
            else:
                self.i += 1
        self.eat("finalize_block", "finalize_block to decide the height")
        self.eat("commit", "commit after finalize_block")


def check_sequence(calls: List[str], clean_start: bool = True
                   ) -> Tuple[bool, Optional[GrammarError]]:
    """Validate a recorded consensus-connection call sequence."""
    try:
        _Parser(list(calls)).start(clean_start)
        return True, None
    except GrammarError as e:
        return False, e


class RecordingApp:
    """Application wrapper logging every consensus-connection call
    (reference test/e2e/app records requests the same way for the
    grammar checker)."""

    def __init__(self, app):
        self._app = app
        self.calls: List[str] = []
        self._lock = threading.Lock()

    def __getattr__(self, name):
        target = getattr(self._app, name)
        if not callable(target) or name not in CONSENSUS_CALLS:
            return target

        def wrapped(*args, **kwargs):
            with self._lock:
                self.calls.append(name)
            return target(*args, **kwargs)
        return wrapped

    def check(self, clean_start: bool = True
              ) -> Tuple[bool, Optional[GrammarError]]:
        with self._lock:
            calls = list(self.calls)
        return check_sequence(calls, clean_start)
