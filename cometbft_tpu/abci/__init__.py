from .application import (  # noqa: F401
    Application, BaseApplication, RequestFinalizeBlock, ResponseFinalizeBlock,
    ExecTxResult, ValidatorUpdate, CheckTxResult, ResponseInfo,
    ResponseCommit, CODE_TYPE_OK,
)
from .kvstore import KVStoreApplication  # noqa: F401
