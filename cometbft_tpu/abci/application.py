"""ABCI: the application interface (reference
abci/types/application.go:9-35 — the 14-method surface — and the request/
response payloads the engine actually consumes).

In-process applications implement `Application`; remote apps connect via
the socket server (abci/server.py). `BaseApplication` provides no-op
defaults exactly like the reference's BaseApplication (:42).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import List, Optional, Protocol

from ..types.proto import Timestamp

CODE_TYPE_OK = 0


@dataclass
class ValidatorUpdate:
    """reference abci/types.pb PubKeyBytes+Power update. A BLS key
    admitted mid-chain must carry its proof of possession in `pop`
    (aggregation is unsound against rogue-key choices without one —
    aggsig/aggregate.py); ed25519 updates leave it empty."""
    pub_key_type: str
    pub_key_bytes: bytes
    power: int
    pop: bytes = b""


@dataclass
class ExecTxResult:
    code: int = CODE_TYPE_OK
    data: bytes = b""
    log: str = ""
    gas_wanted: int = 0
    gas_used: int = 0

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        """Deterministic encoding for last_results_hash: the reference
        strips everything EXCEPT code, data, gas_wanted and gas_used
        (abci/types/types.go:201-208 DeterministicExecTxResult; proto
        fields 1, 2, 5, 6 of ExecTxResult) before merkle-hashing
        (types/results.go NewResults/Hash)."""
        from ..types import proto
        return (proto.f_varint(1, self.code)
                + proto.f_bytes(2, self.data)
                + proto.f_varint(5, self.gas_wanted)
                + proto.f_varint(6, self.gas_used))


@dataclass
class CheckTxResult:
    code: int = CODE_TYPE_OK
    log: str = ""
    gas_wanted: int = 0


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class RequestFinalizeBlock:
    txs: List[bytes]
    height: int
    time: Timestamp
    proposer_address: bytes
    hash: bytes = b""
    next_validators_hash: bytes = b""
    decided_last_commit_votes: List[tuple] = dc_field(default_factory=list)


@dataclass
class ResponseFinalizeBlock:
    tx_results: List[ExecTxResult] = dc_field(default_factory=list)
    validator_updates: List[ValidatorUpdate] = dc_field(default_factory=list)
    app_hash: bytes = b""
    consensus_param_updates: Optional[dict] = None

    def encode(self) -> bytes:
        """Node-local persistence form (reference
        state/store.go SaveFinalizeBlockResponse — stored per height so
        crash recovery / handshake replay can reconstruct results)."""
        import json
        return json.dumps({
            "tx_results": [{"code": r.code, "data": r.data.hex(),
                            "log": r.log, "gas_wanted": r.gas_wanted,
                            "gas_used": r.gas_used}
                           for r in self.tx_results],
            "validator_updates": [
                {"type": u.pub_key_type, "pub_key": u.pub_key_bytes.hex(),
                 "power": u.power, "pop": u.pop.hex()}
                for u in self.validator_updates],
            "app_hash": self.app_hash.hex(),
        }).encode()

    @classmethod
    def decode(cls, raw: bytes) -> "ResponseFinalizeBlock":
        import json
        d = json.loads(raw)
        return cls(
            tx_results=[ExecTxResult(r["code"], bytes.fromhex(r["data"]),
                                     r["log"], r["gas_wanted"], r["gas_used"])
                        for r in d["tx_results"]],
            validator_updates=[
                ValidatorUpdate(u["type"], bytes.fromhex(u["pub_key"]),
                                u["power"],
                                bytes.fromhex(u.get("pop", "")))
                for u in d["validator_updates"]],
            app_hash=bytes.fromhex(d["app_hash"]))


@dataclass
class ResponseCommit:
    retain_height: int = 0


@dataclass
class Snapshot:
    """reference abci Snapshot message (statesync.proto): an app-level
    checkpoint advertised to catching-up peers."""
    height: int
    format: int
    chunks: int
    hash: bytes
    metadata: bytes = b""


class Application(Protocol):
    """reference abci/types/application.go:9-35."""

    # info/query connection
    def info(self) -> ResponseInfo: ...
    def query(self, path: str, data: bytes) -> tuple[int, bytes]: ...
    def query_prove(self, path: str, data: bytes
                    ) -> tuple[int, bytes, int, object]: ...

    # mempool connection
    def check_tx(self, tx: bytes) -> CheckTxResult: ...

    # consensus connection
    def init_chain(self, chain_id: str, initial_height: int,
                   validators: List[ValidatorUpdate],
                   app_state_bytes: bytes) -> tuple[List[ValidatorUpdate],
                                                    bytes]: ...
    def prepare_proposal(self, txs: List[bytes], max_tx_bytes: int,
                         local_last_commit=None) -> List[bytes]:
        """local_last_commit: [(validator_index, address, extension)]
        from the previous height's extended commit when vote extensions
        are enabled (reference abci RequestPrepareProposal
        .local_last_commit.votes[].vote_extension), else None."""
    def process_proposal(self, txs: List[bytes], height: int) -> bool: ...
    def finalize_block(self, req: RequestFinalizeBlock
                       ) -> ResponseFinalizeBlock: ...
    def commit(self) -> ResponseCommit: ...

    # vote extensions
    def extend_vote(self, height: int, round_: int) -> bytes: ...
    def verify_vote_extension(self, height: int, addr: bytes,
                              ext: bytes) -> bool: ...

    # snapshot connection
    def list_snapshots(self) -> list: ...
    def offer_snapshot(self, snapshot, app_hash: bytes) -> str: ...
    def load_snapshot_chunk(self, height: int, format_: int,
                            chunk: int) -> bytes: ...
    def apply_snapshot_chunk(self, index: int, chunk: bytes,
                             sender: str) -> str: ...


class BaseApplication:
    """No-op defaults (reference abci/types/application.go:42-108)."""

    def info(self) -> ResponseInfo:
        return ResponseInfo()

    def query(self, path: str, data: bytes) -> tuple[int, bytes]:
        return CODE_TYPE_OK, b""

    def query_prove(self, path: str, data: bytes
                    ) -> tuple[int, bytes, int, object]:
        """(code, value, height, proof-or-None); apps without provable
        state answer proofless (verifying clients then reject them)."""
        code, value = self.query(path, data)
        return code, value, self.info().last_block_height, None

    def check_tx(self, tx: bytes) -> CheckTxResult:
        return CheckTxResult()

    def init_chain(self, chain_id, initial_height, validators,
                   app_state_bytes):
        return [], b""

    def prepare_proposal(self, txs, max_tx_bytes,
                         local_last_commit=None):
        out, total = [], 0
        for tx in txs:
            total += len(tx)
            if max_tx_bytes >= 0 and total > max_tx_bytes:
                break
            out.append(tx)
        return out

    def process_proposal(self, txs, height) -> bool:
        return True

    def finalize_block(self, req: RequestFinalizeBlock
                       ) -> ResponseFinalizeBlock:
        return ResponseFinalizeBlock(
            tx_results=[ExecTxResult() for _ in req.txs])

    def commit(self) -> ResponseCommit:
        return ResponseCommit()

    def extend_vote(self, height, round_) -> bytes:
        return b""

    def verify_vote_extension(self, height, addr, ext) -> bool:
        return True

    def list_snapshots(self):
        return []

    def offer_snapshot(self, snapshot, app_hash) -> str:
        return "ABORT"

    def load_snapshot_chunk(self, height, format_, chunk) -> bytes:
        return b""

    def apply_snapshot_chunk(self, index, chunk, sender) -> str:
        return "ABORT"
